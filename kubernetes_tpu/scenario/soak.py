"""Day-in-the-life soak: play a trace tape against the full control plane.

One compressed "day" (a :class:`~kubernetes_tpu.scenario.traces.Tape`)
runs against the whole stack at once — scheduler + cluster-autoscaler +
descheduler + monitor over hollow kubelets — with every verb routed
through a seeded FaultPlane and audited by the RaceDetector +
LoopStallWatchdog. Where every other bench config is a synthetic burst,
this is sustained mixed churn: diurnal arrivals, gangs, priorities,
deletes, node flaps/drains/adds, watch expiry — the
``test/integration/scheduler_perf`` successor ROADMAP item 5 calls for.

The result is a :class:`SoakResult` whose ``violations`` list is the
gate surface (`bench[soak]` fails on any entry) and whose ``pressure``
float is a graded how-close-to-breaking signal for the adversarial
scenario search (search.py): 0..1 approaches the gates, >1 means at
least one is breached.

Memory ceilings are first-class: the driver samples RSS, live WAL
records (compaction must hold under churn), monitor TSDB series, the
scheduler's jit-variant cache, and watch-history occupancy into gauges —
all must be flat after warmup.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import time
from dataclasses import dataclass, field

from kubernetes_tpu.obs import REGISTRY
from kubernetes_tpu.scenario.traces import (
    BROWNOUT,
    DELETE,
    NODE_ADD,
    NODE_DRAIN,
    NODE_FLAP,
    SUBMIT,
    SUBMIT_GANG,
    WATCH_EXPIRE,
    WATCHER_DROP,
    Event,
    Tape,
    TraceConfig,
    make_tape,
)

_EVENTS = REGISTRY.counter(
    "scenario_events_applied_total",
    "Trace-tape events applied by the soak driver", labels=("kind",))
_RSS = REGISTRY.gauge(
    "soak_rss_bytes", "Driver-process resident set during the soak")
_WAL = REGISTRY.gauge(
    "soak_wal_records", "Live WAL records (post-compaction) during the soak")
_SERIES = REGISTRY.gauge(
    "soak_tsdb_series", "Embedded-monitor TSDB series during the soak")
_JIT = REGISTRY.gauge(
    "soak_jit_cache_variants", "Scheduler jit-cache variants during the soak")
_WATCHN = REGISTRY.gauge(
    "soak_watch_history_events", "Watch-history window occupancy during "
    "the soak")


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


@dataclass
class SoakResult:
    nodes: int
    ticks: int
    seed: int
    pods_submitted: int
    bound: int
    double_binds: int
    racy_writes: int
    loop_stalls: int
    max_stall_ms: float
    p50_ms: float
    p99_ms: float
    converged: bool
    pending_at_end: int
    faults_injected: int
    node_flaps: int
    drains: int
    adds: int
    orphans_gced: int
    scaleups: int
    desched_moves: int
    rss_warm_bytes: int
    rss_peak_bytes: int
    rss_growth_frac: float
    wal_records: int
    compactions: int
    tsdb_series: int
    jit_variants: int
    watch_history: int
    events_applied: int
    seconds: float
    event_errors: list = field(default_factory=list)
    violations: list = field(default_factory=list)
    pressure: float = 0.0

    def __str__(self) -> str:
        verdict = "clean" if not self.violations \
            else "; ".join(self.violations)
        return (f"soak N={self.nodes} T={self.ticks} seed={self.seed}: "
                f"{self.bound}/{self.pods_submitted} bound, "
                f"p99 {self.p99_ms:.0f}ms, rss +"
                f"{100 * self.rss_growth_frac:.0f}% after warmup, "
                f"{self.compactions} WAL compactions "
                f"({self.wal_records} live records), "
                f"{self.scaleups} scaleups, {self.desched_moves} moves, "
                f"{self.node_flaps} flaps — {verdict}")


async def _run_soak(tape: Tape, *, tick_seconds: float,
                    snapshot_every: int, p99_bound_ms: float,
                    rss_slack_frac: float, warmup_frac: float,
                    error_rate: float, race_detect: bool,
                    heartbeat_every: float, resync_every: float,
                    autoscaler_every: int, descheduler_every: int,
                    scrape_every: int, wal_path: str,
                    converge_timeout_s: float) -> SoakResult:
    from kubernetes_tpu.agent.hollow import HollowCluster, HollowKubelet
    from kubernetes_tpu.api.objects import Node, Pod
    from kubernetes_tpu.apiserver import ObjectStore
    from kubernetes_tpu.apiserver.store import (
        Conflict,
        NotFound,
        TooManyRequests,
    )
    from kubernetes_tpu.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.cloudprovider import FakeCloud
    from kubernetes_tpu.descheduler import Descheduler
    from kubernetes_tpu.gang import (
        GROUP_MIN_ANNOTATION,
        GROUP_NAME_ANNOTATION,
    )
    from kubernetes_tpu.obs.monitor import Monitor
    from kubernetes_tpu.perf.harness import (
        freeze_drill_heap,
        thaw_drill_heap,
    )
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.state import Capacities
    from kubernetes_tpu.testing.faults import FaultPlane
    from kubernetes_tpu.testing.races import LoopStallWatchdog, RaceDetector

    cfg = tape.config
    counts = tape.counts()
    freeze_drill_heap()

    cap = {"cpu": cfg.node_cpu, "memory": cfg.node_memory, "pods": "110"}
    total_pods = tape.pods_submitted()
    inner = ObjectStore(
        watch_window=max(1 << 15, 8 * (total_pods + cfg.nodes)),
        persist_path=wal_path, snapshot_every=snapshot_every)
    # initial fleet pre-registered through the inner store (setup is not
    # the thing under test; the kubelets' register() finds the Nodes)
    for i in range(cfg.nodes):
        name = f"soak-{i:05d}"
        inner.create(Node.from_dict({
            "metadata": {"name": name,
                         "labels": {"kubernetes.io/hostname": name}},
            "status": {"allocatable": dict(cap), "capacity": dict(cap)}}))

    plane = FaultPlane(inner, seed=cfg.seed, error_rate=error_rate)
    store = RaceDetector(plane) if race_detect else plane
    # the stall watchdog is armed only after warmup (first-batch jit
    # compiles are real but not a day-in-the-life pathology) and paused
    # around the synchronous probe-solve scans the driver itself steps —
    # same spirit as freeze_drill_heap: measure the control plane's own
    # loop holds, not known-blocking windows the drill schedules
    watchdog: LoopStallWatchdog | None = None
    stalls: list[float] = []

    def pause_watchdog() -> None:
        nonlocal watchdog
        if watchdog is not None:
            stalls.extend(watchdog.stop())
            watchdog = None

    def resume_watchdog() -> None:
        nonlocal watchdog
        if race_detect and watchdog is None:
            watchdog = LoopStallWatchdog().start()

    cluster = HollowCluster(store, n_nodes=0, heartbeat_every=heartbeat_every,
                            capacity=cap, resync_every=resync_every)

    def register_kubelet(name: str) -> HollowKubelet:
        kubelet = HollowKubelet(store, name,
                                heartbeat_every=heartbeat_every,
                                capacity=cap)
        cluster.add(kubelet)
        plane.attach_kubelet(name, kubelet)
        return kubelet

    for i in range(cfg.nodes):
        register_kubelet(f"soak-{i:05d}")
    await cluster.start()

    async def adopt(name: str) -> None:
        # a Node appeared that no agent owns (autoscaler scale-up or a
        # trace node-add): give it a kubelet so its pods go Running.
        # Registration runs through the audited, fault-injecting store —
        # a real kubelet retries transient apiserver errors, so adoption
        # does too (the fault sequence is op-count based: the retry's
        # ops draw fresh positions)
        for attempt in range(3):
            kubelet = register_kubelet(name)
            try:
                await kubelet.start()
                return
            except (TooManyRequests, Conflict):
                cluster.kubelets.pop(name, None)
                plane.kubelets.pop(name, None)
                kubelet.stop()
                if attempt == 2:
                    raise
                await asyncio.sleep(0.01)

    max_nodes = cfg.nodes + counts.get(NODE_ADD, 0) + cfg.autoscale_max
    widest = max((e.width for e in tape.events if e.kind == SUBMIT_GANG),
                 default=1)
    caps = Capacities(
        num_nodes=1 << max(6, (max_nodes - 1).bit_length()),
        batch_pods=min(2048, max(64, 2 * widest, total_pods // 8)))
    loop = asyncio.get_running_loop()
    sched = Scheduler(store, caps=caps)
    driver = loop.create_task(sched.run())

    cloud = FakeCloud()
    cloud.add_node_group("soak-pool", 0, cfg.autoscale_max,
                         cpu=cfg.node_cpu, memory=cfg.node_memory,
                         pods="110")
    # the autoscaler's own loop is parked (scan_interval huge) and the
    # driver steps run_once() at fixed ticks instead: its probe solves
    # block the loop by design, so stepping them inside watchdog-paused
    # windows keeps the stall gate about everything else — and stepping
    # is deterministic in tape time, which replay wants anyway
    autoscaler = ClusterAutoscaler(
        store, cloud, caps=caps, scan_interval=3600.0,
        scaleup_cooldown=0.0,
        # the day is compressed: real-time scale-down idle windows never
        # elapse, so park scale-down and let drains do the shrinking
        scaledown_cooldown=3600.0, unneeded_time=3600.0)
    await autoscaler.start()

    desched = Descheduler(store, caps=caps, scan_interval=3600.0,
                          max_moves=2, cooldown=0.0, rollback_after=30.0)
    await desched.start()

    monitor = Monitor(store=None, interval=3600.0, alert_for_s=0.0)
    monitor.add_local_target("scheduler",
                             lambda: sched.metrics.registry.render())

    def _pod_dict(name: str, ev, gang: str | None = None) -> dict:
        meta: dict = {"name": name}
        if gang:
            meta["annotations"] = {GROUP_NAME_ANNOTATION: gang,
                                   GROUP_MIN_ANNOTATION: str(ev.width)}
        return {"metadata": meta,
                "spec": {"priority": ev.priority,
                         "containers": [{"name": "app", "resources": {
                             "requests": {"cpu": f"{ev.cpu_m}m",
                                          "memory": f"{ev.mem_mi}Mi"}}}]}}

    recover_at: dict[int, list[str]] = {}
    event_errors: list[str] = []
    applied = 0

    async def apply(ev, t: int) -> None:
        if ev.kind == SUBMIT:
            inner.create(Pod.from_dict(_pod_dict(ev.name, ev)))
        elif ev.kind == SUBMIT_GANG:
            for k in range(ev.width):
                inner.create(Pod.from_dict(
                    _pod_dict(f"{ev.name}-{k}", ev, gang=ev.name)))
        elif ev.kind == DELETE:
            names = [ev.name] if ev.width <= 1 \
                else [f"{ev.name}-{k}" for k in range(ev.width)]
            for nm in names:
                try:
                    inner.delete("Pod", nm, "default")
                except NotFound:
                    pass  # e.g. its node drained first
        elif ev.kind == NODE_FLAP:
            if ev.name in plane.kubelets:
                plane.flap_node(ev.name)
                recover_at.setdefault(t + max(1, ev.down), []) \
                    .append(ev.name)
        elif ev.kind == NODE_DRAIN:
            kubelet = cluster.kubelets.pop(ev.name, None)
            if kubelet is None:
                return  # already drained by an earlier event
            plane.kubelets.pop(ev.name, None)
            kubelet.stop()
            for p in list(inner.list("Pod", copy_objects=False)):
                if p.spec.node_name == ev.name:
                    try:
                        inner.delete("Pod", p.metadata.name,
                                     p.metadata.namespace)
                    except NotFound:
                        pass
            try:
                inner.delete("Node", ev.name, "default")
            except NotFound:
                pass
        elif ev.kind == NODE_ADD:
            if ev.name not in cluster.kubelets:
                await adopt(ev.name)
        elif ev.kind == WATCH_EXPIRE:
            plane.expire_watch_history()
        elif ev.kind == WATCHER_DROP:
            plane.drop_watchers()
        elif ev.kind == BROWNOUT:
            # the tape carries the whole ramp as explicit rows, so a
            # brownout needs no timer state here: set-and-forget, the
            # final row of the window restores the baseline
            plane.error_rate = ev.rate if ev.rate > 0 else error_rate

    by_tick: dict[int, list] = {}
    for ev in tape.events:
        by_tick.setdefault(ev.tick, []).append(ev)

    samples: list[dict] = []

    def sample(t: int) -> None:
        s = {"tick": t, "rss": _rss_bytes(), "wal": inner._wal_records,
             "series": monitor.tsdb.series_count(),
             "jit": len(sched._schedule_fns),
             "watch": len(inner._history)}
        samples.append(s)
        _RSS.labels().set(s["rss"])
        _WAL.labels().set(s["wal"])
        _SERIES.labels().set(s["series"])
        _JIT.labels().set(s["jit"])
        _WATCHN.labels().set(s["watch"])

    def unconverged() -> list:
        return [p for p in inner.list("Pod", copy_objects=False)
                if not (p.spec.node_name
                        and p.status.phase == "Running")]

    orphans_gced = 0

    def gc_orphans() -> None:
        # PodGC parity (pkg/controller/podgc): force-delete pods bound
        # to a Node object that no longer exists. A drain can race an
        # in-flight solve — the bind lands a beat after the drain swept
        # the node's pods, leaving a pod no kubelet will ever ack.
        nonlocal orphans_gced
        node_names = {nd.metadata.name
                      for nd in inner.list("Node", copy_objects=False)}
        for p in list(inner.list("Pod", copy_objects=False)):
            if p.spec.node_name and p.spec.node_name not in node_names:
                try:
                    inner.delete("Pod", p.metadata.name,
                                 p.metadata.namespace)
                    orphans_gced += 1
                except NotFound:
                    pass

    # phase 0 (unmeasured warmup): walk the whole bind path once per jit
    # variant the day can demand. Variant space here is BatchFlags'
    # {gang} x {preempt} (pod specs are otherwise uniform, so every other
    # gate is constant across batches) — submit each combination alone
    # and converge before the next, so each warmup batch is homogeneous
    # and compiles exactly its own variant before the watchdog arms and
    # the memory/latency baselines start. A variant first seen mid-day
    # would read as a ~100ms+ compile stall the control plane never
    # caused.
    warm_names: list[str] = []
    warm_units = [("soak-warm0", 1, 0), ("soak-warmp", 1, 1000),
                  ("soak-warmg", 2, 0), ("soak-warmgp", 2, 1000)]
    for base, width, prio in warm_units:
        if width == 1:
            ev = Event(0, SUBMIT, base, cpu_m=100, mem_mi=100,
                       priority=prio)
            inner.create(Pod.from_dict(_pod_dict(f"{base}-0", ev)))
            warm_names.append(f"{base}-0")
        else:
            ev = Event(0, SUBMIT_GANG, base, cpu_m=100, mem_mi=100,
                       width=width, priority=prio)
            for k in range(width):
                inner.create(Pod.from_dict(
                    _pod_dict(f"{base}-{k}", ev, gang=base)))
                warm_names.append(f"{base}-{k}")
        async with asyncio.timeout(converge_timeout_s):
            while unconverged():
                await asyncio.sleep(0.02)
    for nm in warm_names:
        try:
            inner.delete("Pod", nm, "default")
        except NotFound:
            pass
    for run_once in (autoscaler.run_once, desched.run_once):
        try:
            run_once()
        except Exception:
            pass  # injected store fault mid-scan: the next scan retries
    sched.metrics.e2e_latency.clear()
    # second freeze: warmup just allocated the jit artifacts and compile
    # garbage; a gen2 pass over them mid-day reads as a ~130ms stall the
    # control plane never caused
    freeze_drill_heap()
    resume_watchdog()

    def step_scan(run_once) -> None:
        # probe solves block the loop by design — pause the stall gate
        # for exactly this window (see the watchdog comment above). A
        # scan that trips an injected store fault is simply skipped: the
        # real controllers retry on their next loop iteration, so the
        # stepped equivalent is "this scan saw a flaky apiserver".
        pause_watchdog()
        try:
            run_once()
        except Exception:
            pass
        finally:
            resume_watchdog()

    def step_scans() -> None:
        step_scan(autoscaler.run_once)
        step_scan(desched.run_once)

    t_start = time.perf_counter()
    for t in range(cfg.ticks):
        for name in recover_at.pop(t, ()):
            if name in plane.kubelets:
                plane.recover_node(name)
        for ev in by_tick.get(t, ()):
            try:
                await apply(ev, t)
                applied += 1
                _EVENTS.labels(ev.kind).inc()
            except Exception as exc:  # a tape must never crash the driver
                event_errors.append(f"tick {t} {ev.kind} {ev.name}: "
                                    f"{exc!r}")
        if autoscaler_every and t % autoscaler_every == 0:
            step_scan(autoscaler.run_once)
        for node in inner.list("Node", copy_objects=False):
            if node.metadata.name not in cluster.kubelets:
                await adopt(node.metadata.name)
        if descheduler_every and t and t % descheduler_every == 0:
            step_scan(desched.run_once)
        gc_orphans()
        if scrape_every and t % scrape_every == 0:
            await monitor.scrape_once()
            sample(t)
        await asyncio.sleep(tick_seconds)

    # end of day: recover every still-flapped node, then the whole
    # cluster must converge — every live pod bound exactly once + Running
    for t in sorted(recover_at):
        for name in recover_at[t]:
            if name in plane.kubelets:
                plane.recover_node(name)

    converged = True
    try:
        async with asyncio.timeout(converge_timeout_s):
            waited = 0
            while unconverged():
                await asyncio.sleep(0.05)
                waited += 1
                if waited % 20 == 0:
                    step_scans()
                    gc_orphans()
                    for node in inner.list("Node", copy_objects=False):
                        if node.metadata.name not in cluster.kubelets:
                            await adopt(node.metadata.name)
    except TimeoutError:
        converged = False
    pending = unconverged()
    await monitor.scrape_once()
    sample(cfg.ticks)
    seconds = time.perf_counter() - t_start

    snap = sched.metrics.snapshot()
    driver.cancel()
    sched.stop()
    autoscaler.stop()
    desched.stop()
    cluster.stop()
    thaw_drill_heap()
    pause_watchdog()  # folds the final segment into `stalls`

    double = sum(1 for v in plane.bind_counts.values() if v > 1)
    racy = len(store.racy_writes) if race_detect else 0
    warm_n = max(1, int(len(samples) * warmup_frac))
    rss_warm = max((s["rss"] for s in samples[:warm_n]), default=0)
    rss_peak = max((s["rss"] for s in samples), default=0)
    growth = (rss_peak - rss_warm) / rss_warm if rss_warm else 0.0
    jit_warm, jit_end = samples[warm_n - 1]["jit"], samples[-1]["jit"]
    series_warm = samples[warm_n - 1]["series"]
    series_end = samples[-1]["series"]
    p50 = float(snap.get("e2e_p50_ms", 0.0))
    p99 = float(snap.get("e2e_p99_ms", 0.0))

    violations: list[str] = []
    if double:
        violations.append(f"{double} double-binds")
    if racy:
        violations.append(f"{racy} racy writes")
    if stalls:
        violations.append(f"{len(stalls)} loop stalls >100ms "
                          f"(max {1e3 * max(stalls):.0f}ms)")
    if event_errors:
        violations.append(f"{len(event_errors)} tape events failed "
                          f"(first: {event_errors[0]})")
    if not converged:
        stuck = ", ".join(sorted(
            f"{p.metadata.name}:{p.status.phase or '?'}"
            f"@{p.spec.node_name or 'unbound'}" for p in pending)[:5])
        violations.append(f"{len(pending)} pods unbound or not Running "
                          f"at end of day ({stuck})")
    if rss_warm and growth > rss_slack_frac:
        violations.append(
            f"rss ceiling: +{100 * growth:.0f}% after warmup "
            f"(slack {100 * rss_slack_frac:.0f}%)")
    if snapshot_every and inner._wal_records > snapshot_every:
        violations.append(f"wal unbounded: {inner._wal_records} live "
                          f"records > snapshot_every={snapshot_every}")
    if jit_end > jit_warm + 3:
        violations.append(f"jit cache grew after warmup: "
                          f"{jit_warm} -> {jit_end} variants")
    if series_end > max(series_warm + 8, int(series_warm * 1.25)):
        violations.append(f"tsdb series grew after warmup: "
                          f"{series_warm} -> {series_end}")
    if p99_bound_ms > 0 and p99 > p99_bound_ms:
        violations.append(f"scheduler e2e p99 {p99:.0f}ms > "
                          f"{p99_bound_ms:.0f}ms bound")

    # graded closeness-to-breaking for the scenario search: soft margins
    # below 1.0, then a step + count once gates actually break
    pressure = max(p99 / (p99_bound_ms if p99_bound_ms > 0 else 1e4),
                   (growth / rss_slack_frac) if rss_warm else 0.0)
    if violations:
        pressure = max(pressure, 1.0) + float(len(violations))

    return SoakResult(
        nodes=cfg.nodes, ticks=cfg.ticks, seed=cfg.seed,
        pods_submitted=total_pods, bound=len(plane.bind_counts),
        double_binds=double, racy_writes=racy,
        loop_stalls=len(stalls),
        max_stall_ms=1e3 * max(stalls, default=0.0),
        p50_ms=p50, p99_ms=p99,
        converged=converged, pending_at_end=len(pending),
        faults_injected=plane.stats.injected_total,
        node_flaps=sum(1 for f in plane.stats.node_flaps
                       if f["kind"] == "down"),
        drains=counts.get(NODE_DRAIN, 0), adds=counts.get(NODE_ADD, 0),
        orphans_gced=orphans_gced,
        scaleups=autoscaler.scaleups, desched_moves=desched.moves,
        rss_warm_bytes=rss_warm, rss_peak_bytes=rss_peak,
        rss_growth_frac=growth,
        wal_records=inner._wal_records, compactions=inner.compactions,
        tsdb_series=series_end, jit_variants=jit_end,
        watch_history=samples[-1]["watch"],
        events_applied=applied, seconds=seconds,
        event_errors=event_errors, violations=violations,
        pressure=pressure)


def run_soak(config: TraceConfig | None = None, *,
             tape: Tape | None = None, mutations=(),
             tick_seconds: float = 0.05, snapshot_every: int = 2000,
             p99_bound_ms: float = 0.0, rss_slack_frac: float = 0.35,
             warmup_frac: float = 0.4, error_rate: float = 0.01,
             race_detect: bool = True, heartbeat_every: float = 0.5,
             resync_every: float = 0.25, autoscaler_every: int = 2,
             descheduler_every: int = 10, scrape_every: int = 4,
             converge_timeout_s: float = 120.0) -> SoakResult:
    """Blocking entry point: generate (or take) a tape and play the day.

    ``p99_bound_ms=0`` leaves the latency gate disarmed (smoke tier);
    the full bench arms it. The WAL lives in a temp dir for the run —
    compaction behavior is what's under test, not the artifact."""
    if tape is None:
        tape = make_tape(config or TraceConfig(), mutations)
    with tempfile.TemporaryDirectory(prefix="ktpu-soak-") as td:
        return asyncio.run(_run_soak(
            tape, tick_seconds=tick_seconds, snapshot_every=snapshot_every,
            p99_bound_ms=p99_bound_ms, rss_slack_frac=rss_slack_frac,
            warmup_frac=warmup_frac, error_rate=error_rate,
            race_detect=race_detect, heartbeat_every=heartbeat_every,
            resync_every=resync_every, autoscaler_every=autoscaler_every,
            descheduler_every=descheduler_every,
            scrape_every=scrape_every,
            wal_path=os.path.join(td, "soak.wal"),
            converge_timeout_s=converge_timeout_s))
