from kubernetes_tpu.proxy.proxier import FakeIptables, Proxier  # noqa: F401
