"""kube-proxy (iptables mode): Services + Endpoints -> one NAT table flush.

The pkg/proxy/iptables/proxier.go analog (syncProxyRules :980): watch
Services and Endpoints, compile the COMPLETE kube NAT ruleset in memory —
KUBE-SERVICES dispatch, one KUBE-SVC-* chain per service port, one
KUBE-SEP-* chain per endpoint with statistic-mode random load balancing —
and hand it to `iptables-restore` in a single atomic call (the reference's
central performance idea: never mutate rules incrementally,
pkg/util/iptables/iptables.go:356 Restore).

The iptables boundary is an interface: `SystemIptables` execs the real
`iptables-restore` binary; `FakeIptables` records the restore payloads —
exactly how the reference tests its proxier (fake iptables double,
proxier_test.go). Chain naming matches the reference:
KUBE-SVC-/KUBE-SEP- + base32(sha256(...))[:16] (proxier.go:528
servicePortChainName).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import subprocess

from kubernetes_tpu.client.informer import Informer

log = logging.getLogger(__name__)


def _chain_hash(*parts: str) -> str:
    digest = hashlib.sha256("/".join(parts).encode()).digest()
    return base64.b32encode(digest).decode()[:16]


def svc_chain(ns: str, name: str, port_name: str) -> str:
    return "KUBE-SVC-" + _chain_hash(ns, name, port_name)


def sep_chain(ns: str, name: str, port_name: str, endpoint: str) -> str:
    return "KUBE-SEP-" + _chain_hash(ns, name, port_name, endpoint)


# jump rules from the built-in chains into the kube chains — without these
# the whole ruleset is unreachable (the reference EnsureRule()s them outside
# the restore payload, proxier.go:565-600, because declaring a built-in
# chain in a restore would flush unrelated rules from it). The filter-table
# KUBE-SERVICES chain carries the no-endpoints REJECTs (REJECT is not a
# valid nat-table target; proxier.go:544-556).
JUMP_RULES = (
    ("nat", "PREROUTING", "-m comment --comment "
                          "kubernetes-service-portals -j KUBE-SERVICES"),
    ("nat", "OUTPUT", "-m comment --comment kubernetes-service-portals "
                      "-j KUBE-SERVICES"),
    ("nat", "POSTROUTING", "-m comment --comment "
                           "kubernetes-postrouting-rules "
                           "-j KUBE-POSTROUTING"),
    ("filter", "INPUT", "-m comment --comment kubernetes-service-portals "
                        "-j KUBE-SERVICES"),
    ("filter", "OUTPUT", "-m comment --comment "
                         "kubernetes-service-portals -j KUBE-SERVICES"),
)


class FakeIptables:
    """Test double recording restore payloads (the reference's fake)."""

    def __init__(self):
        self.restores: list[str] = []
        self.jumps: list[tuple[str, str]] = []

    def ensure_jumps(self) -> None:
        self.jumps = list(JUMP_RULES)

    def restore(self, rules: str) -> None:
        self.restores.append(rules)

    @property
    def current(self) -> str:
        return self.restores[-1] if self.restores else ""


class SystemIptables:
    """Execs the real iptables binaries (iptables.go:98,356)."""

    def ensure_jumps(self) -> None:
        for table, chain, rule in JUMP_RULES:
            check = subprocess.run(
                ["iptables", "-t", table, "-C", chain, *rule.split()],
                capture_output=True, timeout=30)
            if check.returncode != 0:
                subprocess.run(
                    ["iptables", "-t", table, "-A", chain, *rule.split()],
                    check=True, timeout=30)

    def restore(self, rules: str) -> None:
        subprocess.run(["iptables-restore", "--noflush"], input=rules,
                       text=True, check=True, timeout=30)


class Proxier:
    def __init__(self, store, iptables=None, cluster_cidr: str = ""):
        self.store = store
        self.iptables = iptables if iptables is not None else FakeIptables()
        self.cluster_cidr = cluster_cidr
        self.services = Informer(store, "Service")
        self.endpoints = Informer(store, "Endpoints")
        self.services.add_handler(self._on_change)
        self.endpoints.add_handler(self._on_change)
        self._dirty = asyncio.Event()
        self._task: asyncio.Task | None = None
        self.sync_count = 0

    def _on_change(self, _event) -> None:
        self._dirty.set()

    # ---- lifecycle ----

    async def start(self) -> None:
        self.services.start()
        self.endpoints.start()
        await self.services.wait_for_sync()
        await self.endpoints.wait_for_sync()
        self.iptables.ensure_jumps()
        self.sync_proxy_rules()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.services.stop()
        self.endpoints.stop()

    # minSyncPeriod-style retry delay after a failed flush; full resync
    # period mirrors the reference's syncPeriod default (30s)
    RETRY_DELAY = 1.0
    SYNC_PERIOD = 30.0

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(self._dirty.wait(), self.SYNC_PERIOD)
                self._dirty.clear()
                await asyncio.sleep(0.01)  # debounce a watch-event burst
            except asyncio.TimeoutError:
                pass  # periodic resync even without changes
            try:
                self.sync_proxy_rules()
            except Exception:  # noqa: BLE001 — a failed flush must not
                # kill the sync loop; mark dirty and retry (the reference
                # retries every syncPeriod)
                log.exception("iptables flush failed; retrying")
                self._dirty.set()
                await asyncio.sleep(self.RETRY_DELAY)

    # ---- the compiler (syncProxyRules, proxier.go:980) ----

    def _endpoints_for(self, ns: str, name: str,
                       port_name: str) -> list[dict]:
        """Backends for ONE service port: endpoint subset ports match the
        service port by name (multi-port services must not DNAT :443 to a
        backend's :80; proxier.go endpointsMap keying by ServicePortName)."""
        eps = self.endpoints.get(name, ns)
        if eps is None:
            return []
        out = []
        for subset in eps.subsets:
            ports = subset.get("ports", [])
            port = next(
                (p.get("port") for p in ports
                 if p.get("port") and p.get("name", "") == port_name),
                None)
            if port is None and not port_name and len(ports) == 1:
                port = ports[0].get("port")
            for addr in subset.get("addresses", []):
                ip = addr.get("ip")
                if ip and port:
                    out.append({"ip": ip, "port": port})
        return out

    def sync_proxy_rules(self) -> str:
        """Compile and atomically restore the full NAT table. Returns the
        restore payload (for observability/tests)."""
        lines = ["*nat",
                 ":KUBE-SERVICES - [0:0]",
                 ":KUBE-NODEPORTS - [0:0]",
                 ":KUBE-MARK-MASQ - [0:0]",
                 ":KUBE-POSTROUTING - [0:0]"]
        rules: list[str] = [
            "-A KUBE-MARK-MASQ -j MARK --set-xmark 0x4000/0x4000",
            "-A KUBE-POSTROUTING -m mark --mark 0x4000/0x4000 -j MASQUERADE",
        ]
        nodeport_rules: list[str] = []
        reject_rules: list[str] = []  # filter-table section (REJECTs)
        for svc in sorted(self.services.items(),
                          key=lambda s: (s.metadata.namespace,
                                         s.metadata.name)):
            ns, name = svc.metadata.namespace, svc.metadata.name
            cluster_ip = svc.spec.get("clusterIP", "")
            if not cluster_ip or cluster_ip == "None":
                continue  # headless / not yet allocated
            # ClientIP session affinity pins a source to one backend via
            # the `recent` match (proxier.go:880 affinityMap; timeout from
            # sessionAffinityConfig, default 10800s)
            affinity = svc.spec.get("sessionAffinity", "") == "ClientIP"
            affinity_timeout = int(
                ((svc.spec.get("sessionAffinityConfig") or {})
                 .get("clientIP") or {}).get("timeoutSeconds") or 10800)
            for p in svc.spec.get("ports") or []:
                port = int(p.get("port") or 0)
                if not port:
                    continue
                proto = p.get("protocol", "TCP").lower()
                port_name = p.get("name", "")
                node_port = int(p.get("nodePort") or 0)
                endpoints = self._endpoints_for(ns, name, port_name)
                svcc = svc_chain(ns, name, port_name)
                comment = f'"{ns}/{name}:{port_name}"'
                if not endpoints:
                    # no backends: REJECT so clients fail fast — in the
                    # FILTER table (REJECT is not a valid nat target;
                    # proxier.go:1171 writes these to filterChains)
                    reject_rules.append(
                        f"-A KUBE-SERVICES -d {cluster_ip}/32 -p {proto} "
                        f"-m {proto} --dport {port} -m comment --comment "
                        f"{comment} -j REJECT")
                    if node_port:
                        reject_rules.append(
                            f"-A KUBE-SERVICES -p {proto} -m {proto} "
                            f"--dport {node_port} -m addrtype "
                            f"--dst-type LOCAL -m comment --comment "
                            f"{comment} -j REJECT")
                    continue
                lines.append(f":{svcc} - [0:0]")
                if self.cluster_cidr:
                    # off-cluster sources hitting the VIP get masqueraded
                    # (proxier.go:1136 "!--src <clusterCIDR> -> MASQ")
                    rules.append(
                        f"-A KUBE-SERVICES ! -s {self.cluster_cidr} "
                        f"-d {cluster_ip}/32 -p {proto} -m {proto} "
                        f"--dport {port} -m comment --comment {comment} "
                        f"-j KUBE-MARK-MASQ")
                rules.append(
                    f"-A KUBE-SERVICES -d {cluster_ip}/32 -p {proto} "
                    f"-m {proto} --dport {port} -m comment --comment "
                    f"{comment} -j {svcc}")
                if node_port:
                    # nodePort traffic always masquerades (the reply must
                    # return via this node; proxier.go:1158-1169), then
                    # shares the service chain
                    nodeport_rules.append(
                        f"-A KUBE-NODEPORTS -p {proto} -m {proto} "
                        f"--dport {node_port} -m comment --comment "
                        f"{comment} -j KUBE-MARK-MASQ")
                    nodeport_rules.append(
                        f"-A KUBE-NODEPORTS -p {proto} -m {proto} "
                        f"--dport {node_port} -m comment --comment "
                        f"{comment} -j {svcc}")
                n = len(endpoints)
                sep_chains = []
                for ep in endpoints:
                    endpoint = f"{ep['ip']}:{ep['port']}"
                    sep_chains.append(
                        (sep_chain(ns, name, port_name, endpoint), ep,
                         endpoint))
                    lines.append(f":{sep_chains[-1][0]} - [0:0]")
                if affinity:
                    # returning clients short-circuit to their recorded
                    # backend before the random split (proxier.go:1484)
                    for sepc, _ep, _endpoint in sep_chains:
                        rules.append(
                            f"-A {svcc} -m recent --name {sepc} --rcheck "
                            f"--seconds {affinity_timeout} --reap "
                            f"-j {sepc}")
                for i, (sepc, ep, endpoint) in enumerate(sep_chains):
                    if i < n - 1:
                        # statistic-mode random split over the remaining
                        # backends (proxier.go:1500)
                        rules.append(
                            f"-A {svcc} -m statistic --mode random "
                            f"--probability {1.0 / (n - i):.5f} -j {sepc}")
                    else:
                        rules.append(f"-A {svcc} -j {sepc}")
                    rules.append(
                        f"-A {sepc} -s {ep['ip']}/32 -j KUBE-MARK-MASQ")
                    if affinity:
                        rules.append(
                            f"-A {sepc} -m recent --name {sepc} --set "
                            f"-p {proto} -m {proto} -j DNAT "
                            f"--to-destination {endpoint}")
                    else:
                        rules.append(
                            f"-A {sepc} -p {proto} -m {proto} -j DNAT "
                            f"--to-destination {endpoint}")
        if nodeport_rules:
            # the nodeports dispatch anchors LAST in KUBE-SERVICES
            # (proxier.go:1189: clusterIP rules take precedence)
            rules.append(
                "-A KUBE-SERVICES -m comment --comment "
                '"kubernetes service nodeports" -m addrtype '
                "--dst-type LOCAL -j KUBE-NODEPORTS")
            rules.extend(nodeport_rules)
        sections = lines + rules + ["COMMIT"]
        # filter-table section: the no-endpoints REJECT chain
        sections += ["*filter", ":KUBE-SERVICES - [0:0]"]
        sections += reject_rules
        sections += ["COMMIT", ""]
        payload = "\n".join(sections)
        self.iptables.restore(payload)
        self.sync_count += 1
        return payload
