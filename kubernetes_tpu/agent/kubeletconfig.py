"""Dynamic kubelet configuration: ConfigMap-sourced config with
checkpoint + last-known-good rollback.

The pkg/kubelet/kubeletconfig analog (controller.go: watch
Node.spec.configSource, download the named ConfigMap, checkpoint it on
local disk, apply on the next sync; a config that fails validation rolls
back to the last-known-good checkpoint and reports the failure through
the node's KubeletConfigOk condition — status.go:71).

Applied fields at hollow fidelity (the knobs this kubelet actually has):
``heartbeatIntervalSeconds``, ``evictionHard`` (``memory.available`` /
``nodefs.available`` Mi thresholds), ``plegPeriodSeconds``. The config
payload lives under the ConfigMap's ``kubelet`` key as JSON, mirroring
the reference's kubelet.config.k8s.io serialization seam.
"""

from __future__ import annotations

import json
import logging
import os
import time

from kubernetes_tpu.api.objects import NodeCondition
from kubernetes_tpu.apiserver.store import Conflict, NotFound

log = logging.getLogger(__name__)

CONFIG_OK_CONDITION = "KubeletConfigOk"
ALLOWED_KEYS = {"heartbeatIntervalSeconds", "evictionHard",
                "plegPeriodSeconds"}


def validate_config(cfg: dict) -> str | None:
    """None when valid, else the rejection reason (the reference's
    kubeletconfig validation gate before a config may be adopted)."""
    if not isinstance(cfg, dict):
        return "config payload is not an object"
    unknown = set(cfg) - ALLOWED_KEYS
    if unknown:
        return f"unknown config keys: {sorted(unknown)}"
    hb = cfg.get("heartbeatIntervalSeconds")
    if hb is not None and (not isinstance(hb, (int, float)) or hb <= 0):
        return "heartbeatIntervalSeconds must be > 0"
    pleg = cfg.get("plegPeriodSeconds")
    if pleg is not None and (not isinstance(pleg, (int, float))
                             or pleg <= 0):
        return "plegPeriodSeconds must be > 0"
    ev = cfg.get("evictionHard")
    if ev is not None:
        if not isinstance(ev, dict):
            return "evictionHard must be an object"
        for key, value in ev.items():
            if key not in ("memory.available", "nodefs.available"):
                return f"unknown eviction signal {key!r}"
            if not isinstance(value, (int, float)) or value < 0:
                return f"evictionHard[{key!r}] must be >= 0"
    return None


class ConfigSync:
    """One kubelet's dynamic-config loop state (kubeletconfig's
    Controller). `sync()` runs on the kubelet's heartbeat cadence."""

    def __init__(self, kubelet, checkpoint_dir: str):
        self.kubelet = kubelet
        self.checkpoint_dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self._last_applied_uid = ""
        self._load_checkpoints()

    # ---- checkpoint store (kubeletconfig/checkpoint/store) ----

    def _path(self, which: str) -> str:
        return os.path.join(self.checkpoint_dir,
                            f"{self.kubelet.node_name}-{which}.json")

    def _load_checkpoints(self) -> None:
        """Resume after restart: re-apply the current checkpoint (or the
        last-known-good) before the first watch delivery."""
        for which in ("current", "last-known-good"):
            try:
                with open(self._path(which)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if validate_config(doc.get("config", {})) is None:
                self._apply(doc["config"])
                self._last_applied_uid = doc.get("uid", "")
                return

    def _checkpoint(self, which: str, uid: str, cfg: dict) -> None:
        with open(self._path(which), "w") as f:
            json.dump({"uid": uid, "config": cfg}, f)

    # ---- the sync pass ----

    def sync(self) -> None:
        store = self.kubelet.store
        try:
            node = store.get("Node", self.kubelet.node_name, "default")
        except NotFound:
            return
        source = (node.spec.config_source or {}).get("configMap")
        if not source:
            return
        try:
            cm = store.get("ConfigMap", source.get("name", ""),
                           source.get("namespace", "default"))
        except NotFound:
            self._set_condition(False, "ConfigMapNotFound",
                                f"configmap {source} not found")
            return
        uid = f"{cm.metadata.uid}/{cm.metadata.resource_version}"
        if uid == self._last_applied_uid:
            return
        try:
            cfg = json.loads((cm.data or {}).get("kubelet", "{}"))
            reason = validate_config(cfg)
        except ValueError:
            reason = "config payload is not valid JSON"
            cfg = None
        if reason is not None:
            # bad config: ROLL BACK to last-known-good (status.go's
            # lkg path) and report through the condition
            log.warning("kubelet %s: rejecting config %s: %s",
                        self.kubelet.node_name, uid, reason)
            self._last_applied_uid = uid  # don't re-try a bad payload
            rolled = self._rollback()
            self._set_condition(
                False, "FailedValidation",
                f"{reason}; "
                + ("rolled back to last-known-good" if rolled
                   else "keeping built-in defaults"))
            return
        self._apply(cfg)
        self._checkpoint("current", uid, cfg)
        self._checkpoint("last-known-good", uid, cfg)
        self._last_applied_uid = uid
        self._set_condition(True, "KubeletConfigOk",
                            f"using config {source.get('name')}")

    def _rollback(self) -> bool:
        try:
            with open(self._path("last-known-good")) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return False
        if validate_config(doc.get("config", {})) is not None:
            return False
        self._apply(doc["config"])
        return True

    def _apply(self, cfg: dict) -> None:
        kubelet = self.kubelet
        if "heartbeatIntervalSeconds" in cfg:
            kubelet.heartbeat_every = float(
                cfg["heartbeatIntervalSeconds"])
        if "plegPeriodSeconds" in cfg:
            kubelet.PLEG_PERIOD = float(cfg["plegPeriodSeconds"])
        ev = cfg.get("evictionHard")
        if ev and getattr(kubelet, "eviction", None) is not None:
            if "memory.available" in ev:
                kubelet.eviction.memory_available_mib = float(
                    ev["memory.available"])
            if "nodefs.available" in ev:
                kubelet.eviction.disk_available_mib = float(
                    ev["nodefs.available"])

    def _set_condition(self, ok: bool, reason: str, message: str) -> None:
        want = "True" if ok else "False"
        now = time.time()

        def mutate(node):
            existing = None
            for c in node.status.conditions:
                if c.type == CONFIG_OK_CONDITION:
                    existing = c
            if existing is None:
                existing = NodeCondition(type=CONFIG_OK_CONDITION,
                                         status="")
                node.status.conditions.append(existing)
            if existing.status != want:
                existing.last_transition_time = now
            existing.status = want
            existing.reason = reason
            existing.message = message
            existing.last_heartbeat_time = now
            return node

        try:
            self.kubelet.store.guaranteed_update(
                "Node", self.kubelet.node_name, "default", mutate)
        except (Conflict, NotFound):
            pass
