"""Hollow kubelet: the kubemark-style node agent.

Plays the kubelet's control-plane role without a container runtime
(pkg/kubemark/hollow_kubelet.go:44 runs the real kubelet against fake
docker/cadvisor; here the "runtime" is a no-op that starts instantly):

- registers its Node object (kubelet_node_status.go registerWithAPIServer),
- heartbeats NodeStatus Ready on a period (:borrows tryUpdateNodeStatus,
  10s default in the reference),
- acks bindings: pods scheduled onto it transition Pending -> Running with
  a Ready condition (syncPod -> status_manager PATCH,
  pkg/kubelet/status/status_manager.go:131),
- stops acking/heartbeating when stopped — the failure-injection lever the
  node lifecycle controller detects.

A HollowCluster shares ONE pod informer across N agents (kubemark scale
shape: thousands of hollow nodes on one host), dispatching bound pods to
their node's agent by spec.nodeName.
"""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.api.objects import Node, NodeCondition, Pod
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    TooManyRequests,
)
from kubernetes_tpu.client.informer import Informer

log = logging.getLogger(__name__)

DEFAULT_HEARTBEAT = 10.0  # nodeStatusUpdateFrequency (componentconfig)


class HollowKubelet:
    """One node's agent. Create via HollowCluster for shared informers."""

    def __init__(self, store: ObjectStore, node_name: str,
                 heartbeat_every: float = DEFAULT_HEARTBEAT,
                 capacity: dict | None = None,
                 labels: dict | None = None):
        self.store = store
        self.node_name = node_name
        self.heartbeat_every = heartbeat_every
        self.capacity = capacity or {"cpu": "4", "memory": "8Gi",
                                     "pods": "110"}
        self.labels = labels or {}
        self._task: asyncio.Task | None = None
        self.running = False
        # False = heartbeats report NotReady (kubelet-detected local
        # trouble, e.g. runtime down) WITHOUT stopping — the flapping /
        # partial-failure shape the reference's zone handling sees
        # (node_controller.go:170); stop() remains the hard-death lever
        self.report_ready = True

    # ---- registration + heartbeat ----

    def register(self) -> None:
        """Create or refresh this kubelet's Node (registerWithAPIServer)."""
        try:
            node = self.store.get("Node", self.node_name, "default")
        except NotFound:
            node = Node.from_dict({
                "metadata": {"name": self.node_name,
                             "labels": {"kubernetes.io/hostname":
                                        self.node_name, **self.labels}},
                "status": {"allocatable": dict(self.capacity),
                           "capacity": dict(self.capacity)}})
            try:
                self.store.create(node)
            except AlreadyExists:
                pass
        self._heartbeat()

    def _heartbeat(self) -> None:
        now = time.time()
        want = "True" if self.report_ready else "False"
        reason = "KubeletReady" if self.report_ready else "KubeletNotReady"

        def mutate(node):
            # CAS mutating ONLY the Ready condition: a blind full-object
            # write here raced the lifecycle controller's taint writes and
            # the TTL annotation (every heartbeat could wipe a just-added
            # NoExecute taint, flapping evictions forever)
            ready = None
            for c in node.status.conditions:
                if c.type == "Ready":
                    ready = c
            if ready is None:
                ready = NodeCondition(type="Ready")
                node.status.conditions.append(ready)
            if ready.status != want:
                ready.last_transition_time = now
            ready.status = want
            ready.reason = reason
            ready.last_heartbeat_time = now
            return node

        try:
            self.store.guaranteed_update("Node", self.node_name, "default",
                                         mutate)
        except (Conflict, NotFound, TooManyRequests):
            # a throttled heartbeat is a missed heartbeat, not a crash:
            # the next period retries (tryUpdateNodeStatus's retry shape)
            pass

    # ---- pod lifecycle ----

    def ack_pod(self, pod: Pod) -> None:
        """Binding observed: run the (instant) hollow runtime and report
        Running + Ready (the syncPod -> status PATCH path)."""
        if not self.running:
            return
        fresh = None
        try:
            fresh = self.store.get("Pod", pod.metadata.name,
                                   pod.metadata.namespace)
        except NotFound:
            return
        except TooManyRequests:
            return  # throttled ack: the resync sweep retries it
        if fresh.spec.node_name != self.node_name \
                or fresh.status.phase == "Running":
            return
        now = time.time()
        fresh.status.phase = "Running"
        fresh.status.conditions = [
            {"type": "Ready", "status": "True", "lastTransitionTime": now}]
        try:
            # CAS against the version just read: a concurrent writer wins
            # and the resync sweep retries the ack
            self.store.update(fresh)
        except (Conflict, NotFound, TooManyRequests):
            pass

    # ---- lifecycle ----

    async def start(self) -> None:
        self.register()
        self.running = True
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        """Stop heartbeating and acking — from the control plane's view the
        node just died (the kubemark failure-injection lever)."""
        self.running = False
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_every)
            if not self.running:
                return
            self._heartbeat()


class HollowCluster:
    """N hollow kubelets over one shared pod informer (kubemark shape)."""

    def __init__(self, store: ObjectStore, n_nodes: int = 0,
                 name_prefix: str = "hollow",
                 heartbeat_every: float = DEFAULT_HEARTBEAT,
                 capacity: dict | None = None, zones: int = 0,
                 resync_every: float = 0.0):
        self.store = store
        self.kubelets: dict[str, HollowKubelet] = {}
        self.pod_informer = Informer(store, "Pod")
        self.pod_informer.add_handler(self._on_pod)
        # resync_every > 0 turns on a level-triggered sweep re-acking bound
        # pods that are not Running yet: an ack dropped by a store fault or
        # a watch gap is retried instead of lost forever (the kubelet's
        # periodic syncPod, not just edge-triggered status writes)
        self.resync_every = resync_every
        self._resync_task: asyncio.Task | None = None
        for i in range(n_nodes):
            name = f"{name_prefix}-{i}"
            labels = ({"failure-domain.beta.kubernetes.io/zone":
                       f"zone-{i % zones}"} if zones else None)
            self.kubelets[name] = HollowKubelet(
                store, name, heartbeat_every=heartbeat_every,
                capacity=capacity, labels=labels)

    def add(self, kubelet: HollowKubelet) -> None:
        self.kubelets[kubelet.node_name] = kubelet

    def _on_pod(self, event) -> None:
        if event.type == "DELETED":
            return
        pod = event.obj
        if not pod.spec.node_name:
            return
        kubelet = self.kubelets.get(pod.spec.node_name)
        if kubelet is not None and kubelet.running:
            kubelet.ack_pod(pod)

    def _resync(self) -> None:
        """Re-ack every bound-but-not-Running pod from the informer cache
        (level-triggered: whatever events were missed, the state heals)."""
        for pod in self.pod_informer.items():
            if pod.spec.node_name and pod.status.phase != "Running":
                kubelet = self.kubelets.get(pod.spec.node_name)
                if kubelet is not None and kubelet.running:
                    kubelet.ack_pod(pod)

    async def _resync_loop(self) -> None:
        while True:
            await asyncio.sleep(self.resync_every)
            try:
                self._resync()
            except Exception:  # noqa: BLE001 — the sweep must survive faults
                log.exception("hollow resync sweep failed; retrying")

    async def start(self) -> None:
        self.pod_informer.start()
        for kubelet in self.kubelets.values():
            await kubelet.start()
        await self.pod_informer.wait_for_sync()
        # ack pods bound before the informer synced
        self._resync()
        if self.resync_every > 0:
            self._resync_task = asyncio.get_running_loop().create_task(
                self._resync_loop())

    def stop(self, node_names=None) -> None:
        """Stop all agents (or the named subset — partial failure)."""
        names = node_names if node_names is not None \
            else list(self.kubelets.keys())
        for name in names:
            self.kubelets[name].stop()
        if node_names is None:
            self.pod_informer.stop()
            if self._resync_task is not None:
                self._resync_task.cancel()
                self._resync_task = None
