"""Kubelet: sync loop, per-pod workers, PLEG, status manager — over a
CRI-style runtime interface.

The pkg/kubelet analog at kubemark fidelity: the control-plane machinery is
real (the same loops the reference runs), the container runtime is a fake
(pkg/kubemark/hollow_kubelet.go runs the real kubelet against fake docker):

- **config source**: the pod informer filtered to spec.nodeName == me (the
  apiserver watch source of syncLoopIteration, kubelet.go:1766);
- **pod workers**: one serialized update queue per pod feeding syncPod
  (pod_workers.go:153 managePodLoop) — create in the runtime, then report
  Running + Ready through the status manager;
- **PLEG**: a periodic relist of runtime state producing lifecycle events
  (pleg/generic.go:181 relist) — exited containers become
  Succeeded/Failed status updates;
- **status manager**: dedups and writes status to the apiserver
  (status/status_manager.go:131 syncPod PATCH);
- **node status**: register + periodic Ready heartbeats
  (kubelet_node_status.go), same as the hollow kubelet.

`FakeRuntime` implements the runtime interface (CRI RunPodSandbox/
CreateContainer/StopPodSandbox shape, collapsed to pod granularity the way
kubemark's fake docker behaves): pods run instantly; pods whose restart
policy is not Always exit successfully after `run-seconds` (annotation
``kubernetes-tpu/run-seconds``, default 0) — which is what lets Jobs run
to completion end-to-end with no manual phase edits."""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.agent.hollow import HollowKubelet
from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.obs.tracing import TRACER, pod_trace_context

log = logging.getLogger(__name__)

_kubelet_mx: tuple | None = None


def _kubelet_metrics() -> tuple:
    """(sync_pod_duration, pleg_relist_duration) histograms — the
    kubelet's sync-loop metrics (pkg/kubelet/metrics), unlabeled: one
    per-process pair, not per-pod (a hollow fleet runs thousands)."""
    global _kubelet_mx
    if _kubelet_mx is None:
        from kubernetes_tpu.obs import metrics as m

        buckets = m.exponential_buckets(1e-5, 4.0, 10)
        _kubelet_mx = (
            m.REGISTRY.histogram("kubelet_sync_pod_duration_seconds",
                                 "Duration of one syncPod pass.",
                                 buckets=buckets),
            m.REGISTRY.histogram("kubelet_pleg_relist_duration_seconds",
                                 "Duration of one PLEG relist pass.",
                                 buckets=buckets),
        )
    return _kubelet_mx


# live cpu usage as a fraction of request (the hollow fleet's stand-in for
# cadvisor samples; controllers/hpa.py AnnotationMetrics reads the same key)
CPU_USAGE_ANNOTATION = "kubernetes-tpu/cpu-usage"

RUN_SECONDS_ANNOTATION = "kubernetes-tpu/run-seconds"
EXIT_CODE_ANNOTATION = "kubernetes-tpu/exit-code"
# fake-runtime probe answers (the scripted half of probing; exec probes run
# against the fake shell instead): flip these annotations on the live pod
# to fail its readiness/liveness, like breaking the real endpoint would
READY_ANNOTATION = "kubernetes-tpu/ready"
LIVE_ANNOTATION = "kubernetes-tpu/live"


class FakeRuntime:
    """CRI-shaped fake: instant sandbox/container start, scripted exits,
    per-pod log buffers and exec (the kubelet server's southbound surface:
    ReadLogs / ExecSync in the CRI)."""

    def __init__(self):
        self._pods: dict[str, dict] = {}
        self._logs: dict[str, list[str]] = {}

    def sync_pod(self, pod: Pod) -> None:
        """RunPodSandbox + CreateContainer + StartContainer, collapsed."""
        if pod.key in self._pods:
            return
        runs_forever = pod.spec.restart_policy == "Always"
        ann = pod.metadata.annotations
        self._pods[pod.key] = {
            "state": "running",
            "started": time.monotonic(),
            "exit_after": (None if runs_forever else
                           float(ann.get(RUN_SECONDS_ANNOTATION, 0) or 0)),
            "exit_code": int(ann.get(EXIT_CODE_ANNOTATION, 0) or 0),
        }
        names = ", ".join(c.name for c in pod.spec.containers) or "c"
        self._logs.setdefault(pod.key, []).append(
            f"{pod.metadata.name}: started containers [{names}]")

    def read_logs(self, key: str) -> list[str]:
        """CRI ReadLogs analog."""
        return list(self._logs.get(key, ()))

    def append_log(self, key: str, line: str) -> None:
        self._logs.setdefault(key, []).append(line)

    def exec_sync(self, key: str, command: list[str]) -> tuple[int, str]:
        """CRI ExecSync analog: echo-style fake shell against the running
        sandbox; exits 126 when the pod isn't running."""
        entry = self._pods.get(key)
        if entry is None or entry["state"] != "running":
            return 126, f"container not running in {key}\n"
        if command[:1] == ["echo"]:
            return 0, " ".join(command[1:]) + "\n"
        if command[:1] == ["hostname"]:
            return 0, key.split("/", 1)[1] + "\n"
        if command[:1] == ["false"]:
            return 1, ""
        return 0, f"exec: {' '.join(command)}\n"

    def probe(self, key: str, pod: Pod, probe: dict, kind: str) -> bool:
        """Execute one probe (prober/prober.go runProbe collapsed onto the
        fake): exec probes run the fake shell (rc 0 = success); httpGet/
        tcpSocket have nothing real behind them, so the scripted
        annotations answer (the kubemark-style fake boundary)."""
        entry = self._pods.get(key)
        if entry is None or entry["state"] != "running":
            return False
        ex = (probe or {}).get("exec")
        if ex:
            rc, _out = self.exec_sync(key, list(ex.get("command") or []))
            return rc == 0
        ann = pod.metadata.annotations
        if kind == "readiness":
            return ann.get(READY_ANNOTATION, "true") != "false"
        return ann.get(LIVE_ANNOTATION, "true") != "false"

    def kill_pod(self, key: str) -> None:
        """StopPodSandbox + RemovePodSandbox. Logs survive (a finished
        Job's logs stay readable until the pod object is deleted)."""
        self._pods.pop(key, None)

    def purge(self, key: str) -> None:
        """Pod object deleted: sandbox AND logs go."""
        self._pods.pop(key, None)
        self._logs.pop(key, None)

    def __contains__(self, key: str) -> bool:
        """Part of the runtime interface: is this pod's sandbox present?"""
        return key in self._pods

    def list_pods(self) -> dict[str, dict]:
        """The PLEG relist source: advance scripted exits, then snapshot."""
        now = time.monotonic()
        for entry in self._pods.values():
            if (entry["state"] == "running"
                    and entry["exit_after"] is not None
                    and now - entry["started"] >= entry["exit_after"]):
                entry["state"] = "exited"
        return dict(self._pods)


class Kubelet(HollowKubelet):
    """A node agent with the kubelet's loop structure; inherits
    registration + heartbeats from the hollow kubelet."""

    PLEG_PERIOD = 0.05  # reference relists at 1s; fakes are faster

    MOUNT_RETRY = 0.1  # reconciler retry period over fakes

    EVICTION_PERIOD = 0.1  # reference monitors every 10s; fakes are faster

    def __init__(self, store: ObjectStore, node_name: str,
                 runtime: FakeRuntime | None = None,
                 volume_manager=None, serve_api: bool = False,
                 eviction=None, config_dir: str | None = None, **kw):
        super().__init__(store, node_name, **kw)
        from kubernetes_tpu.agent.volumes import VolumeManager

        self.runtime = runtime if runtime is not None else FakeRuntime()
        self.volumes = volume_manager if volume_manager is not None \
            else VolumeManager(store, node_name)
        # eviction manager (agent/eviction.py); None = no eviction loop
        # (the reference's --eviction-hard= empty disables it too)
        self.eviction = eviction
        if eviction is not None and eviction.runtime is None:
            eviction.runtime = self.runtime
        self._eviction_task: asyncio.Task | None = None
        # dynamic kubelet config (agent/kubeletconfig.py): a checkpoint
        # dir enables the Node.spec.configSource sync loop
        self.config_sync = None
        self._config_task: asyncio.Task | None = None
        if config_dir is not None:
            from kubernetes_tpu.agent.kubeletconfig import ConfigSync

            self.config_sync = ConfigSync(self, config_dir)
        # allocatable accounting + kubelet-side admission (agent/cm.py)
        from kubernetes_tpu.agent.cm import ContainerManager

        self.cm = ContainerManager(store, node_name)
        self.serve_api = serve_api
        self.server = None  # KubeletServer when serve_api
        self._workers: dict[str, asyncio.Queue] = {}
        self._worker_tasks: dict[str, asyncio.Task] = {}
        self._pleg_task: asyncio.Task | None = None
        self._probe_task: asyncio.Task | None = None
        self._reported: dict[str, tuple] = {}  # status-manager dedup cache
        # prober manager state (prober/prober_manager.go:60): last pod spec
        # seen per worker, readiness results, consecutive liveness failures,
        # restart counts
        self._active: dict[str, Pod] = {}
        self._ready_state: dict[str, bool] = {}
        self._liveness_fails: dict[str, int] = {}
        self.restart_counts: dict[str, int] = {}
        # pods whose bound trace (trace.ktpu.io/context annotation) this
        # kubelet already joined — one kubelet.sync span per pod life, not
        # one per reconcile pass
        self._traced: set[str] = set()

    # ---- config source (dispatch from the shared informer) ----

    def handle_pod(self, event_type: str, pod: Pod) -> None:
        """HandlePodAdditions/Updates/Removals (kubelet.go:1906)."""
        if not self.running:
            return
        if event_type == "DELETED":
            self._stop_worker(pod.key)
            self.runtime.purge(pod.key)
            self.volumes.unmount_pod(pod.key)
            self.cm.release(pod.key)
            self._reported.pop(pod.key, None)
            self._forget_probes(pod.key)
            self._traced.discard(pod.key)
            return
        if pod.spec.node_name != self.node_name:
            return
        if pod.status.phase in ("Succeeded", "Failed"):
            return  # terminal: our own final status write must not
            # resurrect a parked worker for a pod that will never run again
        queue = self._workers.get(pod.key)
        if queue is None:
            queue = asyncio.Queue()
            self._workers[pod.key] = queue
            self._worker_tasks[pod.key] = (
                asyncio.get_running_loop().create_task(
                    self._manage_pod_loop(pod.key, queue)))
        queue.put_nowait(pod)

    def _stop_worker(self, key: str) -> None:
        task = self._worker_tasks.pop(key, None)
        if task is not None:
            task.cancel()
        self._workers.pop(key, None)

    # ---- pod workers (pod_workers.go:153) ----

    async def _manage_pod_loop(self, key: str, queue: asyncio.Queue) -> None:
        from kubernetes_tpu.agent.volumes import MountError

        while True:
            pod = await queue.get()
            # drain to the newest update: workers serialize per pod and
            # always sync against the latest spec (UpdatePod :198)
            while not queue.empty():
                pod = queue.get_nowait()
            t0 = time.perf_counter()
            try:
                self._sync_pod(pod)
            except MountError as e:
                # WaitForAttachAndMount failure: the pod must not start;
                # the reconciler retries until the volume becomes
                # mountable (secret created, PV attached, ...)
                log.info("syncPod(%s): waiting on volumes: %s", key, e)
                loop = asyncio.get_running_loop()
                loop.call_later(self.MOUNT_RETRY, queue.put_nowait, pod)
            except Exception:  # noqa: BLE001 — a worker must not die
                log.exception("syncPod(%s) failed", key)
            finally:
                _kubelet_metrics()[0].observe(time.perf_counter() - t0)

    def _sync_pod(self, pod: Pod) -> None:
        """syncPod (kubelet.go:1390): kubelet admission first (canAdmitPod
        — allocatable accounting, agent/cm.py), then volumes
        (WaitForAttachAndMount, kubelet.go:1447), then the runtime, then
        report status. The first sync of a trace-annotated pod joins the
        pod's bound trace (the stitched trace's terminal hop)."""
        if pod.status.phase in ("Succeeded", "Failed"):
            return
        ctx = None
        if pod.key not in self._traced:
            ctx = pod_trace_context(pod)
            if ctx is not None:
                self._traced.add(pod.key)
        if ctx is not None:
            with TRACER.start_span("kubelet.sync", parent=ctx,
                                   tid="kubelet",
                                   attrs={"pod": pod.key,
                                          "node": self.node_name}):
                self._sync_pod_inner(pod)
        else:
            self._sync_pod_inner(pod)

    def _sync_pod_inner(self, pod: Pod) -> None:
        if pod.key not in self.runtime:
            reason = self.cm.admit(pod)
            if reason is not None:
                # the reference rejects with status Failed reason OutOf*
                # (kubelet.go rejectPod) — the controller recreates, the
                # scheduler places the replacement elsewhere
                self._set_status(pod.key, "Failed", ready=False,
                                 reason=reason)
                self._stop_worker(pod.key)
                log.warning("kubelet %s: rejected %s: %s",
                            self.node_name, pod.key, reason)
                return
            self.volumes.mount_pod(pod)
        self.runtime.sync_pod(pod)
        self._active[pod.key] = pod
        self._set_status(pod.key, "Running",
                         ready=self._ready_state.get(
                             pod.key, self._default_ready(pod)))

    # ---- status manager (status/status_manager.go) ----

    def _set_status(self, key: str, phase: str,
                    ready: bool | None = None,
                    exit_code: int = 0, reason: str = "") -> None:
        """ready: the prober's readiness verdict (None = derive from the
        phase, the pre-prober behavior for probe-less pods)."""
        if ready is None:
            ready = phase == "Running"
        restarts = self.restart_counts.get(key, 0)
        fingerprint = (phase, ready and phase == "Running", restarts)
        if self._reported.get(key) == fingerprint:
            return  # dedup: only status *changes* reach the apiserver
        ns, name = key.split("/", 1)
        try:
            fresh = self.store.get("Pod", name, ns)
        except NotFound:
            return
        if fresh.spec.node_name != self.node_name:
            return
        fresh.status.phase = phase
        if reason:
            fresh.status.reason = reason
        ready_s = "True" if (ready and phase == "Running") else "False"
        fresh.status.conditions = [
            {"type": "Ready", "status": ready_s,
             "lastTransitionTime": time.time()}]
        running = phase == "Running"
        fresh.status.container_statuses = [
            {"name": c.name, "ready": ready_s == "True",
             "restartCount": restarts,
             "state": {"running": {}} if running else
                      {"terminated": {"exitCode": exit_code}}}
            for c in fresh.spec.containers]
        try:
            # CAS against the version just read: losing the race leaves
            # the fingerprint unreported, so the next sync retries
            self.store.update(fresh)
            self._reported[key] = fingerprint
        except (Conflict, NotFound):
            pass

    # ---- probers (prober/prober_manager.go:60, worker.go) ----

    PROBE_PERIOD = 0.1  # reference defaults to 10s; fakes are faster

    @staticmethod
    def _default_ready(pod: Pod) -> bool:
        """A pod with a readiness probe starts NOT ready until its first
        successful probe (the reference prober's initial-result contract);
        probe-less pods are ready as soon as they run."""
        return not any(c.readiness_probe for c in pod.spec.containers)

    def _forget_probes(self, key: str) -> None:
        self._active.pop(key, None)
        self._ready_state.pop(key, None)
        self._liveness_fails.pop(key, None)
        self.restart_counts.pop(key, None)

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(self.PROBE_PERIOD)
            if not self.running:
                return
            # probes answer against _active, which the informer dispatch
            # path keeps fresh (handle_pod -> _sync_pod) — no per-tick
            # store round trips (over a RemoteStore each would be a
            # blocking HTTP call inside the event loop)
            for key, pod in list(self._active.items()):
                try:
                    if key not in self.runtime:
                        continue
                    has_liveness = any(c.liveness_probe
                                       for c in pod.spec.containers)
                    has_readiness = any(c.readiness_probe
                                        for c in pod.spec.containers)
                    if not (has_liveness or has_readiness):
                        continue
                    if has_liveness and self._probe_liveness(key, pod):
                        continue  # restarted: readiness settles next tick
                    if has_readiness:
                        self._probe_readiness(key, pod)
                except Exception:  # noqa: BLE001 — probing must not die
                    log.exception("probe pass failed for %s", key)

    def _probe_liveness(self, key: str, pod: Pod) -> bool:
        """True = the probe failed hard and the pod was restarted or
        terminated this tick."""
        ok = all(self.runtime.probe(key, pod, c.liveness_probe, "liveness")
                 for c in pod.spec.containers if c.liveness_probe)
        if ok:
            self._liveness_fails.pop(key, None)
            return False
        fails = self._liveness_fails.get(key, 0) + 1
        self._liveness_fails[key] = fails
        threshold = max((int((c.liveness_probe or {}).get(
            "failureThreshold", 3)) for c in pod.spec.containers
            if c.liveness_probe), default=3)
        if fails < threshold:
            return False
        # kill, then restartPolicy decides (the sync loop's liveness
        # channel, kubelet.go syncLoopIteration livenessManager.Updates):
        # Never -> the pod goes Failed and stays down
        self._liveness_fails[key] = 0
        self.runtime.kill_pod(key)
        if pod.spec.restart_policy == "Never":
            self._set_status(key, "Failed", exit_code=137)
            self._stop_worker(key)
            self._forget_probes(key)
            log.info("liveness: %s failed, restartPolicy Never -> Failed",
                     key)
            return True
        self.restart_counts[key] = self.restart_counts.get(key, 0) + 1
        self.runtime.sync_pod(pod)
        self._reported.pop(key, None)  # force the restartCount write
        self._set_status(key, "Running",
                         ready=self._ready_state.get(
                             key, self._default_ready(pod)))
        log.info("liveness: restarted %s (count %d)", key,
                 self.restart_counts[key])
        return True

    def _probe_readiness(self, key: str, pod: Pod) -> None:
        ok = all(self.runtime.probe(key, pod, c.readiness_probe,
                                    "readiness")
                 for c in pod.spec.containers if c.readiness_probe)
        if self._ready_state.get(key) != ok:
            self._ready_state[key] = ok
            self._set_status(key, "Running", ready=ok)

    # ---- PLEG (pleg/generic.go:181) ----

    async def _pleg_loop(self) -> None:
        while True:
            await asyncio.sleep(self.PLEG_PERIOD)
            if not self.running:
                return
            t0 = time.perf_counter()
            for key, entry in self.runtime.list_pods().items():
                reported_phase = (self._reported.get(key) or (None,))[0]
                if entry["state"] == "exited" \
                        and reported_phase == "Running":
                    phase = "Succeeded" if entry["exit_code"] == 0 \
                        else "Failed"
                    self._set_status(key, phase,
                                     exit_code=entry["exit_code"])
                    self._stop_worker(key)
                    self.runtime.kill_pod(key)
                    self.volumes.unmount_pod(key)
                    self.cm.release(key)
                    self._forget_probes(key)
            _kubelet_metrics()[1].observe(time.perf_counter() - t0)

    # ---- resource metrics (/stats/summary) ----

    def stats_summary(self) -> dict:
        """The Summary API payload (pkg/kubelet/server/stats, collapsed to
        what the Monitor's resource pipeline consumes): node totals plus
        per-pod cpu/memory usage for every pod with a live sandbox. Usage
        comes from the same sources the eviction manager trusts — the
        cpu-usage annotation (fraction of request) and the memory-usage
        annotation with a requests fallback — so `kubectl top` and HPA see
        the numbers eviction acts on."""
        from kubernetes_tpu.agent.eviction import pod_memory_usage_mib
        from kubernetes_tpu.api.quantity import parse_quantity

        pods_out = []
        node_cpu = 0.0
        node_mem = 0.0
        for key, pod in sorted(self._active.items()):
            if key not in self.runtime:
                continue
            cpu_request = 0.0
            for c in pod.spec.containers:
                if "cpu" in c.requests:
                    try:
                        cpu_request += float(
                            parse_quantity(c.requests["cpu"]))
                    except (ValueError, ArithmeticError):
                        pass
            cpu: dict = {}
            raw = pod.metadata.annotations.get(CPU_USAGE_ANNOTATION)
            if raw is not None:
                try:
                    ratio = float(raw)
                except (TypeError, ValueError):
                    ratio = None
                if ratio is not None:
                    cpu["usageRatio"] = ratio
                    cpu["usageCores"] = ratio * cpu_request
            if "usageCores" not in cpu:
                # no live sample: a hollow sandbox "uses" its request
                cpu["usageCores"] = cpu_request
            mem = float(pod_memory_usage_mib(pod))
            ns, name = key.split("/", 1)
            pods_out.append({"podRef": {"name": name, "namespace": ns},
                             "cpu": cpu, "memory": {"usageMiB": mem}})
            node_cpu += cpu["usageCores"]
            node_mem += mem
        return {"node": {"nodeName": self.node_name,
                         "cpu": {"usageCores": node_cpu},
                         "memory": {"usageMiB": node_mem}},
                "pods": pods_out}

    # ---- lifecycle ----

    async def _eviction_loop(self) -> None:
        """eviction_manager.go:177 Start: synchronize on the monitor
        period (cheap at hollow scale — a store scan plus at most one
        eviction write per pass)."""
        while True:
            await asyncio.sleep(self.EVICTION_PERIOD)
            if not self.running:
                return
            try:
                evicted = self.eviction.synchronize()
                if evicted:
                    self._stop_worker(evicted)
                    self.cm.release(evicted)
                    self._forget_probes(evicted)
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("eviction synchronize failed")

    async def start(self) -> None:
        await super().start()
        self._pleg_task = asyncio.get_running_loop().create_task(
            self._pleg_loop())
        self._probe_task = asyncio.get_running_loop().create_task(
            self._probe_loop())
        if self.eviction is not None:
            self._eviction_task = asyncio.get_running_loop().create_task(
                self._eviction_loop())
        if self.config_sync is not None:
            async def config_loop():
                while True:
                    await asyncio.sleep(self.EVICTION_PERIOD)
                    if not self.running:
                        return
                    try:
                        self.config_sync.sync()
                    except Exception:  # noqa: BLE001 — survive bad cfg
                        log.exception("kubelet config sync failed")

            self._config_task = asyncio.get_running_loop().create_task(
                config_loop())
        if self.serve_api:
            from kubernetes_tpu.agent.server import KubeletServer

            self.server = KubeletServer(self)
            await self.server.start()
            # publish the endpoint so the apiserver node proxy can find us
            # (kubelet_node_status.go sets DaemonEndpoints on registration).
            # CAS on the Node mutating ONLY daemonEndpoints — a blind
            # read-modify-write here raced concurrent Node writers over a
            # RemoteStore and could erase spec.podCIDR/volumesAttached
            # written between the GET and PUT
            port = self.server.port

            def mutate(node):
                node.status.daemon_endpoints = {
                    "kubeletEndpoint": {"Port": port}}
                return node

            try:
                self.store.guaranteed_update("Node", self.node_name,
                                             "default", mutate)
            except (Conflict, NotFound):
                pass

    def stop(self) -> None:
        super().stop()
        if self._pleg_task is not None:
            self._pleg_task.cancel()
            self._pleg_task = None
        if self._probe_task is not None:
            self._probe_task.cancel()
            self._probe_task = None
        if self._eviction_task is not None:
            self._eviction_task.cancel()
            self._eviction_task = None
        if self._config_task is not None:
            self._config_task.cancel()
            self._config_task = None
        if self.server is not None:
            self.server.close()
            self.server = None
        for key in list(self._worker_tasks):
            self._stop_worker(key)

    # the hollow ack path is superseded by the worker/status pipeline
    def ack_pod(self, pod: Pod) -> None:  # pragma: no cover - compat shim
        self.handle_pod("MODIFIED", pod)


class KubeletCluster:
    """N kubelets over one shared pod informer (the kubemark shape, with
    real kubelet loops instead of the hollow ack)."""

    def __init__(self, store: ObjectStore, n_nodes: int = 0,
                 name_prefix: str = "node", heartbeat_every: float = 10.0,
                 capacity: dict | None = None, serve_api: bool = False):
        self.store = store
        self.kubelets: dict[str, Kubelet] = {}
        self.pod_informer = Informer(store, "Pod")
        self.pod_informer.add_handler(self._on_pod)
        for i in range(n_nodes):
            name = f"{name_prefix}-{i}"
            self.kubelets[name] = Kubelet(
                store, name, heartbeat_every=heartbeat_every,
                capacity=capacity, serve_api=serve_api)

    def _on_pod(self, event) -> None:
        pod = event.obj
        if event.type == "DELETED":
            # route the removal to whichever kubelet runs it
            for kubelet in self.kubelets.values():
                if pod.key in kubelet._workers \
                        or pod.key in kubelet.runtime:
                    kubelet.handle_pod("DELETED", pod)
            return
        if not pod.spec.node_name:
            return
        kubelet = self.kubelets.get(pod.spec.node_name)
        if kubelet is not None and kubelet.running:
            kubelet.handle_pod(event.type, pod)

    async def start(self) -> None:
        self.pod_informer.start()
        for kubelet in self.kubelets.values():
            await kubelet.start()
        await self.pod_informer.wait_for_sync()
        for pod in self.pod_informer.items():
            if pod.spec.node_name:
                kubelet = self.kubelets.get(pod.spec.node_name)
                if kubelet is not None and kubelet.running:
                    kubelet.handle_pod("ADDED", pod)

    def stop(self, node_names=None) -> None:
        names = node_names if node_names is not None \
            else list(self.kubelets.keys())
        for name in names:
            self.kubelets[name].stop()
        if node_names is None:
            self.pod_informer.stop()
