"""Container manager: node-allocatable accounting + kubelet admission.

The pkg/kubelet/cm analog at hollow fidelity: no cgroups exist, so the
faithful model is the ACCOUNTING — which pods' requests fit inside node
allocatable, per QoS tier. The kubelet consults it before starting a pod
(canAdmitPod, kubelet.go:1548 + the GeneralPredicates admission check in
lifecycle/predicate.go): a pod whose requests no longer fit (the
scheduler raced a capacity change, or a static/mirror pod bypassed
scheduling) is REJECTED with the reference's OutOfcpu/OutOfmemory status
rather than silently overcommitted.
"""

from __future__ import annotations

from kubernetes_tpu.agent.eviction import qos_class
from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import NotFound


def pod_requests(pod) -> dict[str, float]:
    out = {"cpu": 0.0, "memory": 0.0}
    for c in pod.spec.containers:
        if "cpu" in c.requests:
            out["cpu"] += parse_quantity(c.requests["cpu"])
        if "memory" in c.requests:
            out["memory"] += parse_quantity(c.requests["memory"])
    return out


class ContainerManager:
    """Per-kubelet allocatable ledger (container_manager_linux.go's
    NodeAllocatable view): active pods' requests, grouped by QoS tier for
    observability, checked against node allocatable at admission."""

    def __init__(self, store, node_name: str):
        self.store = store
        self.node_name = node_name
        self._active: dict[str, dict[str, float]] = {}  # key -> requests
        self._qos: dict[str, str] = {}                   # key -> class

    def _allocatable(self) -> dict[str, float]:
        try:
            node = self.store.get("Node", self.node_name, "default")
        except NotFound:
            return {}
        alloc = node.status.allocatable
        out = {}
        for res in ("cpu", "memory"):
            if res in alloc:
                out[res] = parse_quantity(str(alloc[res]))
        return out

    def admit(self, pod) -> str | None:
        """None = admitted (and accounted); else the rejection reason
        (OutOfcpu / OutOfmemory — kubelet.go's canAdmitPod message)."""
        if pod.key in self._active:
            return None  # already running here: resync, not re-admission
        alloc = self._allocatable()
        want = pod_requests(pod)
        used = {"cpu": 0.0, "memory": 0.0}
        for reqs in self._active.values():
            used["cpu"] += reqs["cpu"]
            used["memory"] += reqs["memory"]
        for res in ("cpu", "memory"):
            cap = alloc.get(res)
            if cap is not None and used[res] + want[res] > cap:
                return f"OutOf{res}"
        self._active[pod.key] = want
        self._qos[pod.key] = qos_class(pod)
        return None

    def release(self, key: str) -> None:
        self._active.pop(key, None)
        self._qos.pop(key, None)

    def qos_usage(self) -> dict[str, dict[str, float]]:
        """Aggregate requests per QoS tier (the cm's pod-tier cgroup
        accounting surface, observability for tests/metrics)."""
        out: dict[str, dict[str, float]] = {}
        for key, reqs in self._active.items():
            tier = out.setdefault(self._qos.get(key, "BestEffort"),
                                  {"cpu": 0.0, "memory": 0.0})
            tier["cpu"] += reqs["cpu"]
            tier["memory"] += reqs["memory"]
        return out
