"""Kubelet eviction manager: pressure detection, QoS-ranked eviction,
node-condition feedback.

The pkg/kubelet/eviction analog (eviction_manager.go:213 `synchronize`:
observe usage, compare against thresholds, update node conditions with a
transition period, rank candidate pods, evict ONE victim per pass;
ranking in helpers.go — BestEffort first, then Burstable pods over their
requests, Guaranteed last).

Signals at hollow fidelity: the fake runtime has no cgroups, so per-pod
usage comes from annotations (``kubernetes-tpu/memory-usage-mib`` /
``kubernetes-tpu/disk-usage-mib``), defaulting to the pod's requests —
the same shape kubemark's fake stats provider takes. Node capacity comes
from the Node object's allocatable.

The conditions this manager raises (MemoryPressure / DiskPressure) are
exactly what the scheduler's CheckNodeMemoryPressure /
CheckNodeDiskPressure predicate kernels consume (ops/predicates.py), so
the full loop closes: pressure -> evict -> scheduler avoids the node ->
pressure clears -> (after the transition period) schedulable again.
"""

from __future__ import annotations

import logging
import time

from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore

log = logging.getLogger(__name__)

MEMORY_USAGE_ANNOTATION = "kubernetes-tpu/memory-usage-mib"
DISK_USAGE_ANNOTATION = "kubernetes-tpu/disk-usage-mib"
MIB = 1024 * 1024


def qos_class(pod) -> str:
    """PodQOSClass (pkg/api/v1/helper/qos/qos.go GetPodQOS): Guaranteed
    when every container sets limits == requests for cpu+memory;
    BestEffort when nothing is set; Burstable otherwise."""
    if pod.is_best_effort():
        return "BestEffort"
    for c in pod.spec.containers:
        for res in ("cpu", "memory"):
            req = c.requests.get(res)
            lim = c.limits.get(res)
            if req is None or lim is None \
                    or parse_quantity(req) != parse_quantity(lim):
                return "Burstable"
    return "Guaranteed"


def pod_memory_usage_mib(pod) -> float:
    """Observed memory at hollow fidelity: the usage annotation, else the
    summed container requests."""
    ann = pod.metadata.annotations.get(MEMORY_USAGE_ANNOTATION)
    if ann:
        return float(ann)
    total = 0.0
    for c in pod.spec.containers:
        if "memory" in c.requests:
            total += parse_quantity(c.requests["memory"]) / MIB
    return total


def pod_disk_usage_mib(pod) -> float:
    ann = pod.metadata.annotations.get(DISK_USAGE_ANNOTATION)
    return float(ann) if ann else 0.0


def _rank_key(pod, signal: str):
    """Eviction order (helpers.go rankMemoryPressure / rankDiskPressure —
    the ranker is PER SIGNAL): BestEffort first, then Burstable consuming
    above requests, then the rest; within a tier the largest consumer OF
    THE PRESSURED RESOURCE goes first (a memory ranking under disk
    pressure would evict bystanders while the disk hog survives)."""
    cls = qos_class(pod)
    if signal == "DiskPressure":
        usage = pod_disk_usage_mib(pod)
        requests = 0.0  # no disk requests at this vintage: usage>0 = over
    else:
        usage = pod_memory_usage_mib(pod)
        requests = 0.0
        for c in pod.spec.containers:
            if "memory" in c.requests:
                requests += parse_quantity(c.requests["memory"]) / MIB
    if cls == "BestEffort":
        tier = 0
    elif cls == "Burstable" and usage > requests:
        tier = 1
    else:
        tier = 2
    return (tier, -usage)


class EvictionManager:
    """One kubelet's eviction loop state. `synchronize()` is called by the
    kubelet on its monitor period (Kubelet._eviction_loop)."""

    def __init__(self, store: ObjectStore, node_name: str,
                 memory_available_mib: float = 0.0,
                 disk_available_mib: float = 0.0,
                 pressure_transition_period: float = 5.0,
                 runtime=None):
        self.store = store
        self.node_name = node_name
        # hard thresholds (--eviction-hard memory.available<X,
        # nodefs.available<Y); 0 disables the signal
        self.memory_available_mib = memory_available_mib
        self.disk_available_mib = disk_available_mib
        # hysteresis (--eviction-pressure-transition-period, default 5m):
        # a condition only CLEARS after staying below threshold this long
        self.transition_period = pressure_transition_period
        self.runtime = runtime
        self._last_observed_over: dict[str, float] = {}
        # last condition (status, reason) written per type: the Node is
        # only touched when something CHANGES — a write per monitor pass
        # would emit ~10 Node events/s/node and flood every informer
        self._written: dict[str, tuple] = {}
        self.evicted: list[str] = []

    # ---- observation ----

    def _node_allocatable_mib(self, resource: str) -> float:
        try:
            node = self.store.get("Node", self.node_name, "default")
        except NotFound:
            return 0.0
        raw = node.status.allocatable.get(resource)
        if raw is None:
            return 0.0
        return parse_quantity(str(raw)) / MIB

    def _my_pods(self):
        return [p for p in self.store.list("Pod", copy_objects=False)
                if p.spec.node_name == self.node_name
                and p.status.phase not in ("Succeeded", "Failed")]

    def observe(self) -> dict[str, float]:
        """available MiB per signal (summary API stand-in)."""
        pods = self._my_pods()
        mem_cap = self._node_allocatable_mib("memory")
        mem_used = sum(pod_memory_usage_mib(p) for p in pods)
        disk_cap = self._node_allocatable_mib(
            "storage.kubernetes.io/scratch")
        disk_used = sum(pod_disk_usage_mib(p) for p in pods)
        return {"MemoryPressure": mem_cap - mem_used,
                "DiskPressure": disk_cap - disk_used}

    # ---- the synchronize pass (eviction_manager.go:213) ----

    def synchronize(self) -> str | None:
        """One pass: update conditions, evict at most one pod. Returns the
        evicted pod key, if any."""
        thresholds = {"MemoryPressure": self.memory_available_mib,
                      "DiskPressure": self.disk_available_mib}
        available = self.observe()
        now = time.monotonic()
        under = {}
        for cond, threshold in thresholds.items():
            if threshold <= 0:
                under[cond] = False
                continue
            if available[cond] < threshold:
                under[cond] = True
                self._last_observed_over[cond] = now
            else:
                under[cond] = False
                # hysteresis: stay "under pressure" until the transition
                # period has passed since the last under-threshold reading
                last = self._last_observed_over.get(cond)
                if last is not None \
                        and now - last < self.transition_period:
                    under[cond] = True
        self._write_conditions(under)
        # evict only while a signal is ACTUALLY under threshold — the
        # hysteresis tail keeps the condition up (scheduler keeps avoiding
        # the node) but must not keep killing recovered workloads
        for cond in ("MemoryPressure", "DiskPressure"):
            if thresholds[cond] > 0 and available[cond] < thresholds[cond]:
                return self._evict_one(cond)
        return None

    def _evict_one(self, signal: str = "MemoryPressure") -> str | None:
        candidates = sorted(self._my_pods(),
                            key=lambda p: _rank_key(p, signal))
        if not candidates:
            return None
        victim = candidates[0]
        key = victim.key

        def fail(obj):
            obj.status.phase = "Failed"
            obj.status.reason = "Evicted"
            obj.status.message = ("The node was low on resource: "
                                  "memory/ephemeral-storage.")
            return obj

        try:
            self.store.guaranteed_update(
                "Pod", victim.metadata.name,
                victim.metadata.namespace, fail)
        except (NotFound, Conflict):
            return None
        if self.runtime is not None:
            self.runtime.kill_pod(key)
        self.evicted.append(key)
        log.info("evicted %s (%s) under pressure", key, qos_class(victim))
        return key

    def _write_conditions(self, under: dict[str, bool]) -> None:
        from kubernetes_tpu.api.objects import NodeCondition

        wanted = {c: ("True" if u else "False") for c, u in under.items()}
        if all(self._written.get(c) == w for c, w in wanted.items()):
            return  # nothing flipped: don't spam Node watch events
        now = time.time()

        def mutate(node):
            for cond_type, is_under in under.items():
                want = "True" if is_under else "False"
                reason = ("KubeletHasInsufficientMemory"
                          if cond_type == "MemoryPressure"
                          else "KubeletHasDiskPressure") if is_under else (
                    "KubeletHasSufficientMemory"
                    if cond_type == "MemoryPressure"
                    else "KubeletHasNoDiskPressure")
                existing = None
                for c in node.status.conditions:
                    if c.type == cond_type:
                        existing = c
                if existing is None:
                    existing = NodeCondition(type=cond_type, status="")
                    node.status.conditions.append(existing)
                if existing.status != want:
                    existing.last_transition_time = now
                existing.status = want
                existing.reason = reason
                existing.last_heartbeat_time = now
            return node

        try:
            self.store.guaranteed_update("Node", self.node_name, "default",
                                         mutate)
            self._written.update(wanted)
        except (Conflict, NotFound):
            pass
