"""Volume plugin SPI + kubelet volume manager.

The pkg/volume analog: a `VolumePlugin` SPI (plugins.go VolumePlugin/
Mounter) with the built-in drivers a pod spec can name — emptyDir,
hostPath, secret, configMap, downwardAPI, and persistentVolumeClaim —
plus the kubelet-side `VolumeManager` (volumemanager/reconciler/
reconciler.go:165): mount every volume a pod declares before its
containers start, unmount when the pod goes away. "Mount" here populates
an in-memory mount table (the kubemark-fidelity stand-in for bind mounts);
what is real is the control flow: secret/configMap content is resolved
from the API at mount time (a missing Secret blocks pod start, exactly the
reference's MountVolume error path), and a PVC volume requires the claim
to be Bound and the underlying PV attached to this node
(operation_executor WaitForAttach) before it mounts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.apiserver.store import NotFound, ObjectStore


class MountError(Exception):
    """MountVolume failure — the pod must not start (reconciler retries)."""


@dataclass
class Mount:
    volume_name: str
    plugin: str
    path: str
    data: dict[str, Any] = field(default_factory=dict)


class EmptyDirPlugin:
    """pkg/volume/empty_dir: fresh scratch space per pod."""

    name = "emptyDir"

    def supports(self, vol: dict) -> bool:
        return "emptyDir" in vol

    def mount(self, pod: Pod, vol: dict, node_name: str) -> Mount:
        return Mount(vol["name"], self.name,
                     f"/var/lib/kubelet/pods/{pod.metadata.uid}/volumes/"
                     f"emptydir/{vol['name']}")


class HostPathPlugin:
    """pkg/volume/host_path: the node path itself."""

    name = "hostPath"

    def supports(self, vol: dict) -> bool:
        return "hostPath" in vol

    def mount(self, pod: Pod, vol: dict, node_name: str) -> Mount:
        path = (vol.get("hostPath") or {}).get("path", "")
        if not path:
            raise MountError(f"hostPath volume {vol['name']}: empty path")
        return Mount(vol["name"], self.name, path)


class SecretPlugin:
    """pkg/volume/secret: projects Secret data; a missing Secret is a
    mount failure, not an empty dir."""

    name = "secret"
    kind = "Secret"
    spec_key = "secret"
    ref_key = "secretName"

    def __init__(self, store: ObjectStore):
        self.store = store

    def supports(self, vol: dict) -> bool:
        return self.spec_key in vol

    def mount(self, pod: Pod, vol: dict, node_name: str) -> Mount:
        ref = (vol.get(self.spec_key) or {}).get(self.ref_key, "")
        try:
            obj = self.store.get(self.kind, ref, pod.metadata.namespace)
        except NotFound:
            raise MountError(
                f"{self.kind.lower()} {ref!r} not found for volume "
                f"{vol['name']}") from None
        return Mount(vol["name"], self.name,
                     f"/var/lib/kubelet/pods/{pod.metadata.uid}/volumes/"
                     f"{self.name}/{vol['name']}",
                     data=dict(obj.data))


class ConfigMapPlugin(SecretPlugin):
    """pkg/volume/configmap — same projection over ConfigMaps."""

    name = "configMap"
    kind = "ConfigMap"
    spec_key = "configMap"
    ref_key = "name"


class DownwardAPIPlugin:
    """pkg/volume/downwardapi: project pod metadata fields."""

    name = "downwardAPI"

    def supports(self, vol: dict) -> bool:
        return "downwardAPI" in vol

    def mount(self, pod: Pod, vol: dict, node_name: str) -> Mount:
        data = {}
        for item in (vol.get("downwardAPI") or {}).get("items") or []:
            fieldpath = (item.get("fieldRef") or {}).get("fieldPath", "")
            value = {"metadata.name": pod.metadata.name,
                     "metadata.namespace": pod.metadata.namespace,
                     "metadata.uid": pod.metadata.uid,
                     "spec.nodeName": pod.spec.node_name,
                     }.get(fieldpath)
            if value is None:
                raise MountError(f"downwardAPI volume {vol['name']}: "
                                 f"unsupported fieldPath {fieldpath!r}")
            data[item.get("path", fieldpath)] = value
        return Mount(vol["name"], self.name,
                     f"/var/lib/kubelet/pods/{pod.metadata.uid}/volumes/"
                     f"downwardapi/{vol['name']}", data=data)


class PVCPlugin:
    """pkg/volume/persistent_claim + WaitForAttach: the claim must be
    Bound, and the bound PV attached to this node (by the attach/detach
    controller) before the mount proceeds."""

    name = "persistentVolumeClaim"

    def __init__(self, store: ObjectStore, require_attach: bool = True):
        self.store = store
        self.require_attach = require_attach

    def supports(self, vol: dict) -> bool:
        return "persistentVolumeClaim" in vol

    def mount(self, pod: Pod, vol: dict, node_name: str) -> Mount:
        claim = (vol.get("persistentVolumeClaim") or {}).get("claimName", "")
        try:
            pvc = self.store.get("PersistentVolumeClaim", claim,
                                 pod.metadata.namespace)
        except NotFound:
            raise MountError(f"claim {claim!r} not found") from None
        if not pvc.volume_name:
            raise MountError(f"claim {claim!r} is not bound")
        if self.require_attach:
            from kubernetes_tpu.controllers.volume import _attached_name

            try:
                node = self.store.get("Node", node_name)
            except NotFound:
                raise MountError(f"node {node_name!r} not found") from None
            want = _attached_name(pvc.volume_name)
            if not any(a.get("name") == want
                       for a in node.status.volumes_attached):
                raise MountError(
                    f"volume {pvc.volume_name!r} not yet attached to "
                    f"{node_name}")
        return Mount(vol["name"], self.name,
                     f"/var/lib/kubelet/pods/{pod.metadata.uid}/volumes/"
                     f"pv/{pvc.volume_name}",
                     data={"pv": pvc.volume_name})


class CloudDiskPlugin:
    """The attachable-cloud family (pkg/volume/gce_pd, aws_ebs,
    azure_dd): an inline cloud-disk volume must ATTACH to this instance
    through the cloud provider before it mounts (attacher.go Attach +
    WaitForAttach collapsed to the synchronous fake). Single-writer
    semantics ride the cloud: a disk attached read-write elsewhere fails
    the mount, and the reconciler retries until it detaches."""

    source_key = ""     # pod-spec volume source field
    disk_field = ""     # the disk-name field inside the source

    def __init__(self, cloud):
        self.cloud = cloud

    def supports(self, vol: dict) -> bool:
        return self.source_key in vol

    def mount(self, pod: Pod, vol: dict, node_name: str) -> Mount:
        src = vol[self.source_key] or {}
        disk = src.get(self.disk_field, "")
        if not disk:
            raise MountError(f"{self.source_key} volume "
                             f"{vol.get('name')!r} names no disk")
        if self.cloud is None:
            raise MountError(
                f"{self.source_key}: no cloud provider configured")
        try:
            self.cloud.attach_disk(disk, node_name,
                                   read_only=bool(src.get("readOnly")))
        except RuntimeError as e:
            raise MountError(str(e)) from None
        return Mount(vol["name"], self.source_key,
                     f"/var/lib/kubelet/pods/{pod.metadata.uid}/volumes/"
                     f"{self.source_key}/{disk}",
                     data={"disk": disk})

    def unmount(self, mount: Mount, node_name: str) -> None:
        # release the single-writer lock so a rescheduled pod can attach
        # the disk on its new node (detacher.go Detach)
        self.cloud.detach_disk(mount.data.get("disk", ""), node_name)


class GCEPersistentDiskPlugin(CloudDiskPlugin):
    name = source_key = "gcePersistentDisk"
    disk_field = "pdName"


class AWSElasticBlockStorePlugin(CloudDiskPlugin):
    name = source_key = "awsElasticBlockStore"
    disk_field = "volumeID"


class AzureDiskPlugin(CloudDiskPlugin):
    name = source_key = "azureDisk"
    disk_field = "diskName"


def default_plugins(store: ObjectStore,
                    require_attach: bool = True, cloud=None) -> list:
    plugins = [EmptyDirPlugin(), HostPathPlugin(), SecretPlugin(store),
               ConfigMapPlugin(store), DownwardAPIPlugin(),
               PVCPlugin(store, require_attach=require_attach)]
    if cloud is not None:
        plugins += [GCEPersistentDiskPlugin(cloud),
                    AWSElasticBlockStorePlugin(cloud),
                    AzureDiskPlugin(cloud)]
    return plugins


class VolumeManager:
    """Desired/actual mount worlds for one kubelet (volumemanager/
    volume_manager.go WaitForAttachAndMount, collapsed to synchronous
    mounts over fakes)."""

    def __init__(self, store: ObjectStore, node_name: str,
                 plugins: list | None = None, require_attach: bool = True,
                 cloud=None):
        self.node_name = node_name
        self.plugins = plugins if plugins is not None else default_plugins(
            store, require_attach=require_attach, cloud=cloud)
        self._mounts: dict[str, list[Mount]] = {}  # pod key -> mounts

    def _plugin_for(self, vol: dict):
        for plugin in self.plugins:
            if plugin.supports(vol):
                return plugin
        return None

    def mount_pod(self, pod: Pod) -> list[Mount]:
        """Mount every declared volume or raise MountError (all-or-nothing:
        a pod with any unmountable volume must not start). A failure part
        way through rolls the earlier mounts back before raising — a cloud
        disk attached for a pod that never starts would otherwise hold its
        single-writer lock (and the attach) until a pod with the same key
        was deleted on this exact node."""
        mounts: list[Mount] = []
        for vol in pod.spec.volumes:
            plugin = self._plugin_for(vol)
            if plugin is None:
                self._unmount_all(mounts)
                raise MountError(
                    f"no plugin for volume {vol.get('name')!r} "
                    f"(sources: {sorted(k for k in vol if k != 'name')})")
            try:
                mounts.append(plugin.mount(pod, vol, self.node_name))
            except Exception:
                self._unmount_all(mounts)
                raise
        self._mounts[pod.key] = mounts
        return mounts

    def _unmount_all(self, mounts: list[Mount]) -> None:
        """Best-effort teardown of a mount list, newest first (the partial
        set never entered the mount table, so unmount_pod can't reach it)."""
        for mount in reversed(mounts):
            plugin = next((p for p in self.plugins
                           if getattr(p, "name", "") == mount.plugin), None)
            if plugin is None or not hasattr(plugin, "unmount"):
                continue
            try:
                plugin.unmount(mount, self.node_name)
            except Exception:  # noqa: BLE001 — rollback must not mask
                pass           # the original mount failure

    def unmount_pod(self, pod_key: str) -> None:
        self._unmount_all(self._mounts.pop(pod_key, []))

    def mounts(self, pod_key: str) -> list[Mount]:
        return list(self._mounts.get(pod_key, ()))
