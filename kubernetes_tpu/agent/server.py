"""Kubelet API server: the :10250 surface (logs, exec, running pods).

Analog of pkg/kubelet/server: the kubelet exposes a small HTTP API the
apiserver proxies to (`kubectl logs/exec` ride apiserver -> node proxy ->
kubelet, the reference's SPDY remotecommand path collapsed to plain
chunked HTTP — same topology, simpler framing):

  GET  /containerLogs/{ns}/{pod}/{container}[?follow=true]
  POST /exec/{ns}/{pod}/{container}?command=<json list>
  POST /exec/{ns}/{pod}/{container}   + Upgrade  (interactive streaming)
  POST /portForward/{ns}/{pod}?port=N + Upgrade  (byte tunnel)
  GET  /runningpods/              (debug handler, server.go)
  GET  /healthz

Log following streams chunked lines as the runtime appends them — the
`kubectl logs -f` experience over the fake runtime. The Upgrade flows
speak the channel framing of client/remotecommand.py (the SPDY
remotecommand/portforward analog, pkg/kubelet/server/remotecommand):
stdin lines run through the fake shell with stdout/stderr framed back and
an exit status on the error channel; port-forward relays bytes to the
pod's port backend (an echo service by default, or a real TCP target
named by the `kubernetes-tpu/port-map` annotation — {"8080":
"tcp:host:port"}).
"""

from __future__ import annotations

import asyncio
import json
import logging
from urllib.parse import parse_qs, urlsplit

log = logging.getLogger(__name__)


class KubeletServer:
    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0):
        self.kubelet = kubelet
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def close(self) -> None:
        """Synchronous shutdown (for callers outside the loop — the
        kubelet's stop() path); sockets close, no wait for in-flight
        handlers."""
        if self._server is not None:
            self._server.close()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        from kubernetes_tpu.apiserver.http import read_http_request

        try:
            try:
                parsed = await read_http_request(reader)
            except ValueError:
                await self._respond(writer, 400, b"bad request")
                return
            if parsed is None:
                return
            method, target, headers, _body = parsed
            url = urlsplit(target)
            query = {k: v[-1] for k, v in parse_qs(url.query).items()}
            if headers.get("upgrade"):
                await self._route_upgrade(reader, writer, method, url.path,
                                          query)
                return
            await self._route(writer, method, url.path, query)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _route(self, writer, method: str, path: str,
                     query: dict) -> None:
        from kubernetes_tpu.obs import metrics as obs_metrics
        from kubernetes_tpu.obs.http import obs_response

        obs = obs_response(
            method, "/" + path.strip("/"),
            registry=obs_metrics.REGISTRY,
            ready_checks={
                "syncing": lambda: getattr(self.kubelet, "running", True)})
        if obs is not None:
            status, body, ctype = obs
            await self._respond(writer, status, body, content_type=ctype)
            return
        parts = [p for p in path.strip("/").split("/") if p]
        if parts == ["runningpods"]:
            pods = sorted(self.kubelet.runtime.list_pods())
            await self._respond(writer, 200,
                                json.dumps({"pods": pods}).encode())
            return
        if parts == ["stats", "summary"] and method == "GET":
            # the metrics-server resource pipeline's source: node + per-pod
            # usage, scraped by the Monitor into node_*/pod_* series
            summary = self.kubelet.stats_summary()
            await self._respond(writer, 200, json.dumps(summary).encode(),
                                content_type="application/json")
            return
        if len(parts) == 4 and parts[0] == "containerLogs" \
                and method == "GET":
            _, ns, pod, _container = parts
            await self._serve_logs(writer, f"{ns}/{pod}",
                                   follow=query.get("follow") in
                                   ("1", "true"))
            return
        if len(parts) == 4 and parts[0] == "exec" and method == "POST":
            _, ns, pod, _container = parts
            try:
                command = json.loads(query.get("command", "[]"))
            except ValueError:
                command = []
            if not isinstance(command, list) or not command:
                await self._respond(writer, 400, b"command required")
                return
            code, output = self.kubelet.runtime.exec_sync(
                f"{ns}/{pod}", [str(c) for c in command])
            await self._respond(
                writer, 200,
                json.dumps({"exitCode": code, "output": output}).encode())
            return
        await self._respond(writer, 404, b"not found")

    async def _serve_logs(self, writer, key: str, follow: bool) -> None:
        runtime = self.kubelet.runtime
        lines = runtime.read_logs(key)
        if not follow:
            body = "".join(f"{ln}\n" for ln in lines).encode()
            await self._respond(writer, 200, body)
            return
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/plain\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n")
        sent = 0
        try:
            while True:
                lines = runtime.read_logs(key)
                for ln in lines[sent:]:
                    chunk = f"{ln}\n".encode()
                    writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                                 + b"\r\n")
                sent = len(lines)
                await writer.drain()
                if key not in runtime:  # sandbox gone: stream ends
                    break
                await asyncio.sleep(0.05)
        except (ConnectionError, asyncio.CancelledError):
            return
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    # ---- upgraded streams (remotecommand/portforward analog) ----

    async def _route_upgrade(self, reader, writer, method: str, path: str,
                             query: dict) -> None:
        parts = [p for p in path.strip("/").split("/") if p]
        if len(parts) == 4 and parts[0] == "exec" and method == "POST":
            key = f"{parts[1]}/{parts[2]}"
            await self._accept_upgrade(writer)
            await self._exec_session(reader, writer, key)
            return
        if len(parts) == 3 and parts[0] == "portForward" \
                and method == "POST":
            key = f"{parts[1]}/{parts[2]}"
            try:
                port = int(query.get("port", 0))
            except ValueError:
                port = 0
            await self._accept_upgrade(writer)
            await self._portforward_session(reader, writer, key, port)
            return
        await self._respond(writer, 404, b"not found")

    @staticmethod
    async def _accept_upgrade(writer) -> None:
        from kubernetes_tpu.client.remotecommand import UPGRADE_HEADER

        writer.write(f"HTTP/1.1 101 Switching Protocols\r\n"
                     f"Upgrade: {UPGRADE_HEADER}\r\n"
                     f"Connection: Upgrade\r\n\r\n".encode())
        await writer.drain()

    async def _exec_session(self, reader, writer, key: str) -> None:
        """Interactive shell: each stdin LINE runs through the fake
        runtime's exec; `exit` (or stdin EOF) ends the session with the
        last command's exit code on the error channel."""
        import shlex

        from kubernetes_tpu.client import remotecommand as rc

        runtime = self.kubelet.runtime
        buffer = b""
        last_code = 0

        async def run_line(line: bytes) -> None:
            nonlocal last_code
            text = line.decode(errors="replace").strip()
            if not text:
                return
            if text == "exit":
                raise EOFError
            try:
                argv = shlex.split(text)
            except ValueError as e:
                writer.write(rc.frame(
                    rc.STDERR, f"parse error: {e}\n".encode()))
                last_code = 2
                return
            code, output = runtime.exec_sync(key, argv)
            last_code = code
            target = rc.STDOUT if code == 0 else rc.STDERR
            writer.write(rc.frame(target, output.encode()))
            await writer.drain()

        try:
            while True:
                got = await rc.read_frame(reader)
                if got is None:
                    break
                channel, payload = got
                if channel != rc.STDIN:
                    continue
                if not payload:
                    # stdin EOF: a residual line without a trailing newline
                    # still runs (printf 'cmd' | exec -i must not no-op)
                    if buffer:
                        await run_line(buffer)
                        buffer = b""
                    break
                buffer += payload
                while b"\n" in buffer:
                    line, _, buffer = buffer.partition(b"\n")
                    await run_line(line)
        except (EOFError, ConnectionError, asyncio.CancelledError):
            pass
        try:
            writer.write(rc.frame(rc.ERROR, json.dumps(
                {"exitCode": last_code}).encode()))
            await writer.drain()
        except ConnectionError:
            pass

    async def _portforward_session(self, reader, writer, key: str,
                                   port: int) -> None:
        """Relay STDIN frames to the pod's port backend and its bytes back
        as STDOUT frames. Backend resolution: the pod's
        kubernetes-tpu/port-map annotation may name "tcp:host:port" for a
        real TCP target; anything else (or no entry) is the built-in echo
        service — enough to prove the tunnel end to end over fakes."""
        from kubernetes_tpu.apiserver.store import NotFound
        from kubernetes_tpu.client import remotecommand as rc

        ns, name = key.split("/", 1)
        target = ""
        try:
            pod = self.kubelet.store.get("Pod", name, ns)
            port_map = json.loads(pod.metadata.annotations.get(
                "kubernetes-tpu/port-map", "{}"))
            target = str(port_map.get(str(port), ""))
        except (NotFound, ValueError):
            pass
        up_reader = up_writer = None
        if target.startswith("tcp:"):
            _, host, tcp_port = target.split(":", 2)
            try:
                up_reader, up_writer = await asyncio.wait_for(
                    asyncio.open_connection(host, int(tcp_port)), 5.0)
            except (OSError, asyncio.TimeoutError, ValueError):
                writer.write(rc.frame(rc.ERROR, json.dumps(
                    {"error": f"dial {target} failed"}).encode()))
                await writer.drain()
                return

        async def downstream():
            if up_reader is None:
                return
            while True:
                data = await up_reader.read(65536)
                if not data:
                    break
                writer.write(rc.frame(rc.STDOUT, data))
                await writer.drain()
            writer.write(rc.frame(rc.STDOUT, b""))
            await writer.drain()

        down_task = asyncio.get_running_loop().create_task(downstream())
        try:
            while True:
                got = await rc.read_frame(reader)
                if got is None:
                    break
                channel, payload = got
                if channel != rc.STDIN:
                    continue
                if not payload:
                    break
                if up_writer is not None:
                    up_writer.write(payload)
                    await up_writer.drain()
                else:
                    # echo backend: prove the tunnel without a real server
                    writer.write(rc.frame(rc.STDOUT, payload))
                    await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            down_task.cancel()
            if up_writer is not None:
                up_writer.close()
            try:
                writer.write(rc.frame(rc.ERROR, b"{}"))
                await writer.drain()
            except ConnectionError:
                pass

    @staticmethod
    async def _respond(writer, status: int, body: bytes,
                       content_type: str = "text/plain") -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed",
                  503: "Service Unavailable"}.get(status, "?")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body)
        await writer.drain()
