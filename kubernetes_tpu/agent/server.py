"""Kubelet API server: the :10250 surface (logs, exec, running pods).

Analog of pkg/kubelet/server: the kubelet exposes a small HTTP API the
apiserver proxies to (`kubectl logs/exec` ride apiserver -> node proxy ->
kubelet, the reference's SPDY remotecommand path collapsed to plain
chunked HTTP — same topology, simpler framing):

  GET  /containerLogs/{ns}/{pod}/{container}[?follow=true]
  POST /exec/{ns}/{pod}/{container}?command=<json list>
  GET  /runningpods/              (debug handler, server.go)
  GET  /healthz

Log following streams chunked lines as the runtime appends them — the
`kubectl logs -f` experience over the fake runtime.
"""

from __future__ import annotations

import asyncio
import json
import logging
from urllib.parse import parse_qs, urlsplit

log = logging.getLogger(__name__)


class KubeletServer:
    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0):
        self.kubelet = kubelet
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def close(self) -> None:
        """Synchronous shutdown (for callers outside the loop — the
        kubelet's stop() path); sockets close, no wait for in-flight
        handlers."""
        if self._server is not None:
            self._server.close()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        from kubernetes_tpu.apiserver.http import read_http_request

        try:
            try:
                parsed = await read_http_request(reader)
            except ValueError:
                await self._respond(writer, 400, b"bad request")
                return
            if parsed is None:
                return
            method, target, _headers, _body = parsed
            url = urlsplit(target)
            query = {k: v[-1] for k, v in parse_qs(url.query).items()}
            await self._route(writer, method, url.path, query)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _route(self, writer, method: str, path: str,
                     query: dict) -> None:
        parts = [p for p in path.strip("/").split("/") if p]
        if parts == ["healthz"]:
            await self._respond(writer, 200, b"ok")
            return
        if parts == ["runningpods"]:
            pods = sorted(self.kubelet.runtime.list_pods())
            await self._respond(writer, 200,
                                json.dumps({"pods": pods}).encode())
            return
        if len(parts) == 4 and parts[0] == "containerLogs" \
                and method == "GET":
            _, ns, pod, _container = parts
            await self._serve_logs(writer, f"{ns}/{pod}",
                                   follow=query.get("follow") in
                                   ("1", "true"))
            return
        if len(parts) == 4 and parts[0] == "exec" and method == "POST":
            _, ns, pod, _container = parts
            try:
                command = json.loads(query.get("command", "[]"))
            except ValueError:
                command = []
            if not isinstance(command, list) or not command:
                await self._respond(writer, 400, b"command required")
                return
            code, output = self.kubelet.runtime.exec_sync(
                f"{ns}/{pod}", [str(c) for c in command])
            await self._respond(
                writer, 200,
                json.dumps({"exitCode": code, "output": output}).encode())
            return
        await self._respond(writer, 404, b"not found")

    async def _serve_logs(self, writer, key: str, follow: bool) -> None:
        runtime = self.kubelet.runtime
        lines = runtime.read_logs(key)
        if not follow:
            body = "".join(f"{ln}\n" for ln in lines).encode()
            await self._respond(writer, 200, body)
            return
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/plain\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n")
        sent = 0
        try:
            while True:
                lines = runtime.read_logs(key)
                for ln in lines[sent:]:
                    chunk = f"{ln}\n".encode()
                    writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                                 + b"\r\n")
                sent = len(lines)
                await writer.drain()
                if key not in runtime:  # sandbox gone: stream ends
                    break
                await asyncio.sleep(0.05)
        except (ConnectionError, asyncio.CancelledError):
            return
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    @staticmethod
    async def _respond(writer, status: int, body: bytes) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found"}.get(
            status, "?")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: text/plain\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body)
        await writer.drain()
