"""L6 node agent: the hollow kubelet (kubemark-style) node plane."""

from kubernetes_tpu.agent.hollow import HollowCluster, HollowKubelet

__all__ = ["HollowCluster", "HollowKubelet"]
