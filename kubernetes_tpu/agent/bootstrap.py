"""Kubelet TLS bootstrap: bootstrap token -> CSR -> signed cert -> mTLS.

The pkg/kubelet/certificate + bootstrap flow (reference
pkg/kubelet/kubeletconfig/../certificate/bootstrap/bootstrap.go:60
LoadClientCert): a kubelet that only holds a cluster-join bootstrap token
creates a CertificateSigningRequest with CN=system:node:<name>,
O=system:nodes, waits for the approve/sign controllers
(controllers/certificates.py) to issue status.certificate, writes the key
pair to disk, and reconnects with the client certificate as its identity.
From then on the apiserver's X509Authenticator resolves it to
system:node:<name> and the NodeAuthorizer scopes what it may touch.

Key generation and CSR creation use the openssl binary — the same native
boundary the signing controller uses.
"""

from __future__ import annotations

import base64
import subprocess
import time

from kubernetes_tpu.api.objects import CertificateSigningRequest

NODE_USER_PREFIX = "system:node:"
NODES_GROUP = "system:nodes"


def make_node_csr(node_name: str, workdir: str) -> tuple[str, bytes]:
    """Generate a key + CSR for the node identity.

    Returns (key_file_path, csr_pem). Subject is exactly what the node
    authorizer expects: CN=system:node:<name>, O=system:nodes
    (bootstrap.go:132 builds the same subject)."""
    key_file = f"{workdir}/kubelet-{node_name}.key"
    csr_file = f"{workdir}/kubelet-{node_name}.csr"
    subj = f"/O={NODES_GROUP}/CN={NODE_USER_PREFIX}{node_name}"
    subprocess.run(
        ["openssl", "req", "-new", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key_file, "-out", csr_file, "-subj", subj],
        check=True, capture_output=True, timeout=60)
    with open(csr_file, "rb") as f:
        return key_file, f.read()


def bootstrap_node_cert(client, node_name: str, workdir: str,
                        timeout: float = 30.0,
                        poll: float = 0.2) -> tuple[str, str]:
    """Drive the full bootstrap against a (bootstrap-token) API client.

    `client` is any store-shaped client (RemoteStore or ObjectStore).
    Returns (cert_file, key_file) ready for RemoteStore(cert_file=...,
    key_file=...). Raises TimeoutError if the controllers never issue."""
    key_file, csr_pem = make_node_csr(node_name, workdir)
    name = f"node-csr-{node_name}"
    # Over HTTP the apiserver STAMPS spec.username/groups from the
    # authenticated requester (strategy.go:45), overwriting these values;
    # they only take effect for the in-process ObjectStore topology, where
    # there is no authenticated identity to stamp from.
    csr = CertificateSigningRequest.from_dict({
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "request": base64.b64encode(csr_pem).decode(),
            "username": "kubelet-bootstrap",
            "groups": ["system:bootstrappers"],
            "usages": ["digital signature", "key encipherment",
                       "client auth"],
        }})
    client.create(csr)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        obj = client.get("CertificateSigningRequest", name, "default")
        cert_b64 = (obj.status or {}).get("certificate", "")
        if cert_b64:
            cert_file = f"{workdir}/kubelet-{node_name}.crt"
            with open(cert_file, "wb") as f:
                f.write(base64.b64decode(cert_b64))
            return cert_file, key_file
        # bootstrap runs before the kubelet has a loop: a plain blocking
        # poll on the caller's (bootstrap) thread
        time.sleep(poll)  # ktpu: allow[blocking-in-async]
    raise TimeoutError(
        f"CSR {name}: no certificate issued within {timeout}s")
