"""Scheduler-extender HTTP endpoint: the stock-control-plane integration seam.

Speaks the reference's extender wire protocol so an *unmodified* Go
kube-scheduler can delegate filtering/prioritization to the TPU:
`HTTPExtender` POSTs JSON `ExtenderArgs{pod, nodes|nodenames}` to
URLPrefix+"/"+verb and expects `ExtenderFilterResult` / `HostPriorityList`
back (reference plugin/pkg/scheduler/core/extender.go:100 Filter, :143
Prioritize, :227-243 POST mechanics; wire types
plugin/pkg/scheduler/api/v1/types.go:148-204). The optional bind verb
(`ExtenderBindingArgs`) binds through this framework's store in standalone
deployments.

Two node-delivery modes, matching ExtenderConfig.NodeCacheCapable:
- node-cache-capable (names only): candidates resolve against the maintained
  StateDB — the intended production mode, where the extender watches the
  cluster itself and the Go scheduler ships only names.
- full objects: nodes in the request body are encoded on the fly into a
  scratch state (universe ids shared with the persistent table).

The HTTP layer is a minimal asyncio HTTP/1.1 server — requests are small
JSON POSTs on a trusted network, exactly how the reference treats extenders
(5s default timeout, extender.go:36).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any

import jax
import numpy as np

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.apiserver.flowcontrol import FlowRejected
from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy, build_policy_rows
from kubernetes_tpu.ops.solver import evaluate_pod
from kubernetes_tpu.state import Capacities, encode_cluster
from kubernetes_tpu.state.layout import CapacityError
from kubernetes_tpu.state.pod_batch import empty_batch, encode_pod_into
from kubernetes_tpu.state.statedb import StateDB

log = logging.getLogger(__name__)

_UNBUILT = object()


def _row(batch, i=0):
    return jax.tree.map(lambda a: a[i], batch)


class ExtenderService:
    """Protocol logic, HTTP-free (reused by tests and the HTTP server).

    Both verbs run the CONFIGURED policy's complete predicate/priority set
    via ops.solver.evaluate_pod — the same `_pod_eval` the batch solver's
    scan step executes (one derivation, no drift): a stock Go scheduler
    delegating here gets interpod-affinity, volume, spreading and every
    policy-argument registration, not a hard-coded subset."""

    def __init__(self, caps: Capacities | None = None,
                 policy: Policy = DEFAULT_POLICY, statedb: StateDB | None = None,
                 store=None, solversvc=None, solversvc_buckets: tuple = ()):
        self.caps = caps or Capacities()
        self.policy = policy.with_env_overrides()
        self.statedb = statedb
        self.store = store
        # co-located multi-tenant service (solversvc.SolverService): one
        # warmup() call compiles BOTH the per-cluster path and the
        # service's shape buckets before traffic arrives
        self.solversvc = solversvc
        self.solversvc_buckets = tuple(solversvc_buckets)
        # prows arrays are passed as traced args so per-request tables
        # (full-objects mode) don't recompile; policy/caps stay static
        self._eval = jax.jit(
            lambda state, pod_row, prows: evaluate_pod(
                state, pod_row, self.policy, caps=self.caps, prows=prows))
        # PolicyRows against the persistent statedb table are stable after
        # the first build; full-objects mode rebuilds per fresh table
        self._statedb_prows = _UNBUILT

    def warmup(self) -> None:
        """Compile the evaluation program before serving (first compile can
        exceed the reference client's 5s default timeout, extender.go:36).
        When a solversvc is attached, its pow-2 shape buckets pre-compile
        here too — the compile registry names each bucket variant
        (``solversvc[evaluate,pN]`` / ``solversvc[solve,pN]+flags``) so
        `bench --profile` attributes any recompile to the exact bucket."""
        try:
            dummy = Node.from_dict({
                "metadata": {"name": "warmup-node"},
                "status": {"allocatable": {"cpu": "1", "memory": "1Gi",
                                           "pods": "10"},
                           "conditions": [{"type": "Ready",
                                           "status": "True"}]}})
            self._evaluate(Pod.from_dict({"metadata": {"name": "warmup"}}),
                           [dummy], None)
        except Exception:  # never block serving on a warmup failure
            log.exception("extender warmup failed")
        if self.solversvc is not None:
            self.solversvc.warmup(self.solversvc_buckets)

    # ---- state resolution ----

    def _cached_state(self):
        if self.statedb is None:
            return None, None
        return self.statedb.flush(), self.statedb.table

    def _evaluate(self, pod: Pod, nodes: list[Node] | None,
                  node_names: list[str] | None):
        """Returns (names, feasible bool[N], scores f32[N], row_of)."""
        ctx = self.statedb.volume_ctx if self.statedb is not None else None
        if nodes is not None:
            state, batch, table = encode_cluster(nodes, [pod], self.caps,
                                                 ctx=ctx)
            # argument registrations intern Exists-requirements/topology
            # slots into the fresh table — refill membership afterwards
            prows = build_policy_rows(self.policy, table, self.caps)
            from kubernetes_tpu.state.cluster_state import apply_pending_refreshes
            apply_pending_refreshes(state, table)
            names = [n.metadata.name for n in nodes]
        else:
            state, table = self._cached_state()
            if state is None:
                raise ValueError("nodenames given but no statedb maintained")
            if self._statedb_prows is _UNBUILT:
                self._statedb_prows = build_policy_rows(
                    self.policy, table, self.caps)
            prows = self._statedb_prows
            batch = empty_batch(self.caps)
            encode_pod_into(batch, 0, pod, self.caps, table, ctx=ctx)
            # encoding may have interned new membership/selector/volsel
            # entries; flush() refills the affected columns and re-uploads
            # them (no-op when nothing is pending)
            state = self.statedb.flush()
            names = node_names or []
        feasible, score = self._eval(state, _row(batch), prows)
        return names, np.asarray(feasible), np.asarray(score), table.row_of

    # ---- verbs ----

    def filter(self, args: dict[str, Any]) -> dict[str, Any]:
        """ExtenderFilterResult for ExtenderArgs (extender.go:100)."""
        try:
            pod = Pod.from_dict(args.get("pod") or {})
            nodes, node_names = _parse_candidates(args)
            names, feasible, _, row_of = self._evaluate(pod, nodes, node_names)

            def ok(name: str) -> bool:
                row = row_of.get(name)
                return row is not None and bool(feasible[row])

            items = {n.metadata.name: n.to_dict() for n in nodes} \
                if nodes is not None else None
            return filter_payload(names, ok, items)
        except (ValueError, CapacityError, KeyError) as e:  # malformed args
            return {"error": f"{type(e).__name__}: {e}"}

    def prioritize(self, args: dict[str, Any]) -> list[dict[str, Any]]:
        """HostPriorityList for ExtenderArgs (extender.go:143). Scores are the
        default-policy weighted sum truncated to int (the Go scheduler
        multiplies by the configured extender weight)."""
        pod = Pod.from_dict(args.get("pod") or {})
        nodes, node_names = _parse_candidates(args)
        names, _, score, row_of = self._evaluate(pod, nodes, node_names)

        def score_of(name: str) -> int:
            row = row_of.get(name)
            return int(score[row]) if row is not None else 0

        return priority_payload(names, score_of)

    def bind(self, args: dict[str, Any]) -> dict[str, Any]:
        """ExtenderBindingResult for ExtenderBindingArgs — standalone mode
        binds through this framework's store."""
        if self.store is None:
            return {"Error": "bind not supported: no store configured"}
        from kubernetes_tpu.api.objects import Binding
        from kubernetes_tpu.apiserver.store import Conflict, NotFound
        try:
            self.store.bind(Binding(pod_name=args.get("PodName", ""),
                                    namespace=args.get("PodNamespace", "default"),
                                    target_node=args.get("Node", "")))
            return {"Error": ""}
        except (Conflict, NotFound) as e:
            return {"Error": str(e)}


def _parse_candidates(args: dict[str, Any]):
    if args.get("nodes") is not None:
        return [Node.from_dict(d) for d in args["nodes"].get("items") or []], None
    names = args.get("nodenames")
    return None, list(names or [])


# ---- wire payload shaping, shared by the per-cluster service above and
# the multi-tenant solversvc front end (one evaluation path, one protocol
# rendering — both end at ops.solver.evaluate_pod, single or vmapped) ----

FAILED_REASON = "node(s) didn't satisfy TPU predicates"


def filter_payload(names: list[str], feasible_of,
                   node_items: dict[str, dict] | None) -> dict[str, Any]:
    """ExtenderFilterResult from a per-name feasibility callable.
    `node_items` (name -> node dict) echoes full objects back in
    non-cache-capable mode; None renders the nodenames shape."""
    passed, failed = [], {}
    for name in names:
        if feasible_of(name):
            passed.append(name)
        else:
            failed[name] = FAILED_REASON
    if node_items is not None:
        result: dict[str, Any] = {"nodes": {
            "apiVersion": "v1", "kind": "NodeList",
            "items": [node_items[n] for n in passed]}}
    else:
        result = {"nodenames": passed}
    if failed:
        result["failedNodes"] = failed
    return result


def priority_payload(names: list[str], score_of) -> list[dict[str, Any]]:
    """HostPriorityList from a per-name score callable."""
    return [{"host": name, "score": int(score_of(name))} for name in names]


class ExtenderServer:
    """Minimal asyncio HTTP/1.1 wrapper around ExtenderService.

    Hardened like the reference treats its extenders: a configurable
    per-request deadline (default 5s — DefaultExtenderTimeout,
    extender.go:36) answered with 504 when evaluation overruns, and an
    honest 429 + Retry-After when a fair-queue front end (solversvc)
    sheds the request — `HTTPExtender` raises ExtenderError on either,
    so the stock scheduler's per-pod retry/backoff semantics compose."""

    def __init__(self, service: ExtenderService, host: str = "127.0.0.1",
                 port: int = 0, deadline_s: float = 5.0):
        self.service = service
        self.host = host
        self.port = port
        self.deadline_s = deadline_s
        self._server: asyncio.AbstractServer | None = None
        self._ready = False  # /readyz: true once warmup compiled

    def _warm(self) -> None:
        """Blocking pre-compile, run in an executor before serving
        (subclasses override to warm their own programs)."""
        self.service.warmup()

    async def start(self) -> None:
        await asyncio.get_running_loop().run_in_executor(None, self._warm)
        self._ready = True
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request_line = await reader.readline()
                if not request_line:
                    return
                try:
                    method, path, _ = request_line.decode().split(None, 2)
                except ValueError:
                    await self._respond(writer, 400, {"error": "bad request line"})
                    return
                headers: dict[str, str] = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = line.decode().partition(":")
                    headers[name.strip().lower()] = value.strip()
                length = int(headers.get("content-length", 0))
                body = await reader.readexactly(length) if length else b""

                p = path.split("?", 1)[0].rstrip("/")
                if p in ("/metrics", "/readyz", "/livez"):
                    # text obs endpoints; /healthz keeps its JSON shape
                    # (the reference extender contract this server serves)
                    from kubernetes_tpu.obs import metrics as obs_metrics
                    from kubernetes_tpu.obs.http import (
                        http_head,
                        obs_response,
                    )

                    status, rbody, ctype = obs_response(
                        method, p, registry=obs_metrics.REGISTRY,
                        ready_checks={"warmed-up": lambda: self._ready})
                    writer.write(http_head(status, rbody, ctype))
                    await writer.drain()
                    return
                extra: dict[str, str] = {}
                try:
                    routed = await asyncio.wait_for(
                        self._route(method, path, body), self.deadline_s)
                    status, payload = routed[0], routed[1]
                    extra = routed[2] if len(routed) > 2 else {}
                except asyncio.TimeoutError:
                    status, payload = 504, {
                        "error": f"request exceeded the "
                                 f"{self.deadline_s:.0f}s deadline"}
                except FlowRejected as e:
                    # the fair queues shed this request: honest 429 with a
                    # drain-time hint — HTTPExtender surfaces it and the
                    # stock scheduler requeues the pod with backoff
                    status, payload = 429, {"error": str(e)}
                    extra = {"Retry-After": str(max(1, round(e.retry_after)))}
                keep = headers.get("connection", "keep-alive").lower() != "close"
                await self._respond(writer, status, payload, keep_alive=keep,
                                    extra_headers=extra)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _route(self, method: str, path: str, body: bytes):
        """-> (status, payload) or (status, payload, extra_headers). Verb
        evaluation runs in an executor so the deadline can actually fire
        and device compute never stalls the serving loop."""
        path = path.rstrip("/")
        if method == "GET" and path in ("", "/healthz"):
            return 200, {"ok": True}
        if method != "POST":
            return 405, {"error": f"method {method} not allowed"}
        try:
            args = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            return 400, {"error": f"bad JSON: {e}"}
        if not isinstance(args, dict):
            return 400, {"error": "request body must be a JSON object"}
        verb = path.rsplit("/", 1)[-1]
        loop = asyncio.get_running_loop()
        if verb == "filter":
            return 200, await loop.run_in_executor(
                None, self.service.filter, args)
        if verb == "prioritize":
            return 200, await loop.run_in_executor(
                None, self.service.prioritize, args)
        if verb == "bind":
            return 200, await loop.run_in_executor(
                None, self.service.bind, args)
        return 404, {"error": f"unknown verb {verb!r}"}

    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       payload, keep_alive: bool = False,
                       extra_headers: dict[str, str] | None = None) -> None:
        body = json.dumps(payload).encode()
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 429: "Too Many Requests",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(status, "Error")
        conn = "keep-alive" if keep_alive else "close"
        head = [f"HTTP/1.1 {status} {reason}",
                "Content-Type: application/json",
                f"Content-Length: {len(body)}"]
        for name, value in (extra_headers or {}).items():
            head.append(f"{name}: {value}")
        head.append(f"Connection: {conn}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()
