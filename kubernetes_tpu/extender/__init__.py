from kubernetes_tpu.extender.server import ExtenderServer  # noqa: F401
