"""HTTP extender client: the driver's outbound half of the extender seam.

The HTTPExtender analog (reference plugin/pkg/scheduler/core/extender.go:40;
Filter :100, Prioritize :143, POST mechanics :227-243): after the device
evaluates a pod, each configured extender (api/types.go:129 ExtenderConfig)
gets ExtenderArgs JSON and may veto candidates (Filter) and add weighted
scores (Prioritize). nodeCacheCapable extenders receive only node names;
others get full Node objects. A Filter error fails the pod's scheduling
attempt (generic_scheduler.go:211-228 returns the error), which requeues
it with backoff like any other failure."""

from __future__ import annotations

import json
import socket
from urllib.parse import urlsplit

from kubernetes_tpu.models.policy import ExtenderConfig


class ExtenderError(Exception):
    """Transport failure, non-200, or an error field in the reply."""


class HTTPExtender:
    def __init__(self, config: ExtenderConfig):
        self.config = config
        url = urlsplit(config.url_prefix)
        self.host = url.hostname or "127.0.0.1"
        self.port = url.port or 80
        self.path_prefix = (url.path or "").rstrip("/")

    def _post(self, verb: str, args: dict) -> dict | list:
        payload = json.dumps(args).encode()
        path = f"{self.path_prefix}/{verb}"
        try:
            with socket.create_connection(
                    (self.host, self.port),
                    timeout=self.config.http_timeout) as sock:
                sock.sendall(
                    f"POST {path} HTTP/1.1\r\n"
                    f"Host: {self.host}\r\n"
                    f"Content-Type: application/json\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"Connection: close\r\n\r\n".encode() + payload)
                data = b""
                while True:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    data += chunk
        except OSError as e:
            raise ExtenderError(
                f"extender {self.config.url_prefix}/{verb}: {e}") from e
        head, _, body = data.partition(b"\r\n\r\n")
        try:
            status = int(head.split(None, 2)[1])
        except (IndexError, ValueError):
            raise ExtenderError(
                f"extender {self.config.url_prefix}/{verb}: bad reply"
            ) from None
        if status != 200:
            raise ExtenderError(
                f"extender {self.config.url_prefix}/{verb}: HTTP {status}")
        try:
            return json.loads(body)
        except ValueError as e:
            raise ExtenderError(
                f"extender {self.config.url_prefix}/{verb}: bad JSON: {e}"
            ) from e

    def _args(self, pod, names: list[str], nodes_by_name) -> dict:
        if self.config.node_cache_capable or nodes_by_name is None:
            return {"pod": pod.to_dict(), "nodenames": list(names)}
        return {"pod": pod.to_dict(),
                "nodes": {"apiVersion": "v1", "kind": "NodeList",
                          "items": [nodes_by_name[n].to_dict()
                                    for n in names if n in nodes_by_name]}}

    def filter(self, pod, names: list[str],
               nodes_by_name=None) -> tuple[list[str], dict[str, str]]:
        """-> (passed names, failed name -> reason). No filter verb
        configured = pass-through (extender.go:105)."""
        if not self.config.filter_verb:
            return list(names), {}
        reply = self._post(self.config.filter_verb,
                           self._args(pod, names, nodes_by_name))
        if not isinstance(reply, dict):
            raise ExtenderError("filter reply must be an object")
        if reply.get("error"):
            raise ExtenderError(str(reply["error"]))
        if reply.get("nodenames") is not None:
            passed = list(reply["nodenames"])
        elif reply.get("nodes") is not None:
            passed = [((n.get("metadata") or {}).get("name", ""))
                      for n in (reply["nodes"].get("items") or [])]
        else:
            passed = []
        return passed, dict(reply.get("failedNodes") or {})

    def prioritize(self, pod, names: list[str],
                   nodes_by_name=None) -> dict[str, float]:
        """-> name -> extender score x configured weight
        (generic_scheduler.go:381-401 combines them additively)."""
        if not self.config.prioritize_verb:
            return {}
        reply = self._post(self.config.prioritize_verb,
                           self._args(pod, names, nodes_by_name))
        if not isinstance(reply, list):
            raise ExtenderError("prioritize reply must be a list")
        return {e.get("host", ""): float(e.get("score", 0))
                * self.config.weight for e in reply}
