"""kubernetes_tpu — a TPU-native cluster-orchestration framework.

A from-scratch re-design of the capabilities of Kubernetes (~v1.8 vintage,
reference: mgugino-upstream-stage/kubernetes) around a TPU-first compute model:

- Cluster state lives on device as a structure-of-arrays tensor database
  (`kubernetes_tpu.state`), the analog of the scheduler cache
  (reference: plugin/pkg/scheduler/schedulercache/node_info.go:34-74).
- Scheduling predicates and priorities are masked XLA ops over a
  (pending_pods x nodes) batch (`kubernetes_tpu.ops`), replacing the
  goroutine fan-out hot loops (reference:
  plugin/pkg/scheduler/core/generic_scheduler.go:163,285).
- A batched, serial-equivalent assignment solver replaces the one-pod-at-a-
  time `scheduleOne` driver (reference: plugin/pkg/scheduler/scheduler.go:253).
- The node axis shards across a `jax.sharding.Mesh` over ICI
  (`kubernetes_tpu.parallel`), the TPU-native equivalent of
  `workqueue.Parallelize(16, len(nodes), ...)`.
- A thin asyncio host plane provides the API-machinery capabilities:
  an object store with optimistic concurrency + watch streams
  (`kubernetes_tpu.apiserver`), reflector/informer caches and rate-limited
  workqueues (`kubernetes_tpu.client`), and reconcile controllers
  (`kubernetes_tpu.controllers`).
- Integration with an unmodified Go control plane goes through the stock
  scheduler-extender HTTP/JSON hook (`kubernetes_tpu.extender`, reference:
  plugin/pkg/scheduler/core/extender.go:40).
"""

from kubernetes_tpu import compat as _compat  # noqa: F401  (asyncio.timeout on 3.10)

__version__ = "0.1.0"
