"""Runtime compatibility shims.

The codebase targets Python 3.11; the deployment images pin whatever the
jax toolchain ships, which today is 3.10. The one 3.11-ism used
pervasively (library + tests) is ``asyncio.timeout``. On 3.10 we install
a minimal backport with the same observable semantics for our usage:

- entering schedules a cancellation of the *current task* at the
  deadline;
- a cancellation caused by that deadline surfaces as ``TimeoutError``
  at the ``async with`` exit (external cancellations pass through);
- exiting before the deadline cancels the timer.

Nested timeouts compose (each level converts only its own expiry). The
3.11 ``Task.uncancel`` bookkeeping has no 3.10 equivalent, so a timeout
that fires in the same instant as an external cancel is reported as a
timeout — acceptable for the bounded-wait loops this codebase uses it
for.
"""

from __future__ import annotations

import asyncio


class _TimeoutBackport:
    """``async with asyncio.timeout(delay):`` for Python 3.10."""

    def __init__(self, delay: float | None):
        self._delay = delay
        self._handle = None
        self._expired = False

    async def __aenter__(self):
        if self._delay is not None:
            task = asyncio.current_task()

            def _fire() -> None:
                self._expired = True
                task.cancel()

            self._handle = asyncio.get_running_loop().call_later(
                self._delay, _fire)
        return self

    async def __aexit__(self, exc_type, exc, tb):
        if self._handle is not None:
            self._handle.cancel()
        if self._expired and exc_type is asyncio.CancelledError:
            raise TimeoutError from exc
        return False


def install() -> None:
    """Idempotently fill in ``asyncio.timeout`` when the stdlib lacks it."""
    if not hasattr(asyncio, "timeout"):
        asyncio.timeout = _TimeoutBackport  # type: ignore[attr-defined]


install()
