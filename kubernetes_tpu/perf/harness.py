"""Throughput harness — the scheduler_perf equivalent.

Mirrors the reference's integration benchmark
(test/integration/scheduler_perf/scheduler_test.go:71-100
schedulePods: spin up an in-process control plane, pre-create fake nodes,
pump templated pods in, and measure sustained pods scheduled/sec; hard-fail
thresholds at :35-38). Here the control plane is the in-memory store +
informers and the scheduler is the batched device solver; the measured
number is end-to-end (encode + device solve + bind + watch confirmation).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from kubernetes_tpu.apiserver import ObjectStore
from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy
from kubernetes_tpu.perf.fixtures import make_nodes, make_pods
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.state import Capacities


@dataclass
class ThroughputResult:
    scheduled: int
    seconds: float
    pods_per_sec: float
    batches: int
    metrics: dict
    # per-phase registry histogram snapshot of the timed wave
    # ({phase: {count, sum_ms, p50_ms, p99_ms}}) — bench.py's
    # --metrics-snapshot payload
    phase_hist: dict = field(default_factory=dict)
    # staged-pipeline occupancy over the timed wave (stage_busy_frac +
    # queue-depth high-water marks); empty when KTPU_STAGED_PIPELINE=0
    pipeline: dict = field(default_factory=dict)
    # mesh runs: per-shard live-row occupancy + StateDB flush transfer
    # counters (bench[sharded] extras); empty without a mesh
    sharding: dict = field(default_factory=dict)
    # host<->device transfer-byte deltas over the timed wave (upload:
    # statedb_flush_bytes_total, readback: device_readback_bytes_total)
    transfers: dict = field(default_factory=dict)

    def __str__(self) -> str:
        return (f"{self.scheduled} pods in {self.seconds:.2f}s = "
                f"{self.pods_per_sec:.0f} pods/s over {self.batches} batches")


def _transfer_counters() -> dict:
    """Process-global transfer counters (the profiling plane's byte
    ledger) — deltas around a timed wave attribute its traffic."""
    from kubernetes_tpu.obs import REGISTRY
    out = {}
    for key, name in (("flush_bytes", "statedb_flush_bytes_total"),
                      ("flush_transfers", "statedb_flush_transfers_total"),
                      ("readback_bytes", "device_readback_bytes_total")):
        fam = REGISTRY.get(name)
        out[key] = float(fam.labels().value) if fam is not None else 0.0
    return out


def freeze_drill_heap() -> None:
    """Pre-drill GC hygiene shared by every stall-gated drill (chaos,
    overload, rolling-restart, scenario soak): collect whatever earlier
    configs left behind, then freeze the surviving heap out of the
    collector's reach. A gen2 pass walking co-resident heaps (a previous
    config's object graphs, jax caches) holds the GIL 50-220ms from
    whichever thread trips the allocation threshold — long enough to
    flake the 100ms loop-stall gate with a pause the drill's own loop
    never caused. After the freeze, gen2 passes only walk what the drill
    itself allocates (which IS control-plane behavior)."""
    import gc
    gc.collect()
    gc.freeze()


def thaw_drill_heap() -> None:
    """Undo freeze_drill_heap once the stall-sensitive window is over."""
    import gc
    gc.unfreeze()


async def _run(n_nodes: int, n_pods: int, caps: Capacities, policy: Policy,
               warmup_pods: int, node_kwargs: dict, pod_kwargs: dict,
               mesh=None, n_services: int = 0) -> ThroughputResult:
    store = ObjectStore(watch_window=max(1 << 18, 4 * (n_pods + n_nodes)))
    if n_services:
        from kubernetes_tpu.perf.fixtures import make_services
        for svc in make_services(n_services):
            store.create(svc)
    for node in make_nodes(n_nodes, **node_kwargs):
        store.create(node)
    sched = Scheduler(store, caps=caps, policy=policy, mesh=mesh)
    await sched.start()

    async def drain(expect: int) -> int:
        done = 0
        idle = 0
        while done < expect and idle < 3:
            got = await sched.schedule_pending(wait=0.5)
            done += got
            # a dispatched-but-unsettled batch is progress, not idleness
            busy = got > 0 or sched.inflight_batches > 0
            idle = 0 if busy else idle + 1
        return done

    if warmup_pods:
        for pod in make_pods(warmup_pods, name_prefix="warm", **pod_kwargs):
            store.create(pod)
        await asyncio.sleep(0)
        await drain(warmup_pods)
        # reclaim warmup capacity so the timed wave sees a clean cluster
        for pod in store.list("Pod", copy_objects=False):
            store.delete("Pod", pod.metadata.name, pod.metadata.namespace)
        await asyncio.sleep(0)
        while await sched.schedule_pending(wait=0.05):
            pass
        # the timed wave's metrics must not include warmup samples
        from kubernetes_tpu.scheduler.driver import SchedulerMetrics
        sched.metrics = SchedulerMetrics()
        if sched._staged is not None:
            sched._staged.reset_stats()
        # collect the warmup wave's garbage NOW: a gen2 pass triggered
        # mid-wave (walking every suite's surviving objects when several
        # share the process) otherwise lands its pause in whichever stage
        # thread tripped the allocation threshold, polluting the phase gates
        import gc
        gc.collect()

    for pod in make_pods(n_pods, **pod_kwargs):
        store.create(pod)
    await asyncio.sleep(0)

    batches_before = sched.metrics.batches
    transfers_before = _transfer_counters()
    t0 = time.perf_counter()
    done = await drain(n_pods)
    dt = time.perf_counter() - t0
    transfers_after = _transfer_counters()
    result = ThroughputResult(
        scheduled=done,
        seconds=dt,
        pods_per_sec=done / dt if dt > 0 else 0.0,
        batches=sched.metrics.batches - batches_before,
        metrics=sched.metrics.snapshot(),
        phase_hist=sched.metrics.phase_histograms(),
        pipeline=(sched._staged.snapshot()
                  if sched._staged is not None else {}),
        sharding=({
            "devices": mesh.size,
            "shard_rows": sched.statedb.shard_occupancy(),
            "flush_rows_total": sched.statedb.flush_rows_total,
            "flush_transfers_total": sched.statedb.flush_transfers_total,
            "flush_full_total": sched.statedb.flush_full_total,
        } if mesh is not None else {}),
        transfers={k: int(transfers_after[k] - transfers_before[k])
                   for k in transfers_before},
    )
    sched.stop()
    return result


@dataclass
class DeviceSolveResult:
    """Steady-state compiled-solver throughput with device-resident state —
    the transport-independent number (tunnel RTT/bandwidth variance moves
    the e2e figure up to 3×; this one is stable run-to-run)."""

    n_nodes: int
    batch_pods: int
    iters: int
    ms_per_solve: float
    pods_per_sec: float

    def __str__(self) -> str:
        return (f"device solve N={self.n_nodes} P={self.batch_pods}: "
                f"{self.ms_per_solve:.2f} ms/solve = "
                f"{self.pods_per_sec:.0f} pods/s")


def run_device_solve(
    n_nodes: int,
    batch_pods: int = 4096,
    iters: int = 16,
    policy: Policy = DEFAULT_POLICY,
    node_kwargs: dict | None = None,
    pod_kwargs: dict | None = None,
    mesh=None,
) -> DeviceSolveResult:
    """Time the compiled solver alone: encode one batch, then dispatch it
    `iters` times against device-resident state and block once at the end.
    The chained-dispatch shape matches the driver's steady state (PERF.md's
    'device-only solve' rows)."""
    import numpy as np

    from kubernetes_tpu.state.pod_batch import packed_batch_flags

    store = ObjectStore()
    for node in make_nodes(n_nodes, **(node_kwargs or {})):
        store.create(node)
    num = 1 << max(6, (n_nodes - 1).bit_length())
    caps = Capacities(num_nodes=num, batch_pods=batch_pods)
    sched = Scheduler(store, caps=caps, policy=policy, mesh=mesh)
    for node in store.list("Node", copy_objects=False):
        sched.statedb.upsert_node(node)
    fblob, iblob = sched._next_blobs()
    for i, pod in enumerate(make_pods(batch_pods, **(pod_kwargs or {}))):
        sched.encode_cache.encode_packed_into(fblob, iblob, i, pod)
    flags = packed_batch_flags(fblob, iblob, batch_pods,
                               sched.statedb.table, caps)
    fn = sched._get_schedule_fn(flags)
    state = sched.statedb.flush()
    rr = np.uint32(0)
    import jax

    # pin the packed batch on device once: this measures the solver, not
    # the per-call blob upload (which the e2e figure already carries);
    # under a mesh the batch replicates to every device up front
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(mesh, PartitionSpec())
        fblob, iblob = (jax.device_put(fblob, repl),
                        jax.device_put(iblob, repl))
    else:
        fblob, iblob = jax.device_put(fblob), jax.device_put(iblob)
    warm = fn(state, fblob, iblob, rr)   # compile + device warmup
    np.asarray(warm.assignments)
    rr = warm.rr_end                     # device-resident, chained like the
    t0 = time.perf_counter()             # driver's steady state
    last = None
    for _ in range(iters):
        last = fn(state, fblob, iblob, rr)
        rr = last.rr_end
    np.asarray(last.assignments)
    dt = time.perf_counter() - t0
    return DeviceSolveResult(
        n_nodes=n_nodes, batch_pods=batch_pods, iters=iters,
        ms_per_solve=1e3 * dt / iters,
        pods_per_sec=iters * batch_pods / dt if dt > 0 else 0.0)


@dataclass
class PreemptionResult:
    """Priority/preemption drill: saturate the cluster with low-priority
    filler, then drive a high-priority wave through the nominate-evict-
    rebind flow and measure how fast displaced capacity turns into bound
    high-priority pods."""

    n_nodes: int
    fillers: int
    wave: int
    bound_wave: int
    attempts: int
    victims: int
    seconds: float
    preemption_latency_ms: float
    victims_per_sec: float

    def __str__(self) -> str:
        return (f"preemption N={self.n_nodes}: {self.bound_wave}/{self.wave} "
                f"high-prio pods landed in {self.seconds:.2f}s via "
                f"{self.victims} victims ({self.victims_per_sec:.0f} "
                f"victims/s, p50 latency {self.preemption_latency_ms:.1f}ms)")


async def _run_preemption(n_nodes: int, wave: int,
                          fillers_per_node: int,
                          mesh=None) -> PreemptionResult:
    """Saturate every node's CPU with globalDefault-priority filler, then
    create a wave of pods whose PriorityClass outranks the filler and whose
    request only fits after an eviction. Each wave pod must take the full
    unschedulable -> solver victim pick -> evict + nominate -> requeue ->
    bind path, so the timed wave exercises all three preemption layers."""
    from kubernetes_tpu.api.objects import PriorityClass
    from kubernetes_tpu.apiserver.admission import default_chain

    store = ObjectStore(admission=default_chain(),
                        watch_window=max(1 << 18, 16 * n_nodes))
    store.create(PriorityClass.from_dict({
        "metadata": {"name": "bench-filler"}, "value": 0,
        "globalDefault": True,
        "description": "preemptible bench filler"}))
    store.create(PriorityClass.from_dict({
        "metadata": {"name": "bench-critical"}, "value": 100_000,
        "description": "preempting bench wave"}))
    for node in make_nodes(n_nodes, cpu="4", memory="8Gi"):
        store.create(node)

    num = 1 << max(6, (n_nodes - 1).bit_length())
    caps = Capacities(num_nodes=num,
                      batch_pods=min(2048, max(64, n_nodes)))
    sched = Scheduler(store, caps=caps, mesh=mesh)
    await sched.start()

    async def drain(expect: int) -> int:
        done = 0
        idle = 0
        while done < expect and idle < 6:
            got = await sched.schedule_pending(wait=0.2)
            done += got
            busy = got > 0 or sched.inflight_batches > 0
            idle = 0 if busy else idle + 1
        return done

    # two fillers per node leave 4000m - 2*1900m = 200m free: any wave
    # pod with a 2-core request is unschedulable until a filler is evicted
    n_fill = fillers_per_node * n_nodes
    for pod in make_pods(n_fill, cpu="1900m", memory="256Mi",
                         name_prefix="filler"):
        store.create(pod)
    await asyncio.sleep(0)
    filled = await drain(n_fill)
    if filled < n_fill:
        sched.stop()
        raise RuntimeError(
            f"preemption bench: only {filled}/{n_fill} fillers bound")

    # the timed wave's metrics must not include the filler phase
    from kubernetes_tpu.scheduler.driver import SchedulerMetrics
    sched.metrics = SchedulerMetrics()

    for pod in make_pods(wave, cpu="2", memory="512Mi",
                         name_prefix="crit",
                         priority_class_name="bench-critical"):
        store.create(pod)
    await asyncio.sleep(0)
    t0 = time.perf_counter()
    bound = await drain(wave)
    dt = time.perf_counter() - t0
    snap = sched.metrics.snapshot()
    pre = snap.get("preemption", {})
    sched.stop()
    return PreemptionResult(
        n_nodes=n_nodes, fillers=n_fill, wave=wave, bound_wave=bound,
        attempts=pre.get("attempts", 0), victims=pre.get("victims", 0),
        seconds=dt,
        # each wave pod's e2e sample spans first-seen -> bound, i.e. the
        # whole preemption cycle including the post-eviction requeue
        preemption_latency_ms=snap.get("e2e_p50_ms", 0.0),
        victims_per_sec=pre.get("victims", 0) / dt if dt > 0 else 0.0)


def run_preemption(n_nodes: int = 512, wave: int | None = None,
                   fillers_per_node: int = 2, mesh=None) -> PreemptionResult:
    """Blocking entry point for the priority/preemption drill."""
    if wave is None:
        wave = max(8, n_nodes // 4)
    return asyncio.run(_run_preemption(n_nodes, wave, fillers_per_node,
                                       mesh=mesh))


@dataclass
class RecoveryResult:
    nodes: int
    killed: int
    pods: int
    stranded: int
    seconds_to_recover: float
    # zone-disruption observability (node_controller.go handleDisruption):
    # the killed zone's state observed DURING the outage, and after
    zone_state_during: str = ""
    zone_state_after: str = ""

    def __str__(self) -> str:
        return (f"killed {self.killed}/{self.nodes} nodes ({self.stranded} "
                f"stranded pods): all {self.pods} pods Running on live "
                f"nodes in {self.seconds_to_recover:.2f}s "
                f"(killed zone {self.zone_state_during or '?'} -> "
                f"{self.zone_state_after or '?'})")


async def _run_recovery(n_nodes: int, n_pods: int,
                        kill_frac: float) -> RecoveryResult:
    """Chaos mode: hollow cluster under RS load, kill a node fraction
    CONCENTRATED IN ONE ZONE (so the kill crosses the unhealthy-zone
    threshold and the per-zone disruption machinery engages), and measure
    wall time until every pod is Running on a live node again (the
    kubemark-style failure drill — node lifecycle controller detects,
    evicts; ReplicaSet recreates; scheduler re-places; hollow kubelets
    ack). Heartbeat cadence scales with cluster size so a 5k+-node drill
    does not melt the host plane under heartbeat writes alone."""
    from kubernetes_tpu.agent.hollow import HollowCluster
    from kubernetes_tpu.api.objects import ReplicaSet
    from kubernetes_tpu.controllers import ControllerManager

    heartbeat = max(0.5, n_nodes / 2000.0)
    grace = max(1.5, 2.5 * heartbeat)
    store = ObjectStore(watch_window=max(1 << 18, 16 * (n_pods + n_nodes)))
    cluster = HollowCluster(store, n_nodes=n_nodes,
                            heartbeat_every=heartbeat, zones=3,
                            capacity={"cpu": "32", "memory": "64Gi",
                                      "pods": "110"})
    await cluster.start()
    mgr = ControllerManager(
        store,
        node_lifecycle_kwargs=dict(
            monitor_period=0.2, grace_period=grace, eviction_timeout=0.5,
            eviction_rate=1e9, secondary_eviction_rate=1e9),
        # /10 cut into /24s covers 16k hollow nodes (the default /16's
        # 256 starves a headline-scale drill)
        node_ipam_kwargs=dict(cluster_cidr="10.0.0.0/10"))
    await mgr.start()
    num = 1 << max(6, (n_nodes - 1).bit_length())
    sched = Scheduler(store, caps=Capacities(
        num_nodes=num, batch_pods=min(2048, max(64, n_pods // 2))))
    await sched.start()
    driver = asyncio.get_running_loop().create_task(sched.run())

    store.create(ReplicaSet.from_dict({
        "metadata": {"name": "load", "namespace": "default"},
        "spec": {"replicas": n_pods,
                 "selector": {"matchLabels": {"app": "load"}},
                 "template": {"metadata": {"labels": {"app": "load"}},
                              "spec": {"containers": [{"name": "c",
                                       "resources": {"requests": {
                                           "cpu": "100m",
                                           "memory": "64Mi"}}}]}}}}))

    def running_off(dead_nodes=frozenset()):
        return sum(1 for p in store.list("Pod", copy_objects=False)
                   if p.status.phase == "Running"
                   and p.spec.node_name not in dead_nodes)

    async with asyncio.timeout(120):
        while running_off() < n_pods:
            await asyncio.sleep(0.1)

    by_node: dict[str, int] = {}
    for p in store.list("Pod", copy_objects=False):
        by_node[p.spec.node_name] = by_node.get(p.spec.node_name, 0) + 1
    # victims all come from zone-0 (node i is in zone i%3): killing
    # kill_frac of the CLUSTER takes 3*kill_frac of the zone — at the
    # default 10% that is 30%... so take 60% of zone-0 or the requested
    # cluster fraction, whichever is larger, to cross the 55% unhealthy
    # threshold and flip the zone's disruption state
    zone0 = [k.node_name for k in cluster.kubelets.values()
             if k.labels.get("failure-domain.beta.kubernetes.io/zone")
             == "zone-0"]
    n_kill = max(max(1, int(kill_frac * n_nodes)),
                 int(0.6 * len(zone0)))
    n_kill = min(n_kill, len(zone0))
    victims = sorted(zone0, key=lambda n: by_node.get(n, 0),
                     reverse=True)[:n_kill]
    stranded = sum(by_node.get(v, 0) for v in victims)
    t0 = time.perf_counter()
    cluster.stop(victims)
    dead = frozenset(victims)
    zone_during = ""
    async with asyncio.timeout(600):
        while running_off(dead) < n_pods:
            state = mgr.node_lifecycle.zone_states.get("zone-0", "")
            if state and state != "Normal":
                zone_during = state  # disruption machinery engaged
            await asyncio.sleep(0.1)
    seconds = time.perf_counter() - t0
    zone_after = mgr.node_lifecycle.zone_states.get("zone-0", "")
    sched.stop()
    driver.cancel()
    mgr.stop()
    cluster.stop()
    return RecoveryResult(nodes=n_nodes, killed=len(victims), pods=n_pods,
                          stranded=stranded, seconds_to_recover=seconds,
                          zone_state_during=zone_during,
                          zone_state_after=zone_after)


def run_recovery(n_nodes: int = 200, n_pods: int = 600,
                 kill_frac: float = 0.1) -> RecoveryResult:
    """Blocking entry point for the chaos/recovery drill."""
    return asyncio.run(_run_recovery(n_nodes, n_pods, kill_frac))


@dataclass
class ChaosResult:
    """Convergence-under-chaos drill: a workload scheduled through a
    seeded FaultPlane (store 429s/Conflicts), with a forced watch expiry +
    watcher drop + scheduler crash mid-workload. The cluster must
    converge — every pod bound exactly once and Running — and the figure
    is how fast it does after the disruption."""

    nodes: int
    pods: int
    seed: int
    bound: int
    double_binds: int
    faults_injected: int
    recovery_ms: float
    converged: bool
    # populated only when the drill runs under the RaceDetector/watchdog
    # (race_detect=True); the contract is all three stay zero
    racy_writes: int = 0
    loop_stalls: int = 0
    max_stall_ms: float = 0.0
    # the embedded Monitor's SLO verdict: SchedulerDown must fire during
    # the induced outage and resolve once the restarted scheduler scrapes
    # healthy again
    slo_alert_fired: bool = False
    slo_alert_resolved: bool = False
    monitor_scrapes: int = 0

    def __str__(self) -> str:
        return (f"chaos N={self.nodes} P={self.pods} seed={self.seed}: "
                f"{self.bound}/{self.pods} bound "
                f"({self.double_binds} double-binds, "
                f"{self.faults_injected} faults injected), recovered in "
                f"{self.recovery_ms:.0f}ms, SLO alert "
                f"fired={self.slo_alert_fired} "
                f"resolved={self.slo_alert_resolved}")


async def _run_chaos(n_nodes: int, n_pods: int, seed: int,
                     error_rate: float,
                     race_detect: bool = False) -> ChaosResult:
    """Every control-plane verb (scheduler, hollow kubelets, informers)
    goes through one seeded FaultPlane; observation reads go to the inner
    store so the observer never draws injection. Mid-workload the plane
    expires the watch history, evicts every watcher, and the scheduler
    crashes (driver task cancelled, informers stopped, in-flight device
    results dropped) and restarts cold.

    With race_detect, the whole drill additionally runs under the
    RaceDetector (every verb audited for lost-update writes) and the
    event-loop stall watchdog — the runtime proof behind lint rules
    R1/R5: zero racy writes, zero stalls past the 100ms threshold."""
    from kubernetes_tpu.agent.hollow import HollowCluster
    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.testing.faults import FaultPlane
    from kubernetes_tpu.testing.races import LoopStallWatchdog, RaceDetector

    freeze_drill_heap()

    cap = {"cpu": "16", "memory": "32Gi", "pods": "110"}
    inner = ObjectStore(watch_window=max(1 << 16, 8 * (n_pods + n_nodes)))
    # nodes pre-registered through the inner store: setup is not the thing
    # under test (the kubelets' get finds them, so registration never
    # draws an injected create failure at start)
    for i in range(n_nodes):
        inner.create(Node.from_dict({
            "metadata": {"name": f"hollow-{i}",
                         "labels": {"kubernetes.io/hostname": f"hollow-{i}"}},
            "status": {"allocatable": dict(cap), "capacity": dict(cap)}}))
    plane = FaultPlane(inner, seed=seed, error_rate=error_rate)
    # detector outside the plane: components' verbs draw injection AND are
    # audited; the detector's own bucket peeks bypass both
    store = RaceDetector(plane) if race_detect else plane
    watchdog = LoopStallWatchdog().start() if race_detect else None
    cluster = HollowCluster(store, n_nodes=n_nodes, heartbeat_every=0.5,
                            capacity=cap, resync_every=0.2)
    await cluster.start()
    num = 1 << max(6, (n_nodes - 1).bit_length())
    caps = Capacities(num_nodes=num,
                      batch_pods=min(256, max(64, n_pods)))
    loop = asyncio.get_running_loop()
    sched = Scheduler(store, caps=caps)
    driver = loop.create_task(sched.run())

    # embedded monitoring plane, deterministically stepped (scrape_once at
    # fixed drill points, not the jittered background loop): the scheduler
    # is a local render target through a mutable holder, so the crash
    # window scrapes as a failure (up=0) and SchedulerDown must fire, then
    # resolve after the restart. store=None: the monitor must not write
    # (the RaceDetector audit stays about the control plane under test).
    from kubernetes_tpu.obs.monitor import Monitor

    schedref = {"sched": sched}

    def scheduler_exposition() -> str:
        s = schedref["sched"]
        if s is None:
            raise ConnectionError("scheduler crashed")
        return s.metrics.registry.render()

    monitor = Monitor(store=None, interval=0.5, alert_for_s=0.0)
    monitor.add_local_target("scheduler", scheduler_exposition)

    for pod in make_pods(n_pods, cpu="100m", memory="64Mi",
                         name_prefix="chaos"):
        inner.create(pod)

    def crash_scheduler() -> None:
        # hard kill: no stop() — in-flight device results are dropped on
        # the floor, assumed-but-unconfirmed state is lost. kill() also
        # aborts the staged stage threads mid-batch: solved-but-unapplied
        # work must vanish (crash-consistency), never bind post-mortem
        # through a still-queued loop closure
        driver.cancel()
        sched.kill()
        schedref["sched"] = None

    async with asyncio.timeout(180):
        while len(plane.bind_counts) < max(1, n_pods // 3):
            await asyncio.sleep(0.02)
    await monitor.scrape_once()  # healthy baseline: up{job="scheduler"}=1
    crash_scheduler()
    plane.expire_watch_history()
    plane.drop_watchers()
    # the outage window: the dead scheduler scrapes as down and the SLO
    # alert must transition to firing before the replacement comes up
    await monitor.scrape_once()
    t0 = time.perf_counter()
    sched = Scheduler(store, caps=caps)
    schedref["sched"] = sched
    driver = loop.create_task(sched.run())

    def converged() -> bool:
        pods = inner.list("Pod", copy_objects=False)
        return (len(pods) >= n_pods
                and all(p.spec.node_name and p.status.phase == "Running"
                        for p in pods))

    async with asyncio.timeout(300):
        while not converged():
            await asyncio.sleep(0.05)
    recovery_ms = 1e3 * (time.perf_counter() - t0)
    # post-convergence scrape: the restarted scheduler answers again, so
    # the outage alert must resolve
    await monitor.scrape_once()
    driver.cancel()
    sched.stop()
    cluster.stop()
    thaw_drill_heap()
    stalls = watchdog.stop() if watchdog is not None else []
    double = sum(1 for v in plane.bind_counts.values() if v > 1)
    return ChaosResult(
        nodes=n_nodes, pods=n_pods, seed=seed,
        bound=len(plane.bind_counts), double_binds=double,
        faults_injected=plane.stats.injected_total,
        recovery_ms=recovery_ms,
        converged=double == 0 and len(plane.bind_counts) >= n_pods,
        racy_writes=len(store.racy_writes) if race_detect else 0,
        loop_stalls=len(stalls),
        max_stall_ms=1e3 * max(stalls, default=0.0),
        slo_alert_fired=monitor.fired("SchedulerDown"),
        slo_alert_resolved=monitor.resolved("SchedulerDown"),
        monitor_scrapes=3)


def run_chaos(n_nodes: int = 128, n_pods: int = 200, seed: int = 1234,
              error_rate: float = 0.05,
              race_detect: bool = False) -> ChaosResult:
    """Blocking entry point for the convergence-under-chaos drill."""
    return asyncio.run(_run_chaos(n_nodes, n_pods, seed, error_rate,
                                  race_detect=race_detect))


@dataclass
class AutoscalerResult:
    """Scale-up drill: a burst of pods lands on an empty (or undersized)
    cluster and the autoscaler must grow a node group until everything
    binds. The headline figure is wall time from burst to all-bound
    (scaleup_convergence_ms); the secondary one is the what-if probe cost
    (ms/solve on the simulator's device program)."""

    pods: int
    nodes_added: int
    group_max: int
    seconds: float
    scaleup_convergence_ms: float
    sim_solves: int
    sim_ms_per_solve: float

    def __str__(self) -> str:
        return (f"autoscaler: {self.pods} pods bound after adding "
                f"{self.nodes_added}/{self.group_max} nodes in "
                f"{self.seconds:.2f}s ({self.sim_solves} probe solves, "
                f"{self.sim_ms_per_solve:.2f} ms/solve)")


async def _run_autoscaler(n_pods: int, group_max: int,
                          pod_cpu: str) -> AutoscalerResult:
    from kubernetes_tpu.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.cloudprovider import FakeCloud

    store = ObjectStore(watch_window=max(1 << 16, 16 * n_pods))
    cloud = FakeCloud()
    cloud.add_node_group("bench-pool", 0, group_max,
                         cpu="16", memory="32Gi", pods="110")
    num = 1 << max(6, (group_max - 1).bit_length())
    sched = Scheduler(store, caps=Capacities(
        num_nodes=num, batch_pods=min(1024, max(64, n_pods // 2))))
    loop = asyncio.get_running_loop()
    driver = loop.create_task(sched.run())
    autoscaler = ClusterAutoscaler(
        store, cloud,
        caps=Capacities(num_nodes=num, batch_pods=min(256, max(64, n_pods))),
        scan_interval=0.05, scaleup_cooldown=0.0,
        scaledown_cooldown=3600.0, unneeded_time=3600.0,
        max_expansion=min(8, group_max))
    await autoscaler.start()

    for pod in make_pods(n_pods, cpu=pod_cpu, memory="128Mi",
                         name_prefix="burst"):
        store.create(pod)

    def all_bound() -> bool:
        pods = store.list("Pod", copy_objects=False)
        return len(pods) >= n_pods and all(p.spec.node_name for p in pods)

    t0 = time.perf_counter()
    async with asyncio.timeout(300):
        while not all_bound():
            await asyncio.sleep(0.02)
    dt = time.perf_counter() - t0
    sim = autoscaler.simulator
    autoscaler.stop()
    driver.cancel()
    sched.stop()
    return AutoscalerResult(
        pods=n_pods, nodes_added=autoscaler.scaleups,
        group_max=group_max, seconds=dt,
        scaleup_convergence_ms=1e3 * dt,
        sim_solves=sim.solve_count,
        sim_ms_per_solve=(1e3 * sim.solve_seconds / sim.solve_count
                          if sim.solve_count else 0.0))


def run_autoscaler(n_pods: int = 256, group_max: int = 16,
                   pod_cpu: str = "500m") -> AutoscalerResult:
    """Blocking entry point for the autoscaler scale-up drill."""
    return asyncio.run(_run_autoscaler(n_pods, group_max, pod_cpu))


@dataclass
class DefragResult:
    """Gang-defragmentation drill: a cluster fragmented by skewed fillers
    (every node's headroom below one gang pod's request, aggregate free
    space ample) receives a Pending gang that cannot schedule; the
    descheduler must plan and execute a minimal move set until the gang
    lands and every displaced pod rebinds. The headline figure is wall
    time from descheduler start to gang-schedulability restored
    (defrag_convergence_ms); the RaceDetector audits the whole drill."""

    nodes: int
    gang: int
    max_moves: int
    seed: int
    start_unschedulable: bool   # the gang was unbound before the planner
    dry_run_planned: int        # moves a dry-run pass WOULD have made
    dry_run_moves: int          # must stay 0
    moves: int
    rollbacks: int
    gangs_defragged: int
    defrag_convergence_ms: float
    sim_solves: int
    sim_ms_per_solve: float
    double_binds: int
    racy_writes: int
    converged: bool

    def __str__(self) -> str:
        return (f"defrag N={self.nodes} gang={self.gang} seed={self.seed}: "
                f"{self.moves} move(s) (budget {self.max_moves}), gang "
                f"landed in {self.defrag_convergence_ms:.0f}ms "
                f"({self.sim_solves} probe solves, "
                f"{self.sim_ms_per_solve:.2f} ms/solve, "
                f"{self.double_binds} double-binds, "
                f"{self.racy_writes} racy writes)")


async def _run_defrag(n_nodes: int, gang_size: int, max_moves: int,
                      seed: int) -> DefragResult:
    from kubernetes_tpu.api.objects import Node, Pod
    from kubernetes_tpu.descheduler import Descheduler
    from kubernetes_tpu.gang import (
        GROUP_MIN_ANNOTATION,
        GROUP_NAME_ANNOTATION,
    )
    from kubernetes_tpu.testing.races import RaceDetector

    import numpy as np

    rng = np.random.RandomState(seed)
    inner = ObjectStore(watch_window=max(1 << 16, 8 * n_nodes))
    # the fragmented shape: 4-cpu nodes, one 2-cpu filler each (headroom 2
    # everywhere), a seeded quarter additionally carrying a 500m skew pod
    # (headroom 1.5) — no node fits a 3-cpu gang pod, aggregate free space
    # is ~2 cpu per node. Fillers are created pre-bound (setup is not the
    # thing under test; their later rebinds ARE, and count exactly once).
    skewed = set(rng.choice(n_nodes, size=n_nodes // 4, replace=False))
    for i in range(n_nodes):
        name = f"frag-{i:06d}"
        inner.create(Node.from_dict({
            "metadata": {"name": name,
                         "labels": {"kubernetes.io/hostname": name}},
            "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                       "pods": "110"},
                       "conditions": [{"type": "Ready",
                                       "status": "True"}]}}))
        inner.create(Pod.from_dict({
            "metadata": {"name": f"fill-{i:06d}"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "2", "memory": "256Mi"}}}],
                "nodeName": name}}))
        if i in skewed:
            inner.create(Pod.from_dict({
                "metadata": {"name": f"skew-{i:06d}"},
                "spec": {"containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "500m", "memory": "64Mi"}}}],
                    "nodeName": name}}))
    store = RaceDetector(inner)
    num = 1 << max(6, (n_nodes - 1).bit_length())
    caps = Capacities(num_nodes=num, batch_pods=64)
    loop = asyncio.get_running_loop()
    sched = Scheduler(store, caps=caps)
    driver = loop.create_task(sched.run())

    ann = {GROUP_NAME_ANNOTATION: "defrag-gang",
           GROUP_MIN_ANNOTATION: str(gang_size)}
    for j in range(gang_size):
        inner.create(Pod.from_dict({
            "metadata": {"name": f"gang-{j:03d}", "annotations": dict(ann)},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "3", "memory": "512Mi"}}}]}}))

    def gang_pods():
        return [p for p in inner.list("Pod", copy_objects=False)
                if p.metadata.name.startswith("gang-")]

    # let the scheduler take its shot: the gang must NOT land on the
    # fragmented cluster (that unschedulability is the drill's premise)
    await asyncio.sleep(max(0.75, n_nodes / 20000))
    start_unschedulable = all(not p.spec.node_name for p in gang_pods())

    # scan_interval parks the background loop; the drill steps run_once
    # itself so pass timing is deterministic
    descheduler = Descheduler(
        store, caps=Capacities(num_nodes=num,
                               batch_pods=max(64, gang_size + max_moves)),
        scan_interval=3600.0, max_moves=max_moves,
        cooldown=3600.0, rollback_after=60.0, dry_run=True)
    await descheduler.start()
    # dry-run first: the plan is computed and counted, nothing moves
    descheduler.run_once()
    dry_run_planned = descheduler.planned_moves
    dry_run_moves = descheduler.moves

    descheduler.dry_run = False
    t0 = time.perf_counter()

    def landed() -> bool:
        return descheduler.gangs_defragged >= 1

    async with asyncio.timeout(300):
        while not landed():
            descheduler.run_once()
            await asyncio.sleep(0.05)
    dt = time.perf_counter() - t0

    def all_bound() -> bool:
        return all(p.spec.node_name
                   for p in inner.list("Pod", copy_objects=False))

    async with asyncio.timeout(60):
        while not all_bound():
            await asyncio.sleep(0.02)
    sim = descheduler.simulator
    descheduler.stop()
    driver.cancel()
    sched.stop()
    double = sum(1 for v in store.bind_counts.values() if v > 1)
    bound_gang = sum(1 for p in gang_pods() if p.spec.node_name)
    return DefragResult(
        nodes=n_nodes, gang=gang_size, max_moves=max_moves, seed=seed,
        start_unschedulable=start_unschedulable,
        dry_run_planned=dry_run_planned, dry_run_moves=dry_run_moves,
        moves=descheduler.moves, rollbacks=descheduler.rollbacks,
        gangs_defragged=descheduler.gangs_defragged,
        defrag_convergence_ms=1e3 * dt,
        sim_solves=sim.solve_count,
        sim_ms_per_solve=(1e3 * sim.solve_seconds / sim.solve_count
                          if sim.solve_count else 0.0),
        double_binds=double,
        racy_writes=len(store.racy_writes),
        converged=(bound_gang >= gang_size
                   and descheduler.moves <= max_moves
                   and dry_run_moves == 0))


def run_defrag(n_nodes: int = 128, gang_size: int = 8, max_moves: int = 8,
               seed: int = 1234) -> DefragResult:
    """Blocking entry point for the gang-defragmentation drill."""
    return asyncio.run(_run_defrag(n_nodes, gang_size, max_moves, seed))


def run_throughput(
    n_nodes: int,
    n_pods: int,
    caps: Capacities | None = None,
    policy: Policy = DEFAULT_POLICY,
    warmup_pods: int | None = None,
    node_kwargs: dict | None = None,
    pod_kwargs: dict | None = None,
    mesh=None,
    n_services: int = 0,
) -> ThroughputResult:
    """Blocking entry point: returns sustained scheduling throughput."""
    if caps is None:
        num_nodes = 1 << max(6, (n_nodes - 1).bit_length())
        # large batches amortize the fixed per-batch dispatch/readback round
        # trip (the dominant cost on remote-device transports); 4096 is the
        # measured sweet spot — 8192 crosses an XLA layout cliff at 16k nodes
        # (203ms vs 25ms per solve)
        caps = Capacities(num_nodes=num_nodes,
                          batch_pods=min(4096, max(64, n_pods // 6)))
    if warmup_pods is None:
        warmup_pods = min(2 * caps.batch_pods, n_pods)
    return asyncio.run(_run(n_nodes, n_pods, caps, policy, warmup_pods,
                            node_kwargs or {}, pod_kwargs or {}, mesh,
                            n_services=n_services))


@dataclass
class OverloadResult:
    """Noisy-tenant overload drill: a tenant floods the HTTP apiserver at a
    multiple of the scheduler's own request rate while a workload
    schedules through it. APF must keep the scheduler flow's latency
    bounded (p99 within 5x the unloaded baseline), every pod must bind
    exactly once, and the flood must be shed with honest 429s — the API
    plane stays alive instead of melting uniformly."""

    nodes: int
    pods: int
    seed: int
    flood_multiplier: float
    bound: int
    double_binds: int
    # p99s are SERVER-side seat-to-response latencies for the scheduler's
    # flow schema (FlowController.latency_samples) — what the API plane
    # actually did to the scheduler, unpolluted by client-process GIL
    # contention from the flood threads sharing the drill process
    p99_unloaded_ms: float
    p99_loaded_ms: float
    flood_requests: int
    flood_rejected: int
    sched_rps: float
    converged: bool
    racy_writes: int = 0
    loop_stalls: int = 0
    max_stall_ms: float = 0.0
    dispatched: dict = field(default_factory=dict)
    rejected: dict = field(default_factory=dict)

    @property
    def p99_bounded(self) -> bool:
        """The drill's latency contract: loaded p99 within 5x unloaded,
        with a 100ms floor so a millisecond-scale unloaded baseline on a
        busy CI box can't fail the drill on scheduler-jitter noise (at
        drill scale the 5x term dominates)."""
        return self.p99_loaded_ms <= max(5 * self.p99_unloaded_ms, 100.0)

    def __str__(self) -> str:
        return (f"overload N={self.nodes} P={self.pods} "
                f"x{self.flood_multiplier:.0f} flood: {self.bound}/"
                f"{self.pods} bound, sched p99 {self.p99_unloaded_ms:.1f}ms"
                f" -> {self.p99_loaded_ms:.1f}ms, flood "
                f"{self.flood_rejected}/{self.flood_requests} shed")


def _p99_ms(samples) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    return 1e3 * ordered[int(0.99 * (len(ordered) - 1))]


def run_overload(n_nodes: int = 64, n_pods: int = 256, seed: int = 2026,
                 flood_multiplier: float = 50.0, race_detect: bool = True,
                 warm_pods: int = 32, probes: int = 40) -> OverloadResult:
    """Blocking entry point for the noisy-tenant overload drill.

    Topology is the deployment shape (tests/http_util.py): the APIServer —
    APF + watch cache on, over a seeded FaultPlane (and RaceDetector +
    loop-stall watchdog when race_detect) — runs its own event loop in a
    background thread; the scheduler drives it over TCP as
    system:kube-scheduler, and `FaultPlane.flood` fires the tenant's
    seeded traffic storm from client threads."""
    import random as _random
    import socket as _socket
    import threading

    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver.auth import TokenAuthenticator, UserInfo
    from kubernetes_tpu.apiserver.http import APIServer, RemoteStore
    from kubernetes_tpu.apiserver.store import TooManyRequests
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.faults import FaultPlane
    from kubernetes_tpu.testing.races import LoopStallWatchdog, RaceDetector

    cap = {"cpu": "16", "memory": "32Gi", "pods": "110"}
    inner = ObjectStore(watch_window=max(1 << 16, 8 * (n_pods + n_nodes)))
    for i in range(n_nodes):
        inner.create(Node.from_dict({
            "metadata": {"name": f"ovl-{i}",
                         "labels": {"kubernetes.io/hostname": f"ovl-{i}"}},
            "status": {"allocatable": dict(cap), "capacity": dict(cap)}}))
    plane = FaultPlane(inner, seed=seed)
    server_store = RaceDetector(plane) if race_detect else plane
    auth = TokenAuthenticator({
        "sched-token": UserInfo("system:kube-scheduler",
                                ("system:authenticated",)),
        "tenant-token": UserInfo("tenant-a", ("system:authenticated",))})

    started = threading.Event()
    holder: dict = {}

    freeze_drill_heap()

    def serve() -> None:
        async def main():
            server = APIServer(server_store, authenticator=auth,
                               max_in_flight=64, watch_cache=True)
            await server.start()
            watchdog = LoopStallWatchdog().start() if race_detect else None
            holder["server"] = server
            holder["loop"] = asyncio.get_running_loop()
            holder["shutdown"] = asyncio.Event()
            started.set()
            await holder["shutdown"].wait()
            holder["stalls"] = watchdog.stop() if watchdog else []
            await server.stop()

        asyncio.run(main())

    thread = threading.Thread(target=serve, daemon=True)
    thread.start()
    if not started.wait(30):
        raise RuntimeError("overload drill: APIServer thread failed to start")
    server = holder["server"]
    host, port = server.host, server.port

    flood_stop = threading.Event()
    flood_lock = threading.Lock()
    flood_counts = {"requests": 0, "rejected": 0}
    flood_threads: list[threading.Thread] = []
    flood_rate = {"rps": 20.0}

    def flood_hook(flow: str, mult: float, rng: _random.Random) -> None:
        # one thread per ~100 target rps, each pacing its share with
        # seeded jitter so the burst pattern replays from the fault seed
        rate = max(20.0, flood_rate["rps"]) * mult
        n_threads = min(8, max(1, round(rate / 100)))

        def storm(thread_seed: int) -> None:
            r = _random.Random(thread_seed)
            per = rate / n_threads
            req = (f"GET /api/v1/pods HTTP/1.1\r\nHost: {host}\r\n"
                   "Authorization: Bearer tenant-token\r\n"
                   "Accept: application/json\r\n"
                   "Connection: close\r\n\r\n").encode()
            while not flood_stop.is_set():
                status = 0
                try:
                    with _socket.create_connection((host, port),
                                                   timeout=10) as sock:
                        sock.sendall(req)
                        head = b""
                        while b"\r\n\r\n" not in head and len(head) < 65536:
                            chunk = sock.recv(65536)
                            if not chunk:
                                break
                            head += chunk
                        # drain and DISCARD the body undecoded: the flood
                        # must cost the SERVER — a real tenant parses its
                        # responses on the tenant's machine, and json-
                        # decoding 8 threads' worth of big lists in this
                        # process would starve the serving loop's GIL and
                        # corrupt the stall measurement
                        while sock.recv(65536):
                            pass
                    status = int(head.split(None, 2)[1])
                except Exception:
                    pass
                with flood_lock:
                    flood_counts["requests"] += 1
                    if status == 429:
                        flood_counts["rejected"] += 1
                flood_stop.wait(r.uniform(0.5, 1.5) / per)

        for _ in range(n_threads):
            t = threading.Thread(target=storm,
                                 args=(rng.randrange(1 << 32),),
                                 daemon=True)
            t.start()
            flood_threads.append(t)

    plane.flood_hook = flood_hook

    async def drive() -> OverloadResult:
        # small bind batches on purpose: one bulk bind is a single
        # synchronous store op on the serving loop, and the drill's
        # zero->100ms-stall contract bounds how long any one op may run
        caps = Capacities(num_nodes=1 << max(6, (n_nodes - 1).bit_length()),
                          batch_pods=min(64, max(16, n_pods)))
        sched_client = RemoteStore(host, port, token="sched-token")
        creator = RemoteStore(host, port, token="sched-token")
        sched = Scheduler(sched_client, caps=caps)
        loop = asyncio.get_running_loop()
        driver = loop.create_task(sched.run())

        def create_with_retry(pod) -> None:
            while True:
                try:
                    creator.create(pod)
                    return
                except TooManyRequests as e:
                    # runs under asyncio.to_thread — never on the event loop
                    time.sleep(max(0.05, getattr(e, "retry_after", 0.0)))  # ktpu: allow[blocking-in-async]

        async def wait_bound(expect: int, timeout_s: float) -> bool:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                pods = await asyncio.to_thread(creator.list, "Pod")
                if sum(1 for p in pods if p.spec.node_name) >= expect:
                    return True
                await asyncio.sleep(0.1)
            return False

        # the scheduler flow's server-side latency samples — every create/
        # list/bind the scheduler identity makes lands here, so each phase
        # has the same request mix and the two p99s compare like for like
        def sched_samples() -> list[float]:
            return list(server.flow.latency_samples.get("system", ()))

        # ---- phase A: unloaded baseline (convergence polling while the
        # warm workload binds, then idle probes) ----
        t_warm = time.perf_counter()
        for pod in make_pods(warm_pods, cpu="100m", memory="64Mi",
                             name_prefix="warm"):
            await asyncio.to_thread(create_with_retry, pod)
        warm_ok = await wait_bound(warm_pods, 120)
        warm_s = max(time.perf_counter() - t_warm, 1e-3)
        flood_rate["rps"] = max(
            20.0, server.flow.dispatched.get("system", 0) / warm_s)
        probe = RemoteStore(host, port, token="sched-token")
        for _ in range(probes):
            await asyncio.to_thread(probe.list, "Pod")
            await asyncio.sleep(0.01)
        n_unloaded = len(sched_samples())

        # ---- phase B: the storm ----
        plane.flood("tenant-a", flood_multiplier)
        for pod in make_pods(n_pods, cpu="100m", memory="64Mi",
                             name_prefix="ovl"):
            await asyncio.to_thread(create_with_retry, pod)
        conv = await wait_bound(warm_pods + n_pods, 240)
        for _ in range(probes):
            await asyncio.to_thread(probe.list, "Pod")
        samples = sched_samples()
        unloaded, loaded = samples[:n_unloaded], samples[n_unloaded:]
        flood_stop.set()
        for t in flood_threads:
            t.join(timeout=5)
        driver.cancel()
        sched.stop()

        double = sum(1 for v in plane.bind_counts.values() if v > 1)
        return OverloadResult(
            nodes=n_nodes, pods=warm_pods + n_pods, seed=seed,
            flood_multiplier=flood_multiplier,
            bound=len(plane.bind_counts), double_binds=double,
            p99_unloaded_ms=_p99_ms(unloaded),
            p99_loaded_ms=_p99_ms(loaded),
            flood_requests=flood_counts["requests"],
            flood_rejected=flood_counts["rejected"],
            sched_rps=flood_rate["rps"],
            converged=(warm_ok and conv and double == 0
                       and len(plane.bind_counts) >= warm_pods + n_pods),
            racy_writes=len(server_store.racy_writes) if race_detect else 0,
            dispatched=dict(server.flow.dispatched),
            rejected=dict(server.flow.rejected))

    try:
        result = asyncio.run(drive())
    finally:
        flood_stop.set()
        holder["loop"].call_soon_threadsafe(holder["shutdown"].set)
        thread.join(timeout=15)
        thaw_drill_heap()
    stalls = holder.get("stalls", [])
    result.loop_stalls = len(stalls)
    result.max_stall_ms = 1e3 * max(stalls, default=0.0)
    return result


@dataclass
class RollingRestartResult:
    """Rolling-restart chaos drill: 3 stateless apiserver replicas over one
    shared store serve a live scheduler + informer + watcher workload while
    every replica is killed once mid-flight — hard (SIGKILL-style transport
    aborts) and graceful (drain: readyz 503, in-flight finishes, watchers
    get the terminal DRAIN frame) — then restarted. The control plane must
    come out exactly-once and gapless: every pod bound once, zero racy
    read-modify-writes, zero loop stalls past 100ms, and the dedicated
    watcher's resourceVersion stream equal to the store's authoritative
    Pod history — no gap, no duplicate — across every failover."""

    nodes: int
    pods: int
    seed: int
    replicas: int
    bound: int
    double_binds: int
    failovers: int
    failover_p99_ms: float
    resumes: int          # informer resume-from-rv successes (cheap path)
    relists: int          # informer full relists during the drill
    watch_resumes: int    # dedicated watcher's transport-level resumes
    watch_events: int
    watch_gaps: int
    watch_dupes: int
    converged: bool
    racy_writes: int = 0
    loop_stalls: int = 0
    max_stall_ms: float = 0.0
    replica_faults: list = field(default_factory=list)

    @property
    def gate(self) -> bool:
        """The drill's whole contract in one bool (the bench's gate)."""
        return (self.converged and self.double_binds == 0
                and self.racy_writes == 0 and self.loop_stalls == 0
                and self.watch_gaps == 0 and self.watch_dupes == 0
                and self.watch_resumes >= 1)

    def __str__(self) -> str:
        return (f"rolling-restart R={self.replicas} N={self.nodes} "
                f"P={self.pods}: {self.bound}/{self.pods} bound, "
                f"{len(self.replica_faults)} faults, "
                f"{self.failovers} failovers p99 "
                f"{self.failover_p99_ms:.1f}ms, resumes/relists "
                f"{self.resumes}/{self.relists}, watch "
                f"{self.watch_events} events {self.watch_gaps} gaps "
                f"{self.watch_dupes} dupes")


def run_rolling_restart(n_nodes: int = 16, n_pods: int = 96,
                        seed: int = 2027, replicas: int = 3,
                        race_detect: bool = True) -> RollingRestartResult:
    """Blocking entry point for the rolling-restart HA drill.

    Topology: a ReplicaSet of `replicas` APIServers (watch cache on) over
    ONE seeded FaultPlane (plus RaceDetector + loop-stall watchdog when
    `race_detect`) on a background serving loop; the scheduler, a pod
    creator, and a dedicated resourceVersion-recording watcher all drive
    it over TCP through replica-aware RemoteStores. Replica injuries fire
    through the FaultPlane's seeded action schedule — op-indexed, so each
    one lands at the same point of the workload on replay — at the 1/4,
    1/2 and 3/4 pod-creation milestones: hard kill, graceful drain, hard
    kill. Each victim is restarted on its original port before the next
    injury, the rolling shape."""
    import threading

    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver.auth import TokenAuthenticator, UserInfo
    from kubernetes_tpu.apiserver.store import AlreadyExists, TooManyRequests
    from kubernetes_tpu.client.informer import _metrics
    from kubernetes_tpu.testing.faults import FaultPlane
    from kubernetes_tpu.testing.races import LoopStallWatchdog, RaceDetector
    from kubernetes_tpu.testing.replicas import ReplicaSet

    cap = {"cpu": "16", "memory": "32Gi", "pods": "110"}
    inner = ObjectStore(watch_window=max(1 << 16, 8 * (n_pods + n_nodes)))
    for i in range(n_nodes):
        inner.create(Node.from_dict({
            "metadata": {"name": f"ha-{i}",
                         "labels": {"kubernetes.io/hostname": f"ha-{i}"}},
            "status": {"allocatable": dict(cap), "capacity": dict(cap)}}))
    plane = FaultPlane(inner, seed=seed)
    server_store = RaceDetector(plane) if race_detect else plane
    auth = TokenAuthenticator({
        "sched-token": UserInfo("system:kube-scheduler",
                                ("system:authenticated",))})

    freeze_drill_heap()

    rs = ReplicaSet(server_store, n=replicas, watch_cache=True,
                    authenticator=auth).start()
    for i, control in enumerate(rs.controls()):
        plane.attach_replica(i, control)
    watchdog_box: dict = {}
    if race_detect:
        rs._call(lambda: watchdog_box.update(
            dog=LoopStallWatchdog().start()))

    async def drive() -> RollingRestartResult:
        caps = Capacities(num_nodes=1 << max(6, (n_nodes - 1).bit_length()),
                          batch_pods=min(64, max(16, n_pods)))
        sched_client = rs.client(token="sched-token")
        creator = rs.client(token="sched-token")
        watcher_client = rs.client(token="sched-token")
        mx = _metrics("Pod")
        relists0, resumes0 = mx[3].value, mx[4].value
        sched = Scheduler(sched_client, caps=caps)
        loop = asyncio.get_running_loop()
        driver = loop.create_task(sched.run())

        # the coherence witness: one logical watch across the whole
        # replica set, recording every (type, resourceVersion) it delivers
        observed: list[tuple[str, int]] = []
        watcher = watcher_client.watch_resilient("Pod", since=0)
        watch_stop = asyncio.Event()

        async def observe() -> None:
            while not watch_stop.is_set():
                try:
                    ev = await watcher.next(timeout=0.5)
                except ConnectionError:
                    return  # every endpoint stayed dead past the deadline
                if ev is not None:
                    observed.append((ev.type, ev.resource_version))

        observer = loop.create_task(observe())

        def create_with_retry(pod) -> None:
            while True:
                try:
                    creator.create(pod)
                    return
                except AlreadyExists:
                    # a failover replay: the first send landed before its
                    # replica died — the shared store already has the pod,
                    # which is exactly the exactly-once contract
                    return
                except TooManyRequests as e:
                    # runs under asyncio.to_thread — never on the event loop
                    time.sleep(max(0.05, getattr(e, "retry_after", 0.0)))  # ktpu: allow[blocking-in-async]

        async def wait_bound(expect: int, timeout_s: float) -> bool:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                pods = await asyncio.to_thread(creator.list, "Pod")
                if sum(1 for p in pods if p.spec.node_name) >= expect:
                    return True
                await asyncio.sleep(0.1)
            return False

        async def wait_fault(count: int) -> None:
            # the scheduled injury fires inside a store tick on the
            # serving loop; wait until it has actually landed before
            # restarting the victim
            deadline = time.monotonic() + 30
            while len(plane.stats.replica_faults) < count \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.01)

        async def restart_replica(idx: int) -> None:
            # a draining victim closes its listener early but stops late:
            # wait for the port to free before rebinding it
            deadline = time.monotonic() + 15
            while rs.servers[idx]._server is not None \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            await asyncio.to_thread(rs.restart, idx)

        # injuries at pod-creation milestones, fired via the seeded action
        # schedule (op-indexed: the next store op pulls the trigger)
        milestones = {
            n_pods // 4: ("kill", 0),
            n_pods // 2: ("drain", 1),
            (3 * n_pods) // 4: ("kill", 2),
        }
        faults_seen = 0
        for i, pod in enumerate(make_pods(n_pods, cpu="100m",
                                          memory="64Mi",
                                          name_prefix="ha")):
            injury = milestones.get(i)
            if injury is not None:
                kind, victim = injury
                if kind == "kill":
                    plane.schedule(
                        plane.stats.ops + 1,
                        lambda p, v=victim: p.kill_replica(v),
                        f"kill-replica-{victim}")
                else:
                    plane.schedule(
                        plane.stats.ops + 1,
                        lambda p, v=victim: p.drain_replica(v),
                        f"drain-replica-{victim}")
                faults_seen += 1
                await asyncio.to_thread(create_with_retry, pod)
                await wait_fault(faults_seen)
                await restart_replica(victim)
            else:
                await asyncio.to_thread(create_with_retry, pod)
        conv = await wait_bound(n_pods, 240)

        # fence the coherence check at a fixed revision, then let the
        # watcher catch up to it before comparing against the store's
        # authoritative history
        fence_rv = inner.resource_version
        deadline = time.monotonic() + 30
        while (watcher.last_rv or 0) < fence_rv \
                and time.monotonic() < deadline \
                and not observer.done():
            await asyncio.sleep(0.05)
        watch_stop.set()
        watcher.stop()
        observer.cancel()
        driver.cancel()
        sched.stop()

        expected = [e.resource_version for e in inner._history
                    if e.kind == "Pod" and e.resource_version <= fence_rv]
        got = [rv for _, rv in observed if rv <= fence_rv]
        gaps = len(set(expected) - set(got))
        dupes = len(got) - len(set(got))
        double = sum(1 for v in plane.bind_counts.values() if v > 1)
        samples = (list(sched_client.failover_samples)
                   + list(creator.failover_samples)
                   + list(watcher_client.failover_samples))
        return RollingRestartResult(
            nodes=n_nodes, pods=n_pods, seed=seed, replicas=replicas,
            bound=len(plane.bind_counts), double_binds=double,
            failovers=(sched_client.failover_total
                       + creator.failover_total
                       + watcher_client.failover_total),
            failover_p99_ms=_p99_ms([s / 1e3 for s in samples]),
            resumes=int(mx[4].value - resumes0),
            relists=int(mx[3].value - relists0),
            watch_resumes=watcher.resumes,
            watch_events=len(got), watch_gaps=gaps, watch_dupes=dupes,
            converged=(conv and double == 0
                       and len(plane.bind_counts) >= n_pods),
            racy_writes=len(server_store.racy_writes) if race_detect else 0,
            replica_faults=list(plane.stats.replica_faults))

    try:
        result = asyncio.run(drive())
    finally:
        stalls = rs._call(watchdog_box["dog"].stop) \
            if watchdog_box else []
        rs.stop()
        thaw_drill_heap()
    result.loop_stalls = len(stalls)
    result.max_stall_ms = 1e3 * max(stalls, default=0.0)
    return result


@dataclass
class StoreHAResult:
    """Store-HA chaos drill: N *replicated stores* (each a ReplicatedStore
    + apiserver + WAL stream + lease candidacy, apiserver/replication.py)
    serve a live scheduler + coherence-watcher workload while the PRIMARY
    store is killed mid-flight — the last-SPOF failure the stateless
    rolling-restart drill could never inject. A standby must win the
    lease, replay its WAL prefix, mint the next fencing epoch and take
    the write load; the old primary is then resurrected believing it
    still rules, and its first write must come back FencedWrite with the
    new primary's endpoint — zero writes accepted under the stale epoch,
    zero split-brain. The witness watch stream must stay gapless and
    duplicate-free across the failover (shared rv sequence + FailoverWatch
    since=last_rv resume), and every pod binds exactly once."""

    nodes: int
    pods: int
    seed: int
    replicas: int
    bound: int
    double_binds: int
    promotions: int              # epoch mints past the bootstrap election
    promotion_p99_ms: float      # primary-kill to standby-serving
    epoch: int                   # ruling epoch at drill end
    fenced_rejections: int       # writes the fencing guard turned away
    fenced_leaks: int            # writes ACCEPTED under a stale epoch (0!)
    stale_resurrect_fenced: bool  # the resurrected primary was fenced
    records_streamed: int
    snapshots_sent: int
    snapshots_discarded: int
    watch_events: int
    watch_gaps: int
    watch_dupes: int
    watch_resumes: int
    converged: bool
    racy_writes: int = 0
    loop_stalls: int = 0
    max_stall_ms: float = 0.0
    replica_faults: list = field(default_factory=list)

    @property
    def gate(self) -> bool:
        """The drill's whole contract in one bool (the bench's gate)."""
        return (self.converged and self.double_binds == 0
                and self.fenced_leaks == 0 and self.stale_resurrect_fenced
                and self.promotions >= 1
                and self.watch_gaps == 0 and self.watch_dupes == 0
                and self.racy_writes == 0 and self.loop_stalls == 0)

    def __str__(self) -> str:
        return (f"store-ha R={self.replicas} N={self.nodes} P={self.pods}: "
                f"{self.bound}/{self.pods} bound, "
                f"{self.promotions} promotions p99 "
                f"{self.promotion_p99_ms:.1f}ms epoch {self.epoch}, "
                f"{self.fenced_rejections} fenced "
                f"{self.fenced_leaks} leaks, "
                f"streamed {self.records_streamed} records "
                f"{self.snapshots_sent} snaps, watch "
                f"{self.watch_events} events {self.watch_gaps} gaps "
                f"{self.watch_dupes} dupes")


def run_store_ha(n_nodes: int = 8, n_pods: int = 48, seed: int = 2031,
                 replicas: int = 3,
                 race_detect: bool = True) -> StoreHAResult:
    """Blocking entry point for the store-HA (fenced failover) drill.

    Topology: a StoreReplicaSet of `replicas` replicated stores over one
    coordination quorum wrapped in a seeded FaultPlane (plus RaceDetector
    + loop-stall watchdog when `race_detect` — elector renew/CAS traffic
    ticks the plane continuously, so the op-indexed action schedule fires
    at deterministic points of the lease protocol). The scheduler, a pod
    creator and a resourceVersion-recording witness drive the data plane
    over TCP through primary-chasing RemoteStores. At the 1/3 milestone
    the ruling primary store is KILLED (state and beliefs frozen); at 2/3
    it is resurrected still believing it rules, and a client pinned to it
    proves the fence: FencedWrite carrying the new epoch + endpoint, no
    state mutated, and the deposed primary demotes and rejoins as a
    standby."""
    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver.auth import TokenAuthenticator, UserInfo
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.apiserver.store import (
        AlreadyExists,
        FencedWrite,
        NotFound,
        TooManyRequests,
    )
    from kubernetes_tpu.testing.faults import FaultPlane
    from kubernetes_tpu.testing.races import LoopStallWatchdog, RaceDetector
    from kubernetes_tpu.testing.replicas import StoreReplicaSet

    coord_inner = ObjectStore()
    plane = FaultPlane(coord_inner, seed=seed)
    coord = RaceDetector(plane) if race_detect else plane
    auth = TokenAuthenticator({
        "sched-token": UserInfo("system:kube-scheduler",
                                ("system:authenticated",))})

    freeze_drill_heap()

    sg = StoreReplicaSet(
        coord, n=replicas,
        watch_window=max(1 << 16, 8 * (n_pods + n_nodes)),
        lease_duration=0.6, renew_deadline=0.45, retry_period=0.05,
        server_kwargs={"authenticator": auth}).start()
    for i, control in enumerate(sg.controls()):
        plane.attach_store_replica(i, control)
    watchdog_box: dict = {}

    async def drive() -> StoreHAResult:
        caps = Capacities(num_nodes=1 << max(6, (n_nodes - 1).bit_length()),
                          batch_pods=min(64, max(16, n_pods)))
        sched_client = sg.client(token="sched-token")
        creator = sg.client(token="sched-token")
        watcher_client = sg.client(token="sched-token")
        cap = {"cpu": "16", "memory": "32Gi", "pods": "110"}

        def create_with_retry(obj, deadline_s: float = 30.0) -> None:
            deadline = time.monotonic() + deadline_s
            while True:
                try:
                    creator.create(obj)
                    return
                except AlreadyExists:
                    return  # failover replay: exactly-once held
                except TooManyRequests as e:
                    # thread context (asyncio.to_thread), never the loop
                    time.sleep(max(0.05, getattr(e, "retry_after", 0.0)))  # ktpu: allow[blocking-in-async]
                except ConnectionError:
                    # promotion blackout: NO primary rules for a lease
                    # interval — unlike the stateless drill there is no
                    # other replica that can take the write, so ride it
                    # out (FencedWrite chases internally; what surfaces
                    # here is the every-endpoint-refused window)
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)  # ktpu: allow[blocking-in-async]

        for i in range(n_nodes):
            await asyncio.to_thread(create_with_retry, Node.from_dict({
                "metadata": {"name": f"sha-{i}",
                             "labels": {"kubernetes.io/hostname":
                                        f"sha-{i}"}},
                "status": {"allocatable": dict(cap),
                           "capacity": dict(cap)}}))

        sched = Scheduler(sched_client, caps=caps)
        loop = asyncio.get_running_loop()
        driver = loop.create_task(sched.run())

        # the coherence witness: one logical Pod watch across the whole
        # group, recording (type, rv, key, bound?) for the gapless gate
        # AND the exactly-once-bind gate (a split-brained double bind
        # would surface as two bound-MODIFIEDs for one key)
        observed: list[tuple[str, int, str, bool]] = []
        watcher = watcher_client.watch_resilient("Pod", since=0)
        watch_stop = asyncio.Event()

        async def observe() -> None:
            while not watch_stop.is_set():
                try:
                    ev = await watcher.next(timeout=0.5)
                except ConnectionError:
                    return  # every endpoint stayed dead past the deadline
                if ev is not None:
                    key = (f"{ev.obj.metadata.namespace or 'default'}/"
                           f"{ev.obj.metadata.name}")
                    observed.append(
                        (ev.type, ev.resource_version, key,
                         bool(ev.obj.spec.node_name)))

        observer = loop.create_task(observe())

        async def wait_bound(expect: int, timeout_s: float) -> bool:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                try:
                    pods = await asyncio.to_thread(creator.list, "Pod")
                except ConnectionError:
                    await asyncio.sleep(0.2)
                    continue
                if sum(1 for p in pods if p.spec.node_name) >= expect:
                    return True
                await asyncio.sleep(0.1)
            return False

        async def wait_fault(count: int) -> None:
            deadline = time.monotonic() + 30
            while len(plane.stats.replica_faults) < count \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.01)

        # warm the solver's jit variants BEFORE arming the stall watchdog:
        # first-call XLA compile can hold the GIL past the 100ms stall
        # threshold, which would charge a one-time compile cost against
        # the failover drill's loop-health contract
        n_warm = 2
        for pod in make_pods(n_warm, cpu="100m", memory="64Mi",
                             name_prefix="warm"):
            await asyncio.to_thread(create_with_retry, pod)
        await wait_bound(n_warm, 120)
        if race_detect:
            # the store-group loop legitimately fsyncs WAL compactions and
            # shares the GIL with solver jit on the driver loop, so give it
            # headroom over the 100ms default; real blocking bugs in the
            # replication path (sync reads, time.sleep) stall far longer
            sg._call(lambda: watchdog_box.update(
                dog=LoopStallWatchdog(threshold_s=0.25).start()))

        victim = sg.primary_index()
        kill_at = max(1, n_pods // 3)
        resurrect_at = max(kill_at + 1, (2 * n_pods) // 3)
        stale_fenced = False
        stale_fence_epoch = 0
        proof = make_pods(1, cpu="100m", memory="64Mi",
                          name_prefix="stale-proof")[0]
        faults_seen = 0
        for i, pod in enumerate(make_pods(n_pods, cpu="100m",
                                          memory="64Mi",
                                          name_prefix="sha")):
            if i == kill_at:
                # op-indexed on the COORDINATION plane: the elector's next
                # renew/CAS pulls the trigger, same point every replay
                plane.schedule(
                    plane.stats.ops + 1,
                    lambda p, v=victim: p.kill_store_replica(v),
                    f"kill-store-primary-{victim}")
                faults_seen += 1
                await asyncio.to_thread(create_with_retry, pod)
                await wait_fault(faults_seen)
                # a standby must promote before writes flow again;
                # create_with_retry above already rode the blackout
            elif i == resurrect_at:
                plane.schedule(
                    plane.stats.ops + 1,
                    lambda p, v=victim: p.resurrect_store_replica(v),
                    f"resurrect-store-{victim}")
                faults_seen += 1
                await asyncio.to_thread(create_with_retry, pod)
                await wait_fault(faults_seen)
                # the resurrectee still believes it is primary at the old
                # epoch: a client pinned to it must get FencedWrite, and
                # its state must stay untouched (verified below via the
                # everywhere-absent proof pod)
                stale = sg.replicas[victim]
                deadline = time.monotonic() + 10
                while stale.killed and time.monotonic() < deadline:
                    await asyncio.sleep(0.01)
                pinned = RemoteStore(stale.host, stale.api_port,
                                     token="sched-token")

                def poke():
                    try:
                        pinned.create(proof)
                        return "accepted"
                    except FencedWrite as e:
                        return ("fenced", e.epoch)
                    except ConnectionError:
                        return "conn"

                outcome = await asyncio.to_thread(poke)
                if isinstance(outcome, tuple):
                    stale_fenced = True
                    stale_fence_epoch = outcome[1]
            else:
                await asyncio.to_thread(create_with_retry, pod)
        conv = await wait_bound(n_warm + n_pods, 240)

        # fence the coherence check at the ruling primary's revision,
        # then let the witness catch up before comparing histories
        p_idx = sg.wait_for_primary(10)
        primary = sg.replicas[p_idx].store
        fence_rv = primary.resource_version
        deadline = time.monotonic() + 30
        while (watcher.last_rv or 0) < fence_rv \
                and time.monotonic() < deadline \
                and not observer.done():
            await asyncio.sleep(0.05)
        watch_stop.set()
        watcher.stop()
        observer.cancel()
        driver.cancel()
        sched.stop()

        # the fenced-leak proof: the stale write must exist NOWHERE — not
        # on the ruling primary, not on the resurrectee's own copy
        leaks = 0
        for replica in sg.replicas:
            try:
                replica.store.get("Pod", proof.metadata.name)
                leaks += 1
            except NotFound:
                pass
        if not stale_fenced:
            leaks += 1  # the poke was swallowed or accepted: count it

        expected = [e.resource_version for e in primary._history
                    if e.kind == "Pod" and e.resource_version <= fence_rv]
        got = [rv for _, rv, _, _ in observed if rv <= fence_rv]
        gaps = len(set(expected) - set(got))
        dupes = len(got) - len(set(got))
        # exactly-once binds, judged from the AUTHORITATIVE timeline (the
        # ruling primary's history): a split-brained double bind would
        # show as a second unbound->bound transition for one key, or a
        # bound pod silently moving nodes. Post-bind MODIFIEDs that keep
        # the assignment (trace-annotation stamps on sampled batches,
        # condition writes) are not binds. The witness is the wrong judge
        # for this — it may legitimately have observed a bind the dead
        # primary acked but never replicated (the async-replication ack
        # window); that bind is not in the surviving timeline and the
        # scheduler's retry is the recovery, not a bug.
        bind_counts: dict[str, int] = {}
        last_node: dict[str, str] = {}
        for e in primary._history:
            if e.kind != "Pod" or e.resource_version > fence_rv:
                continue
            key = (f"{e.obj.metadata.namespace or 'default'}/"
                   f"{e.obj.metadata.name}")
            if e.type == "DELETED":
                last_node.pop(key, None)
                continue
            node = e.obj.spec.node_name or ""
            prev = last_node.get(key, "")
            if node and (not prev or node != prev):
                bind_counts[key] = bind_counts.get(key, 0) + 1
            last_node[key] = node
        double = sum(1 for v in bind_counts.values() if v > 1)
        bound_final = sum(
            1 for p in primary.list("Pod")
            if p.spec.node_name and p.metadata.name.startswith("sha-"))
        return StoreHAResult(
            nodes=n_nodes, pods=n_pods, seed=seed, replicas=replicas,
            bound=bound_final, double_binds=double,
            promotions=sum(1 for _, ep in sg.promotions if ep >= 2),
            promotion_p99_ms=_p99_ms(
                [s / 1e3 for s in sg.promotion_samples_ms]),
            epoch=max((r.store.epoch for r in sg.replicas), default=0),
            fenced_rejections=sum(
                r.store.fenced_writes for r in sg.replicas),
            fenced_leaks=leaks,
            stale_resurrect_fenced=(stale_fenced
                                    and stale_fence_epoch >= 2),
            records_streamed=sum(r.records_sent for r in sg.replicas),
            snapshots_sent=sum(r.snapshots_sent for r in sg.replicas),
            snapshots_discarded=sum(
                r.snapshots_discarded for r in sg.replicas),
            watch_events=len(got), watch_gaps=gaps, watch_dupes=dupes,
            watch_resumes=watcher.resumes,
            converged=(conv and bound_final >= n_pods),
            racy_writes=len(coord.racy_writes) if race_detect else 0,
            replica_faults=list(plane.stats.replica_faults))

    try:
        result = asyncio.run(drive())
    finally:
        stalls = sg._call(watchdog_box["dog"].stop) \
            if watchdog_box else []
        sg.stop()
        thaw_drill_heap()
    result.loop_stalls = len(stalls)
    result.max_stall_ms = 1e3 * max(stalls, default=0.0)
    return result


@dataclass
class FanoutResult:
    """Watch-cache fan-out drill: N subscribers, M store events, and the
    proof that the store did O(M) work — `store_fanout_puts` counts one
    queue put per event (the cache's single subscription), not N*M."""

    watchers: int
    events: int
    store_fanout_puts: int
    deliveries: int
    events_per_sec: float
    evicted: int

    def __str__(self) -> str:
        return (f"fanout W={self.watchers} E={self.events}: store did "
                f"{self.store_fanout_puts} puts, cache delivered "
                f"{self.deliveries} ({self.events_per_sec:.0f}/s, "
                f"{self.evicted} evicted)")


async def _run_watch_fanout(watchers: int, events: int) -> FanoutResult:
    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver.watchcache import WatchCache

    store = ObjectStore(watch_window=max(1 << 14, 4 * events))
    cache = WatchCache(store).start()
    subs = [cache.watch("Node") for _ in range(watchers)]
    base = store.fanout_puts
    t0 = time.perf_counter()
    store.create(Node.from_dict({"metadata": {"name": "fan"}}))
    for i in range(events - 1):
        store.guaranteed_update(
            "Node", "fan", "default",
            lambda n, i=i: n.metadata.labels.update({"tick": str(i)}))

    async def drain(sub) -> int:
        got = 0
        while got < events:
            ev = await sub.next(timeout=10.0)
            if ev is None:
                break
            got += 1
        return got

    counts = await asyncio.gather(*(drain(s) for s in subs))
    dt = max(time.perf_counter() - t0, 1e-9)
    cache.stop()
    return FanoutResult(
        watchers=watchers, events=events,
        store_fanout_puts=store.fanout_puts - base,
        deliveries=sum(counts),
        events_per_sec=sum(counts) / dt,
        evicted=cache.evictions)


def run_watch_fanout(watchers: int = 10_000,
                     events: int = 100) -> FanoutResult:
    """Blocking entry point for the watch-cache fan-out drill."""
    return asyncio.run(_run_watch_fanout(watchers, events))


@dataclass
class FanoutXLResult:
    """Sharded fan-out scale drill (bench[fanout-xl]): 100k sink watchers
    on shard threads vs the single-loop fallback, in one process. The
    contracts proven here: deliveries/s ≥ gate× the single-loop baseline,
    store puts exactly O(events), zero slow-consumer evictions at nominal
    rate, encode-once (frames_encoded == events while frames_delivered ==
    deliveries), a witness stream gapless/dup-free against store history
    at a fence rv, and scheduler e2e p99 unperturbed while the flood
    runs."""

    watchers: int
    events: int               # burst + nominal store events
    shards: int
    store_fanout_puts: int
    deliveries: int           # sharded sink deliveries (burst + nominal)
    events_per_sec: float     # burst-phase sink deliveries/s
    baseline_watchers: int
    baseline_deliveries: int
    baseline_events_per_sec: float  # single-loop (shards=0) queue mode
    speedup: float
    evicted: int
    frames_encoded: int       # registry delta over the sharded phases
    frames_delivered: int
    encode_ratio: float       # delivered / encoded
    witness_events: int
    witness_gaps: int
    witness_dupes: int
    sched_p99_base_ms: float      # batch e2e p99, scheduler alone
    sched_p99_flood_ms: float     # same workload under the nominal flood
    sched_pods_per_sec_base: float
    sched_pods_per_sec_flood: float

    def __str__(self) -> str:
        return (f"fanout-xl W={self.watchers} E={self.events} "
                f"S={self.shards}: {self.deliveries} deliveries "
                f"({self.events_per_sec:.0f}/s, {self.speedup:.1f}x the "
                f"single-loop {self.baseline_events_per_sec:.0f}/s), "
                f"store {self.store_fanout_puts} puts, "
                f"{self.evicted} evicted, encode ratio "
                f"{self.encode_ratio:.0f}:1, witness "
                f"{self.witness_events} events {self.witness_gaps} gaps "
                f"{self.witness_dupes} dupes, sched p99 "
                f"{self.sched_p99_base_ms:.1f}->"
                f"{self.sched_p99_flood_ms:.1f}ms")


async def _sched_round(n_nodes: int, n_pods: int) -> tuple[float, float]:
    """One scheduler workload round on its own store: returns
    (pods_per_sec, batch-e2e p99 ms). The fanout-xl perturbation probe —
    same process, loop and GIL as the flood, separate store."""
    store = ObjectStore()
    for node in make_nodes(n_nodes):
        store.create(node)
    caps = Capacities(num_nodes=1 << max(4, (n_nodes - 1).bit_length()),
                      batch_pods=min(64, max(8, n_pods)))
    sched = Scheduler(store, caps=caps)
    await sched.start()
    for pod in make_pods(n_pods, cpu="100m", memory="64Mi",
                         name_prefix="xl"):
        store.create(pod)
    await asyncio.sleep(0)
    samples: list[float] = []
    done = 0
    idle = 0
    t0 = time.perf_counter()
    while done < n_pods and idle < 5:
        tb = time.perf_counter()
        got = await sched.schedule_pending(wait=0.2)
        if got:
            samples.append(time.perf_counter() - tb)
            done += got
            idle = 0
        else:
            idle = 0 if sched.inflight_batches > 0 else idle + 1
    dt = max(time.perf_counter() - t0, 1e-9)
    sched.stop()
    return done / dt, _p99_ms(samples)


async def _run_fanout_xl(watchers: int, events: int, nominal_events: int,
                         baseline_watchers: int, sched_nodes: int,
                         sched_pods: int) -> FanoutXLResult:
    from array import array

    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver import watchcache as wc

    mx = wc._metrics()

    def tick(store, label: str, i: int) -> None:
        store.guaranteed_update(
            "Node", "fan", "default",
            lambda n, i=i: n.metadata.labels.update({label: str(i)}))

    # ---- phase 0: scheduler alone — the perturbation baseline ----
    pps_base, p99_base = await _sched_round(sched_nodes, sched_pods)

    # ---- phase 1: single-loop baseline (the KTPU_FANOUT_SHARDS=0
    # fallback, queue mode — exactly the pre-shard bench[fanout] shape) ----
    base_store = ObjectStore(watch_window=max(1 << 12, 4 * events))
    base_cache = wc.WatchCache(base_store, shards=0).start()
    base_subs = [base_cache.watch("Node")
                 for _ in range(baseline_watchers)]

    async def drain(sub) -> int:
        got = 0
        while got < events:
            ev = await sub.next(timeout=10.0)
            if ev is None:
                break
            got += 1
        return got

    tb0 = time.perf_counter()
    base_store.create(Node.from_dict({"metadata": {"name": "fan"}}))
    for i in range(events - 1):
        tick(base_store, "tick", i)
    base_counts = await asyncio.gather(*(drain(s) for s in base_subs))
    base_dt = max(time.perf_counter() - tb0, 1e-9)
    base_deliveries = sum(base_counts)
    base_rate = base_deliveries / base_dt
    await base_cache.aclose()

    # ---- phase 2: sharded burst at full scale ----
    total_events = events + nominal_events
    store = ObjectStore(watch_window=max(1 << 12, 4 * total_events + 64))
    cache = wc.WatchCache(store).start()
    if not cache.sharded:
        raise RuntimeError(
            "bench[fanout-xl] needs KTPU_FANOUT_SHARDS >= 1")
    counts = array("q", [0] * watchers)
    handles = []
    for i in range(watchers):
        def sink(frame, _i=i, _counts=counts):
            _counts[_i] += 1
            frame.json_bytes()  # the wire bytes all sinks share
        handles.append(cache.watch_sink("Node", sink=sink))

    rv0 = store.resource_version
    witness = cache.watch(None)  # coherence witness, queue mode
    puts0 = store.fanout_puts
    enc0 = mx[1].labels().value
    dlv0 = mx[2].labels().value
    observed: list[tuple[str, int]] = []

    async def observe() -> None:
        while True:
            ev = await witness.next(timeout=2.0)
            if ev is None:
                if witness._stopped:
                    return
                continue
            observed.append((ev.type, ev.resource_version))

    observer = asyncio.get_running_loop().create_task(observe())

    async def settle(expect: int, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while sum(counts) < expect and time.monotonic() < deadline:
            await asyncio.sleep(0.005)

    t0 = time.perf_counter()
    store.create(Node.from_dict({"metadata": {"name": "fan"}}))
    for i in range(events - 1):
        tick(store, "tick", i)
    await settle(watchers * events, 120.0)
    dt = max(time.perf_counter() - t0, 1e-9)
    burst_deliveries = sum(counts)
    rate = burst_deliveries / dt

    # ---- phase 3: nominal-rate flood + concurrent scheduler round ----
    async def paced() -> None:
        for i in range(nominal_events):
            tick(store, "nom", i)
            await asyncio.sleep(0.05)

    (pps_flood, p99_flood), _ = await asyncio.gather(
        _sched_round(sched_nodes, sched_pods), paced())
    await settle(watchers * total_events, 60.0)

    # ---- fence + witness coherence (the bench[ha] diff shape) ----
    fence = store.resource_version
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if observed and observed[-1][1] >= fence:
            break
        await asyncio.sleep(0.02)
    witness.stop()
    observer.cancel()
    try:
        await observer
    except asyncio.CancelledError:
        pass

    expected = [e.resource_version for e in store._history
                if rv0 < e.resource_version <= fence]
    got = [rv for _, rv in observed if rv <= fence]
    gaps = len(set(expected) - set(got))
    dupes = len(got) - len(set(got))

    deliveries = sum(counts)
    encoded = int(mx[1].labels().value - enc0)
    delivered = int(mx[2].labels().value - dlv0)
    puts = store.fanout_puts - puts0
    shards_n = cache.shards_n
    evicted = cache.evictions
    for h in handles:
        h.stop()
    await cache.aclose()
    return FanoutXLResult(
        watchers=watchers, events=total_events, shards=shards_n,
        store_fanout_puts=puts, deliveries=deliveries,
        events_per_sec=rate,
        baseline_watchers=baseline_watchers,
        baseline_deliveries=base_deliveries,
        baseline_events_per_sec=base_rate,
        speedup=rate / max(base_rate, 1e-9),
        evicted=evicted,
        frames_encoded=encoded, frames_delivered=delivered,
        encode_ratio=delivered / max(encoded, 1),
        witness_events=len(got), witness_gaps=gaps, witness_dupes=dupes,
        sched_p99_base_ms=p99_base, sched_p99_flood_ms=p99_flood,
        sched_pods_per_sec_base=pps_base,
        sched_pods_per_sec_flood=pps_flood)


def run_fanout_xl(watchers: int = 100_000, events: int = 12,
                  nominal_events: int = 8,
                  baseline_watchers: int = 10_000,
                  sched_nodes: int = 32,
                  sched_pods: int = 128) -> FanoutXLResult:
    """Blocking entry point for the sharded fan-out scale drill."""
    return asyncio.run(_run_fanout_xl(watchers, events, nominal_events,
                                      baseline_watchers, sched_nodes,
                                      sched_pods))


@dataclass
class MonitorBenchResult:
    """Monitoring-plane overhead drill: a Monitor scrapes a fleet of real
    ObsServers (each over its own churning registry) at a fixed interval
    while instant queries run against the TSDB. The contract: zero scrape
    failures, and the TSDB stays bounded — the series count stops growing
    once the fleet's label space is discovered (no per-scrape series
    leak)."""

    targets: int
    seconds: float
    interval: float
    scrapes: int
    scrape_failures: int
    samples_ingested: int
    samples_per_sec: float
    scrape_p99_ms: float
    query_p99_ms: float
    tsdb_series: int
    tsdb_samples: int
    series_stable: bool

    def __str__(self) -> str:
        return (f"monitor T={self.targets} @{self.interval}s x"
                f"{self.seconds:.0f}s: {self.scrapes} scrapes "
                f"({self.scrape_failures} failed), "
                f"{self.samples_per_sec:.0f} samples/s, scrape p99 "
                f"{self.scrape_p99_ms:.1f}ms, query p99 "
                f"{self.query_p99_ms:.2f}ms, {self.tsdb_series} series "
                f"({'stable' if self.series_stable else 'GROWING'})")


async def _run_monitor_bench(n_targets: int, seconds: float,
                             interval: float,
                             retention_samples: int = 120,
                             seed: int = 7) -> MonitorBenchResult:
    import random as _random

    from kubernetes_tpu.obs.http import ObsServer
    from kubernetes_tpu.obs.metrics import Registry
    from kubernetes_tpu.obs.monitor import Monitor

    rng = _random.Random(seed)
    servers: list[ObsServer] = []
    churners: list[tuple] = []
    for i in range(n_targets):
        reg = Registry()
        reqs = reg.counter("bench_requests_total", "synthetic traffic",
                           labels=("code",))
        lat = reg.histogram("bench_request_duration_seconds",
                            "synthetic latency")
        srv = ObsServer(registry=reg)
        await srv.start()
        servers.append(srv)
        churners.append((reqs, lat))
    monitor = Monitor(store=None, interval=interval,
                      retention_samples=retention_samples,
                      include_builtin_rules=False)
    for i, srv in enumerate(servers):
        monitor.add_static_target(f"bench-{i}", srv.url)

    stop = asyncio.Event()

    async def churn() -> None:
        # keep every target's exposition moving between scrapes so counter
        # deltas and histogram fills are real, not a static page re-read.
        # Every code label ticks every round: the fleet's full label space
        # exists from the first scrape, so the stability gate below is a
        # real leak detector, not label-discovery noise
        while not stop.is_set():
            for reqs, lat in churners:
                for code in ("200", "429", "500"):
                    reqs.labels(code).inc(rng.randrange(1, 20))
                lat.observe(rng.random() / 10)
            await asyncio.sleep(interval / 4)

    churn_task = asyncio.get_running_loop().create_task(churn())
    scrape_ms: list[float] = []
    query_ms: list[float] = []
    series_mid = 0
    t_end = time.perf_counter() + seconds
    n_scrapes = 0
    while time.perf_counter() < t_end:
        t0 = time.perf_counter()
        await monitor.scrape_once()
        scrape_ms.append(1e3 * (time.perf_counter() - t0))
        n_scrapes += 1
        for expr in (f'rate(bench_requests_total[{4 * interval}s])',
                     'histogram_quantile(0.99, '
                     f'bench_request_duration_seconds_bucket'
                     f'[{4 * interval}s])',
                     'sum by (code) (bench_requests_total)'):
            q0 = time.perf_counter()
            monitor.query(expr)
            query_ms.append(1e3 * (time.perf_counter() - q0))
        if n_scrapes == 2:
            # by the second scrape every target's full label space has
            # been seen: growth beyond this point is a series leak
            series_mid = monitor.tsdb.series_count()
        await asyncio.sleep(
            max(0.0, interval - (time.perf_counter() - t0)))
    stop.set()
    churn_task.cancel()
    for srv in servers:
        await srv.stop()

    failures = sum(
        child.value
        for _v, child in monitor._mx_failures.children())
    ingested = monitor._mx_samples.labels().value
    return MonitorBenchResult(
        targets=n_targets, seconds=seconds, interval=interval,
        scrapes=n_scrapes, scrape_failures=int(failures),
        samples_ingested=int(ingested),
        samples_per_sec=ingested / max(seconds, 1e-9),
        scrape_p99_ms=sorted(scrape_ms)[int(0.99 * (len(scrape_ms) - 1))]
        if scrape_ms else 0.0,
        query_p99_ms=sorted(query_ms)[int(0.99 * (len(query_ms) - 1))]
        if query_ms else 0.0,
        tsdb_series=monitor.tsdb.series_count(),
        tsdb_samples=monitor.tsdb.sample_count(),
        series_stable=(series_mid > 0
                       and monitor.tsdb.series_count() <= series_mid))


def run_monitor_bench(n_targets: int = 5, seconds: float = 10.0,
                      interval: float = 1.0,
                      retention_samples: int = 120,
                      seed: int = 7) -> MonitorBenchResult:
    """Blocking entry point for the monitoring-plane overhead drill."""
    return asyncio.run(_run_monitor_bench(n_targets, seconds, interval,
                                          retention_samples, seed=seed))


@dataclass
class MultiProcResult:
    """Multi-process control-plane drill (bench[multiproc]): a store-owner
    process feeding N worker processes over the shared-memory event ring,
    A/B'd against the in-process sharded topology at the same shape. The
    contracts: every worker's sinks see every event as the owner's
    encode-once wire bytes (owner frames_encoded == ring appends == store
    resourceVersion; worker re-encodes == 0), a SIGKILL'd worker is
    reaped + respawned without replaying delivered frames or double-
    binding a pod, the cross-process witness stream is gapless/dup-free
    against the owner's authoritative history at a fence rv, and the
    monitoring plane discovers every worker's per-process /metrics and
    scrapes the fleet with zero failures."""

    workers: int
    shards: int
    watchers: int             # total bench sinks across the fleet
    events: int               # Node burst events
    inproc_deliveries: int
    inproc_events_per_sec: float
    deliveries: int           # cross-process aggregate sink deliveries
    events_per_sec: float
    speedup: float            # cross-process rate / in-process rate
    ring_appends: int
    store_events: int         # owner store resourceVersion delta
    owner_frames_encoded: int
    worker_frames_encoded: int  # sum across workers — must stay 0
    pods: int
    bound: int
    double_binds: int
    bind_conflicts: int       # replayed binds answered Conflict
    kills: int
    respawns: int
    reaped: list = field(default_factory=list)
    failovers: int = 0
    witness_events: int = 0
    witness_gaps: int = 0
    witness_dupes: int = 0
    monitor_targets: int = 0
    scrapes: int = 0
    scrape_failures: int = 0

    @property
    def gate(self) -> bool:
        """Correctness contract in one bool (speedup gates separately —
        it is a perf target, not a correctness invariant)."""
        return (self.ring_appends == self.store_events
                and self.owner_frames_encoded == self.ring_appends
                and self.worker_frames_encoded == 0
                and self.deliveries >= self.watchers * self.events
                and self.bound == self.pods and self.double_binds == 0
                and self.witness_gaps == 0 and self.witness_dupes == 0
                and self.respawns >= 1 and 0 in self.reaped
                and self.monitor_targets >= self.workers
                and self.scrape_failures == 0)

    def __str__(self) -> str:
        return (f"multiproc W={self.workers}x{self.watchers // max(self.workers, 1)} "
                f"E={self.events} S={self.shards}: {self.deliveries} "
                f"deliveries ({self.events_per_sec:.0f}/s, "
                f"{self.speedup:.2f}x in-process "
                f"{self.inproc_events_per_sec:.0f}/s), ring "
                f"{self.ring_appends} appends / {self.store_events} events, "
                f"worker re-encodes {self.worker_frames_encoded}, "
                f"{self.bound}/{self.pods} bound "
                f"({self.double_binds} double, {self.bind_conflicts} "
                f"replay-conflicts), witness {self.witness_events} events "
                f"{self.witness_gaps} gaps {self.witness_dupes} dupes, "
                f"monitor {self.monitor_targets} targets "
                f"{self.scrape_failures} failed scrapes")


def _worker_metric(host: str, port: int, name: str) -> float:
    """Blocking: read one unlabeled counter from a worker's /metrics."""
    import urllib.request

    with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5.0) as resp:
        text = resp.read().decode("utf-8", "replace")
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            rest = line[len(name):]
            if rest[:1] not in ("", " ", "{", "\t"):
                continue  # a longer family sharing the prefix
            total += float(rest.rsplit(None, 1)[-1])
    return total


async def _inproc_fanout_round(watchers: int, events: int,
                               shards: int) -> tuple[int, float]:
    """The A side: today's single-process topology (KTPU_WORKER_PROCS=0)
    at the drill's shape — sharded fan-out, all sinks in one process."""
    from array import array

    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver import watchcache as wc

    store = ObjectStore(watch_window=max(1 << 12, 4 * events))
    cache = wc.WatchCache(store, shards=shards).start()
    counts = array("q", [0] * watchers)
    handles = []
    for i in range(watchers):
        def sink(frame, _i=i, _counts=counts):
            _counts[_i] += 1
            frame.json_bytes()
        handles.append(cache.watch_sink("Node", sink=sink))
    t0 = time.perf_counter()
    store.create(Node.from_dict({"metadata": {"name": "fan"}}))
    for i in range(events - 1):
        store.guaranteed_update(
            "Node", "fan", "default",
            lambda n, i=i: n.metadata.labels.update({"tick": str(i)}))
    deadline = time.monotonic() + 60
    expect = watchers * events
    while sum(counts) < expect and time.monotonic() < deadline:
        await asyncio.sleep(0.005)
    dt = max(time.perf_counter() - t0, 1e-9)
    deliveries = sum(counts)
    for h in handles:
        h.stop()
    await cache.aclose()
    return deliveries, deliveries / dt


def run_multiproc(workers: int = 2, per_worker_watchers: int = 100,
                  events: int = 20, n_pods: int = 24,
                  shards: int | None = None,
                  ring_capacity: int = 1 << 20) -> MultiProcResult:
    """Blocking entry point for the multi-process control-plane drill.

    Five phases: (1) in-process sharded baseline at the same total-sink
    shape; (2) cross-process burst — Node events appended once by the
    owner, fanned out by every worker's shard threads, aggregate delivery
    rate read from each worker's own /metrics; (3) rolling worker-kill
    bind drill — SIGKILL mid-binds, owner reaps the ring slot, the
    respawn resumes without replaying delivered frames, replayed binds
    answer Conflict (exactly-once); (4) cross-process witness diff
    against the owner's authoritative history at a fence rv; (5) the
    monitoring plane discovers every worker's /metrics through the
    advertised Endpoints and scrapes the fleet."""
    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.apiserver.store import (
        AlreadyExists,
        Binding,
        Conflict,
        TooManyRequests,
    )
    from kubernetes_tpu.obs.monitor import Monitor
    from kubernetes_tpu.testing.replicas import MultiProcCluster

    total_watchers = workers * per_worker_watchers
    cap = {"cpu": "16", "memory": "32Gi", "pods": "110"}
    n_bind_nodes = 4

    cluster = MultiProcCluster(
        n=workers, shards=shards, ring_capacity=ring_capacity,
        bench_watchers=per_worker_watchers, bench_kind="Node",
        advertise=True).start()

    async def drive() -> MultiProcResult:
        # ---- phase 1: in-process baseline, same total shape ----
        shards_n = shards if shards is not None else 2
        inproc_deliveries, inproc_rate = await _inproc_fanout_round(
            total_watchers, events, shards_n)

        client = cluster.client()
        witness_client = cluster.client()
        ports = [p for _, p in cluster.endpoints]
        host = cluster.host

        def delivered_sum(alive_ports) -> float:
            return sum(_worker_metric(
                host, p, "watchcache_frames_delivered_total")
                for p in alive_ports)

        # the cross-process witness: a resilient Pod watch through the
        # worker fleet, recording every (type, rv) across the kill
        observed: list[tuple[str, int]] = []
        watcher = witness_client.watch_resilient("Pod", since=0)
        watch_stop = asyncio.Event()

        async def observe() -> None:
            while not watch_stop.is_set():
                try:
                    ev = await watcher.next(timeout=0.5)
                except ConnectionError:
                    return
                if ev is not None:
                    observed.append((ev.type, ev.resource_version))

        observer = asyncio.get_running_loop().create_task(observe())

        # bind targets, created before the measured burst so their fan-out
        # doesn't pollute the delivery ledger
        for i in range(n_bind_nodes):
            await asyncio.to_thread(client.create, Node.from_dict({
                "metadata": {"name": f"mp-{i}",
                             "labels": {"kubernetes.io/hostname": f"mp-{i}"}},
                "status": {"allocatable": dict(cap),
                           "capacity": dict(cap)}}))
        # quiesce: wait until the node-creation fan-out stops moving
        prev = -1.0
        while True:
            cur = await asyncio.to_thread(delivered_sum, ports)
            if cur == prev:
                break
            prev = cur
            await asyncio.sleep(0.05)
        base_delivered = prev

        # ---- phase 2: cross-process burst ----
        expect = total_watchers * events
        t0 = time.perf_counter()
        await asyncio.to_thread(
            client.create, Node.from_dict({"metadata": {"name": "fan"}}))
        for i in range(events - 1):
            await asyncio.to_thread(
                client.guaranteed_update, "Node", "fan", "default",
                lambda n, i=i: n.metadata.labels.update({"tick": str(i)}))
        deadline = time.monotonic() + 120
        delivered = 0.0
        while time.monotonic() < deadline:
            delivered = await asyncio.to_thread(delivered_sum, ports)
            if delivered - base_delivered >= expect:
                break
            await asyncio.sleep(0.005)
        dt = max(time.perf_counter() - t0, 1e-9)
        deliveries = int(delivered - base_delivered)
        rate = deliveries / dt
        worker_encoded = int(sum(await asyncio.gather(*(
            asyncio.to_thread(_worker_metric, host, p,
                              "watchcache_frames_encoded_total")
            for p in ports))))

        # ---- phase 3: rolling worker-kill bind drill ----
        def create_with_retry(pod) -> None:
            while True:
                try:
                    client.create(pod)
                    return
                except AlreadyExists:
                    return  # failover replay: exactly-once held
                except TooManyRequests as e:
                    time.sleep(max(0.05, getattr(e, "retry_after", 0.0)))  # ktpu: allow[blocking-in-async]

        acks: dict[str, int] = {}
        conflicts = 0

        def bind_with_retry(name: str, node: str) -> None:
            nonlocal conflicts
            for _ in range(64):
                try:
                    client.bind(Binding(pod_name=name, namespace="default",
                                        target_node=node))
                    acks[name] = acks.get(name, 0) + 1
                    return
                except Conflict:
                    # the first send landed before its worker died: the
                    # authoritative store already holds the bind, and the
                    # replay is refused — the exactly-once evidence
                    conflicts += 1
                    return
                except ConnectionError:
                    time.sleep(0.02)  # ktpu: allow[blocking-in-async]
            raise RuntimeError(f"bind of {name} never reached the owner")

        pods = list(make_pods(n_pods, cpu="100m", memory="64Mi",
                              name_prefix="mp"))
        kills = 0
        for i, pod in enumerate(pods):
            await asyncio.to_thread(create_with_retry, pod)
            if i == n_pods // 2:
                # SIGKILL mid-binds: no drain frame, no shm detach — the
                # owner's liveness sweep must reclaim the ring slot
                await asyncio.to_thread(cluster.kill_worker, 0)
                kills += 1
            await asyncio.to_thread(bind_with_retry, pod.metadata.name,
                                    f"mp-{i % n_bind_nodes}")
        reaped = await asyncio.to_thread(cluster.reap_dead)
        await asyncio.to_thread(cluster.respawn_worker, 0)
        bound = sum(
            1 for p in await asyncio.to_thread(client.list, "Pod")
            if p.spec.node_name)
        double = sum(1 for v in acks.values() if v > 1)

        # ---- phase 4: witness coherence at a fence rv ----
        fence = cluster.store.resource_version
        deadline = time.monotonic() + 30
        while (watcher.last_rv or 0) < fence \
                and time.monotonic() < deadline \
                and not observer.done():
            await asyncio.sleep(0.05)
        watch_stop.set()
        watcher.stop()
        observer.cancel()
        try:
            await observer
        except asyncio.CancelledError:
            pass
        expected = [e.resource_version for e in cluster.store._history
                    if e.kind == "Pod" and e.resource_version <= fence]
        got = [rv for _, rv in observed if rv <= fence]
        gaps = len(set(expected) - set(got))
        dupes = len(got) - len(set(got))

        # ---- phase 5: fleet scrape over discovered worker targets ----
        monitor = Monitor(store=cluster.client(), interval=0.5,
                          include_builtin_rules=False)
        targets = [t for t in monitor.targets() if t.job == "apiserver"]
        scrapes = 0
        for _ in range(3):
            await monitor.scrape_once()
            scrapes += 1
        failures = int(sum(
            child.value for _v, child in monitor._mx_failures.children()))

        owner = cluster.owner
        return MultiProcResult(
            workers=workers, shards=cluster.specs[0].shards or 0,
            watchers=total_watchers, events=events,
            inproc_deliveries=inproc_deliveries,
            inproc_events_per_sec=inproc_rate,
            deliveries=deliveries, events_per_sec=rate,
            speedup=rate / max(inproc_rate, 1e-9),
            ring_appends=owner.ring.appends,
            store_events=cluster.store.resource_version,
            owner_frames_encoded=owner.frames_encoded,
            worker_frames_encoded=worker_encoded,
            pods=n_pods, bound=bound, double_binds=double,
            bind_conflicts=conflicts, kills=kills,
            respawns=cluster.respawns, reaped=reaped,
            failovers=(client.failover_total
                       + witness_client.failover_total),
            witness_events=len(got), witness_gaps=gaps,
            witness_dupes=dupes,
            monitor_targets=len(targets), scrapes=scrapes,
            scrape_failures=failures)

    try:
        return asyncio.run(drive())
    finally:
        cluster.stop()


@dataclass
class SolverSvcResult:
    """Solver-as-a-service drill: M tenant control planes — one speaking
    the stock extender wire protocol with full node objects, the rest the
    native batch-solve endpoint — share ONE continuous-batching device
    program. Gates (all armed, even in --smoke): every pod binds exactly
    once per tenant under the RaceDetector, zero cross-tenant assignments,
    a noisy tenant's flood moves the stock-wire victim's p99 by at most
    5x, and the multi-tenant aggregate throughput at least matches a
    single tenant pushing the same total shape through the same service
    (the continuous-batching claim, measured)."""

    tenants: int
    nodes_per_tenant: int
    pods_per_tenant: int
    seed: int
    bound: int
    expected_bound: int
    double_binds: int
    isolation_violations: int     # service counter (refused row decodes)
    cross_tenant_assignments: int  # audit: assigned node not the tenant's
    # victim = the stock-extender-wire tenant; SERVER-side seat-to-response
    # latencies from its per-tenant sample ring, unloaded vs noisy flood
    p99_unloaded_ms: float
    p99_loaded_ms: float
    flood_requests: int
    flood_rejected: int
    solo_pods_per_sec: float
    agg_pods_per_sec: float
    steps: int
    occupancy_max: int
    converged: bool
    racy_writes: int = 0

    @property
    def p99_bounded(self) -> bool:
        """Same contract as the overload drill: loaded p99 within 5x
        unloaded, 100ms floor for scheduler-jitter noise at CI scale."""
        return self.p99_loaded_ms <= max(5 * self.p99_unloaded_ms, 100.0)

    @property
    def batching_wins(self) -> bool:
        return self.agg_pods_per_sec >= self.solo_pods_per_sec

    def __str__(self) -> str:
        return (f"solver-svc M={self.tenants} N={self.nodes_per_tenant}/t "
                f"P={self.pods_per_tenant}/t: {self.bound}/"
                f"{self.expected_bound} bound, victim p99 "
                f"{self.p99_unloaded_ms:.1f}ms -> {self.p99_loaded_ms:.1f}"
                f"ms under flood ({self.flood_rejected}/"
                f"{self.flood_requests} shed), "
                f"agg {self.agg_pods_per_sec:.0f} vs solo "
                f"{self.solo_pods_per_sec:.0f} pods/s, {self.steps} steps")


def _svc_post(base: str, path: str, payload: dict,
              timeout: float = 30.0) -> tuple[int, dict | list]:
    import json as _json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        base + path, data=_json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, _json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, _json.loads(body or b"{}")
        except ValueError:
            return e.code, {}


def run_solver_svc(n_tenants: int = 4, nodes_per_tenant: int = 32,
                   pods_per_tenant: int = 96, seed: int = 2026,
                   req_pods: int = 8, batch_pods: int = 64,
                   window_ms: float = 2.0, seats: int = 2,
                   queue_wait_s: float = 0.02, flood_threads: int = 12,
                   race_detect: bool = True) -> SolverSvcResult:
    """Blocking entry point for the solver-as-a-service drill.

    Topology: ONE SolverService + SolverFrontend on this thread's event
    loop; tenant control planes are client threads over real TCP.
    tenant-0 is an unmodified extender consumer (HTTPExtender:
    filter -> prioritize -> bind per pod, full node objects on the wire);
    tenants 1..M-1 speak the native /solve endpoint with bind=True.
    Every tenant registers the SAME node names (adversarial), each with
    its own RaceDetector-wrapped ObjectStore. Phases: solo baseline
    (one tenant, the whole native shape, sequential) -> multi-tenant
    concurrent (the aggregate gate) -> victim unloaded p99 -> victim p99
    under a noisy tenant's native flood (the fairness gate)."""
    import threading

    from kubernetes_tpu.extender.client import ExtenderConfig, HTTPExtender
    from kubernetes_tpu.solversvc.core import SolverService, _svc_metrics
    from kubernetes_tpu.solversvc.server import SolverFrontend
    from kubernetes_tpu.solversvc.tenancy import split_tenant
    from kubernetes_tpu.testing.races import RaceDetector

    n_tenants = max(2, n_tenants)
    native = [f"tenant-{i}" for i in range(1, n_tenants)]
    victim = "tenant-0"
    solo = "solo"
    # pow-2 capacity for every tenant's namespaced node rows + solo's
    total_nodes = (n_tenants + (n_tenants - 1)) * nodes_per_tenant
    cap_nodes = 1
    while cap_nodes < total_nodes:
        cap_nodes *= 2
    caps = Capacities(num_nodes=max(64, cap_nodes), batch_pods=batch_pods)
    # pre-compile EVERY pod bucket the drill can hit (coalesced solve
    # groups bucket at next-pow-2 of their summed rows): a mid-flood
    # compile stall would pollute the victim's loaded p99 with XLA time
    buckets = []
    b = 4
    while b <= batch_pods:
        buckets.append(b)
        b *= 2

    svc = SolverService(caps=caps, window_s=window_ms / 1000.0,
                        total_seats=seats, queue_wait_s=queue_wait_s)
    mx = _svc_metrics()
    steps0 = int(mx["steps"].labels().value)

    stores: dict[str, ObjectStore] = {}
    for name in (victim, solo, *native):
        store: object = ObjectStore()
        if race_detect:
            store = RaceDetector(store)
        stores[name] = store
        svc.register_tenant(name, store=store)

    nodes = make_nodes(nodes_per_tenant, cpu="16", memory="64Gi")
    solo_nodes = make_nodes((n_tenants - 1) * nodes_per_tenant,
                            cpu="16", memory="64Gi")
    nodes_by_name = {n.metadata.name: n for n in nodes}

    def pods_for(prefix: str, count: int) -> list:
        return make_pods(count, cpu="20m", memory="32Mi",
                         name_prefix=prefix)

    flood_stop = threading.Event()
    flood_counts = {"requests": 0, "rejected": 0}
    flood_lock = threading.Lock()

    async def drive() -> SolverSvcResult:
        frontend = SolverFrontend(svc, warmup_buckets=tuple(buckets))
        await frontend.start()
        base = frontend.url
        try:
            return await phases(base)
        finally:
            await frontend.stop()

    async def phases(base: str) -> SolverSvcResult:
        # node state sync: native tenants + solo over the wire, all with
        # the SAME node names; the victim's nodes ride its filter calls
        for name in native:
            await asyncio.to_thread(
                _svc_post, base, f"/tenants/{name}/state",
                {"nodes": [n.to_dict() for n in nodes]})
        await asyncio.to_thread(
            _svc_post, base, f"/tenants/{solo}/state",
            {"nodes": [n.to_dict() for n in solo_nodes]})

        def native_requests(tenant: str, pods: list) -> int:
            """Closed loop: one solve request of req_pods in flight at a
            time — a control plane draining its queue. Returns binds."""
            ok = 0
            for i in range(0, len(pods), req_pods):
                chunk = pods[i:i + req_pods]
                stores[tenant].create_many(chunk)
                status, body = _svc_post(
                    base, f"/tenants/{tenant}/solve",
                    {"pods": [p.to_dict() for p in chunk], "bind": True})
                if status == 200 and isinstance(body, dict):
                    ok += sum(1 for b in body.get("bound", ()) if b)
            return ok

        # ---- phase A: solo baseline (same total native shape, 1 tenant)
        solo_pods = pods_for("solo", (n_tenants - 1) * pods_per_tenant)
        t0 = time.perf_counter()
        solo_bound = await asyncio.to_thread(native_requests, solo,
                                             solo_pods)
        solo_dt = time.perf_counter() - t0
        svc.drop_tenant(solo)

        # ---- phase B: the same shape split over M-1 concurrent tenants
        per_tenant = {name: pods_for(f"{name}-p", pods_per_tenant)
                      for name in native}
        t0 = time.perf_counter()
        bound_counts = await asyncio.gather(*(
            asyncio.to_thread(native_requests, name, per_tenant[name])
            for name in native))
        multi_dt = time.perf_counter() - t0
        native_bound = int(sum(bound_counts))

        # ---- phase C: victim over the stock extender wire, unloaded
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=f"{base}/tenants/{victim}",
            filter_verb="filter", prioritize_verb="prioritize",
            weight=1, node_cache_capable=False))
        names = list(nodes_by_name)

        from kubernetes_tpu.extender.client import ExtenderError

        def shed_retry(call):
            # a stock scheduler retries a shed extender callout; the
            # server-side latency ring only records seated requests, so
            # retries don't pollute the p99 measurement
            for _ in range(40):
                try:
                    return call()
                except ExtenderError as e:
                    if "HTTP 429" not in str(e):
                        raise
                    # client-thread backoff, never on a loop
                    time.sleep(0.05)  # ktpu: allow[blocking-in-async]
            return call()

        def victim_wave(prefix: str, count: int) -> int:
            ok = 0
            for pod in pods_for(prefix, count):
                stores[victim].create(pod)
                passed, _failed = shed_retry(
                    lambda: ext.filter(pod, names, nodes_by_name))
                if not passed:
                    continue
                scores = shed_retry(
                    lambda: ext.prioritize(pod, passed, nodes_by_name))
                best = max(passed, key=lambda n: scores.get(n, 0.0))
                status, body = _svc_post(
                    base, f"/tenants/{victim}/bind",
                    {"PodName": pod.metadata.name,
                     "PodNamespace": pod.metadata.namespace or "default",
                     "Node": best})
                if status == 200 and not body.get("Error"):
                    ok += 1
            return ok

        victim_t = svc.tenants[victim]
        bound_a = await asyncio.to_thread(victim_wave, "vic-a",
                                          pods_per_tenant)
        unloaded = list(victim_t.latency)  # server-side seconds

        # ---- phase D: same wave under a noisy native tenant's flood
        def flood(worker: int) -> None:
            fpods = [p.to_dict()
                     for p in pods_for(f"flood{worker}", req_pods)]
            while not flood_stop.is_set():
                status, _body = _svc_post(
                    base, f"/tenants/{native[0]}/solve",
                    {"pods": fpods, "bind": False})
                with flood_lock:
                    flood_counts["requests"] += 1
                    if status == 429:
                        flood_counts["rejected"] += 1

        # real threads, NOT asyncio.to_thread: on a small box the default
        # executor has ~cpu+4 workers and the flood would starve the
        # victim's own executor slot (and anything else sharing the pool)
        flood_workers = [threading.Thread(target=flood, args=(i,),
                                          daemon=True)
                         for i in range(flood_threads)]
        for w in flood_workers:
            w.start()
        bound_b = await asyncio.to_thread(victim_wave, "vic-b",
                                          pods_per_tenant)
        flood_stop.set()
        while any(w.is_alive() for w in flood_workers):
            await asyncio.sleep(0.02)
        loaded = list(victim_t.latency)[len(unloaded):]

        # ---- audit: exactly-once binds + zero cross-tenant assignments
        bound = native_bound + solo_bound + bound_a + bound_b
        expected = ((n_tenants - 1) * pods_per_tenant * 2
                    + 2 * pods_per_tenant)
        double = 0
        racy = 0
        if race_detect:
            for store in stores.values():
                double += store.double_binds
                racy += len(store.racy_writes)
        cross = 0
        for name in (victim, *native):
            t = svc.tenants[name]
            own = {split_tenant(k)[1] for k in t.nodes}
            cross += sum(1 for node in t.assignments.values()
                         if node not in own)

        return SolverSvcResult(
            tenants=n_tenants, nodes_per_tenant=nodes_per_tenant,
            pods_per_tenant=pods_per_tenant, seed=seed,
            bound=bound, expected_bound=expected, double_binds=double,
            isolation_violations=int(mx["isolation"].labels().value),
            cross_tenant_assignments=cross,
            p99_unloaded_ms=_p99_ms(unloaded),
            p99_loaded_ms=_p99_ms(loaded),
            flood_requests=flood_counts["requests"],
            flood_rejected=flood_counts["rejected"],
            solo_pods_per_sec=len(solo_pods) / max(solo_dt, 1e-9),
            agg_pods_per_sec=sum(len(p) for p in per_tenant.values())
            / max(multi_dt, 1e-9),
            steps=int(mx["steps"].labels().value) - steps0,
            occupancy_max=int(mx["occupancy"].labels().value),
            converged=(bound == expected and double == 0 and cross == 0),
            racy_writes=racy)

    try:
        return asyncio.run(drive())
    finally:
        flood_stop.set()


@dataclass
class FederationResult:
    """Federation drill: one hub control plane (health + sync +
    GlobalPlanner) over N in-process member control planes, a mixed
    globally-placed workload set (incl. one gang), and a mid-run member
    saturation (its nodes vanish; its NodeGroup has zero headroom).
    Gates: every workload's replicas land across clusters exactly once
    (member copies sum to the hub total and match the plan, no
    duplicates), the planner records >= 1 spillover for the saturated
    member and drains its demand to siblings, the whole thing converges
    within budget, and the RaceDetector sees zero racy hub writes."""

    clusters: int
    pods: int
    seed: int
    workloads: int
    planned: int                 # workloads holding a complete plan
    placed: int                  # replicas ensured on members, post-drain
    exactly_once: bool           # sums match the hub totals + the plans
    duplicate_placements: int
    spillovers: int              # planner spillover events recorded
    victim_drained: bool         # saturated member ended at 0 replicas
    cycles: int
    solves: int
    solve_p50_ms: float
    converged: bool
    racy_writes: int = 0

    @property
    def gate(self) -> bool:
        return (self.converged and self.exactly_once
                and self.duplicate_placements == 0
                and self.spillovers >= 1 and self.victim_drained
                and self.racy_writes == 0)

    def __str__(self) -> str:
        return (f"fed C={self.clusters} P={self.pods}: "
                f"{self.planned}/{self.workloads} planned, "
                f"{self.placed} replicas placed "
                f"({'exactly-once' if self.exactly_once else 'DUPED'}), "
                f"{self.spillovers} spillovers "
                f"(victim {'drained' if self.victim_drained else 'WEDGED'}),"
                f" {self.cycles} cycles {self.solves} solves "
                f"~{self.solve_p50_ms:.1f}ms")


def run_federation(n_clusters: int = 4, n_pods: int = 24, seed: int = 2032,
                   race_detect: bool = True) -> FederationResult:
    """Blocking entry point for the federation global-planning drill.

    Topology: hub ObjectStore (RaceDetector-wrapped) running the full
    FederationControlPlane with the GlobalPlanner; N member ObjectStores,
    each a few nodes plus a NodeGroup pinned at max size (headroom 0 —
    saturation cannot be autoscaled away). Workloads: ~n_pods replicas
    split over several `placement: global` ReplicaSets, one of them a
    gang. Mid-run, member 0's nodes are deleted: its next capacity report
    shows zero free, the planner's charge trips spillover, the member's
    row is masked, demand re-plans onto siblings, and the sync controller
    rescales the victim's copies to zero."""
    import random

    from kubernetes_tpu.api.objects import Node, NodeGroup, ReplicaSet
    from kubernetes_tpu.apiserver.store import NotFound
    from kubernetes_tpu.federation.kubefed import (
        FederationControlPlane,
        join,
    )
    from kubernetes_tpu.federation.planner import (
        PLACEMENT_ANNOTATION,
        PLACEMENT_GLOBAL,
        parse_plan,
    )
    from kubernetes_tpu.gang import GROUP_MIN_ANNOTATION, GROUP_NAME_ANNOTATION
    from kubernetes_tpu.testing.races import RaceDetector

    n_clusters = max(3, n_clusters)
    rng = random.Random(seed)
    hub_inner = ObjectStore()
    hub = RaceDetector(hub_inner) if race_detect else hub_inner
    members = {f"member-{i}": ObjectStore() for i in range(n_clusters)}
    victim = "member-0"

    # every member can hold the WHOLE workload set on its own (spillover
    # must be able to drain anywhere), via a few fat nodes
    nodes_per = 2
    cpu_per_node = max(4, n_pods)  # cores; replicas request 500m each
    for name, store in members.items():
        for j in range(nodes_per):
            store.create(Node.from_dict({
                "metadata": {"name": f"{name}-n{j}",
                             "labels": {"kubernetes.io/hostname":
                                        f"{name}-n{j}"}},
                "status": {
                    "allocatable": {"cpu": str(cpu_per_node),
                                    "memory": f"{4 * cpu_per_node}Gi",
                                    "pods": "110"},
                    "capacity": {"cpu": str(cpu_per_node),
                                 "memory": f"{4 * cpu_per_node}Gi",
                                 "pods": "110"},
                    "conditions": [{"type": "Ready", "status": "True"}]}}))
        # pool pinned at max: zero autoscaler headroom, so a saturated
        # member spills instead of pretending it can grow
        store.create(NodeGroup.from_dict({
            "metadata": {"name": f"{name}-pool"},
            "spec": {"minSize": nodes_per, "maxSize": nodes_per},
            "status": {"targetSize": nodes_per,
                       "readyNodes": nodes_per}}))

    def client_factory(cluster):
        store = members.get(cluster.metadata.name)
        if store is None:
            raise ConnectionError(cluster.metadata.name)
        return store

    # mixed workload set: one gang + several plain ReplicaSets summing to
    # ~n_pods replicas, all placement=global
    gang_size = max(3, min(8, n_pods // 4))
    remaining = max(1, n_pods - gang_size)
    sizes = []
    while remaining > 0:
        s = min(remaining, rng.randint(2, 6))
        sizes.append(s)
        remaining -= s
    workloads = []
    for i, size in enumerate(sizes):
        workloads.append(ReplicaSet.from_dict({
            "metadata": {"name": f"fedw-{i}", "annotations": {
                PLACEMENT_ANNOTATION: PLACEMENT_GLOBAL}},
            "spec": {"replicas": size, "template": {
                "metadata": {"labels": {"app": f"fedw-{i}"}},
                "spec": {"containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "500m", "memory": "256Mi"}}}]}}}}))
    workloads.append(ReplicaSet.from_dict({
        "metadata": {"name": "fedw-gang", "annotations": {
            PLACEMENT_ANNOTATION: PLACEMENT_GLOBAL,
            GROUP_NAME_ANNOTATION: "fedw-gang",
            GROUP_MIN_ANNOTATION: str(gang_size)}},
        "spec": {"replicas": gang_size, "template": {
            "metadata": {"labels": {"app": "fedw-gang"}},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "500m", "memory": "256Mi"}}}]}}}}))
    total = sum(w.replicas for w in workloads)

    batch = 1
    while batch < max(16, total):
        batch *= 2
    plane = FederationControlPlane(
        hub, client_factory, health_period=0.1,
        planner=True, plan_interval=0.1,
        planner_caps=Capacities(num_nodes=max(8, n_clusters),
                                batch_pods=min(64, batch)))
    planner = plane.planner

    freeze_drill_heap()

    async def drive() -> FederationResult:
        for name in members:
            join(hub, name)
        for w in workloads:
            hub.create(w)
        await plane.start()
        for cluster in plane.clusters.items():
            plane.health.enqueue(cluster.metadata.name)

        def member_counts(wname: str) -> dict[str, int]:
            out = {}
            for cname, store in members.items():
                try:
                    out[cname] = store.get("ReplicaSet", wname).replicas
                except NotFound:
                    pass
            return out

        def settled(require_victim_zero: bool) -> bool:
            for w in workloads:
                try:
                    fresh = hub.get("ReplicaSet", w.metadata.name)
                except NotFound:
                    return False
                plan = parse_plan(fresh)
                if plan is None or int(plan.get("unplaced", 0)) > 0:
                    return False
                if require_victim_zero and \
                        plan["clusters"].get(victim, 0) > 0:
                    return False
                got = member_counts(w.metadata.name)
                for cname in members:
                    if got.get(cname, 0) != plan["clusters"].get(cname, 0):
                        return False
                if sum(got.values()) != w.replicas:
                    return False
            return True

        async def wait_settled(require_victim_zero: bool,
                               timeout_s: float) -> bool:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if settled(require_victim_zero):
                    return True
                await asyncio.sleep(0.05)
            return False

        phase1 = await wait_settled(False, 120.0)

        # saturate the victim: its nodes vanish (kernel panic, preemption,
        # a zone outage) while its NodeGroup stays pinned at max size —
        # the next capacity report shows zero free and zero headroom
        for j in range(nodes_per):
            members[victim].delete("Node", f"{victim}-n{j}")
        phase2 = await wait_settled(True, 120.0)

        dupes = 0
        placed = 0
        exactly_once = True
        for w in workloads:
            got = member_counts(w.metadata.name)
            placed += sum(got.values())
            if sum(got.values()) != w.replicas:
                exactly_once = False
            if sum(got.values()) > w.replicas:
                dupes += 1
        planned = sum(
            1 for w in workloads
            if parse_plan(hub.get("ReplicaSet", w.metadata.name)))
        victim_total = sum(
            member_counts(w.metadata.name).get(victim, 0)
            for w in workloads)
        solve_ms = (1e3 * planner.solve_seconds / planner.solve_count
                    if planner.solve_count else 0.0)
        plane.stop()
        return FederationResult(
            clusters=n_clusters, pods=total, seed=seed,
            workloads=len(workloads), planned=planned, placed=placed,
            exactly_once=exactly_once, duplicate_placements=dupes,
            spillovers=planner.spillovers,
            victim_drained=(victim_total == 0),
            cycles=planner.cycles, solves=planner.solve_count,
            solve_p50_ms=solve_ms,
            converged=(phase1 and phase2),
            racy_writes=len(hub.racy_writes) if race_detect else 0)

    try:
        result = asyncio.run(drive())
    finally:
        thaw_drill_heap()
    return result
