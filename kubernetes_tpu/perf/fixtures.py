"""Synthetic cluster fixtures — the scheduler_perf strategy analog.

Mirrors the reference harness's fixture generators
(test/integration/scheduler_perf/scheduler_test.go:41-68
`TrivialNodePrepareStrategy` + pod templates, and test/utils/runners.go
`NewTestPodCreator`): uniform fake nodes and templated pods at configurable
scale, so throughput runs never need a real cluster.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Node, Pod


def make_nodes(
    n: int,
    cpu: str = "4",
    memory: str = "8Gi",
    pods: str = "110",
    zones: int = 3,
    labels_per_node: int = 0,
    taint_every: int = 0,
) -> list[Node]:
    """Uniform ready nodes; optional zone spread, filler labels, periodic
    NoSchedule taints (for taint-heavy configs)."""
    out = []
    for i in range(n):
        labels = {
            "kubernetes.io/hostname": f"node-{i}",
            "failure-domain.beta.kubernetes.io/zone": f"zone-{i % max(zones, 1)}",
            "failure-domain.beta.kubernetes.io/region": "region-1",
        }
        for j in range(labels_per_node):
            labels[f"label-{j}"] = f"value-{(i + j) % 7}"
        taints = []
        if taint_every and i % taint_every == 0:
            taints = [{"key": "dedicated", "value": "special",
                       "effect": "NoSchedule"}]
        out.append(Node.from_dict({
            "metadata": {"name": f"node-{i}", "labels": labels},
            "spec": {"taints": taints},
            "status": {
                "allocatable": {"cpu": cpu, "memory": memory, "pods": pods},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }))
    return out


def make_pods(
    n: int,
    cpu: str = "100m",
    memory: str = "250Mi",
    name_prefix: str = "pod",
    selector_every: int = 0,
    tolerate: bool = False,
    namespace: str = "default",
) -> list[Pod]:
    """Templated pending pods (the basic scheduler_perf pod spec: small
    cpu/memory requests)."""
    out = []
    for i in range(n):
        spec: dict = {"containers": [{
            "name": "app",
            "image": "k8s.gcr.io/pause:3.0",
            "resources": {"requests": {"cpu": cpu, "memory": memory}},
        }]}
        if selector_every and i % selector_every == 0:
            spec["nodeSelector"] = {"label-0": f"value-{i % 7}"}
        if tolerate:
            spec["tolerations"] = [{"key": "dedicated", "operator": "Exists"}]
        out.append(Pod.from_dict({
            "metadata": {"name": f"{name_prefix}-{i}", "namespace": namespace},
            "spec": spec,
        }))
    return out
