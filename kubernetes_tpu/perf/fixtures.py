"""Synthetic cluster fixtures — the scheduler_perf strategy analog.

Mirrors the reference harness's fixture generators
(test/integration/scheduler_perf/scheduler_test.go:41-68
`TrivialNodePrepareStrategy` + pod templates, and test/utils/runners.go
`NewTestPodCreator`): uniform fake nodes and templated pods at configurable
scale, so throughput runs never need a real cluster.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Node, Pod


def make_nodes(
    n: int,
    cpu: str = "4",
    memory: str = "8Gi",
    pods: str = "110",
    zones: int = 3,
    labels_per_node: int = 0,
    taint_every: int = 0,
) -> list[Node]:
    """Uniform ready nodes; optional zone spread, filler labels, periodic
    NoSchedule taints (for taint-heavy configs)."""
    out = []
    for i in range(n):
        labels = {
            "kubernetes.io/hostname": f"node-{i}",
            "failure-domain.beta.kubernetes.io/zone": f"zone-{i % max(zones, 1)}",
            "failure-domain.beta.kubernetes.io/region": "region-1",
        }
        for j in range(labels_per_node):
            labels[f"label-{j}"] = f"value-{(i + j) % 7}"
        taints = []
        if taint_every and i % taint_every == 0:
            taints = [{"key": "dedicated", "value": "special",
                       "effect": "NoSchedule"}]
        out.append(Node.from_dict({
            "metadata": {"name": f"node-{i}", "labels": labels},
            "spec": {"taints": taints},
            "status": {
                "allocatable": {"cpu": cpu, "memory": memory, "pods": pods},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }))
    return out


def make_pods(
    n: int,
    cpu: str = "100m",
    memory: str = "250Mi",
    name_prefix: str = "pod",
    selector_every: int = 0,
    tolerate: bool = False,
    namespace: str = "default",
    app_groups: int = 0,
    anti_affinity_every: int = 0,
    pref_affinity_every: int = 0,
    gang_size: int = 0,
    gang_min: int | None = None,
    priority_class_name: str = "",
) -> list[Pod]:
    """Templated pending pods (the basic scheduler_perf pod spec: small
    cpu/memory requests).

    app_groups labels pods app=app-{i%g} (service/spread targets);
    anti_affinity_every adds required hostname anti-affinity against the
    pod's own app group; pref_affinity_every adds preferred zone affinity
    toward it (the interpod-heavy config shape, BASELINE.md);
    gang_size groups consecutive pods into all-or-nothing gangs of that
    size (quorum gang_min, default the full size) — keep n divisible by
    gang_size or the trailing partial group waits out its quorum timeout;
    priority_class_name stamps spec.priorityClassName (resolved to a
    numeric priority at admission when the store runs the default chain)."""
    out = []
    for i in range(n):
        meta: dict = {"name": f"{name_prefix}-{i}", "namespace": namespace}
        if app_groups:
            meta["labels"] = {"app": f"app-{i % app_groups}"}
        if gang_size:
            from kubernetes_tpu.gang import (GROUP_MIN_ANNOTATION,
                                             GROUP_NAME_ANNOTATION)
            meta["annotations"] = {
                GROUP_NAME_ANNOTATION:
                    f"{name_prefix}-gang-{i // gang_size}",
                GROUP_MIN_ANNOTATION: str(gang_min or gang_size)}
        spec: dict = {"containers": [{
            "name": "app",
            "image": "k8s.gcr.io/pause:3.0",
            "resources": {"requests": {"cpu": cpu, "memory": memory}},
        }]}
        if priority_class_name:
            spec["priorityClassName"] = priority_class_name
        if selector_every and i % selector_every == 0:
            spec["nodeSelector"] = {"label-0": f"value-{i % 7}"}
        if tolerate:
            spec["tolerations"] = [{"key": "dedicated", "operator": "Exists"}]
        affinity: dict = {}
        sel = {"matchLabels": {"app": f"app-{i % app_groups}"}} \
            if app_groups else None
        if anti_affinity_every and sel and i % anti_affinity_every == 0:
            affinity["podAntiAffinity"] = {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": sel,
                    "topologyKey": "kubernetes.io/hostname"}]}
        if pref_affinity_every and sel and i % pref_affinity_every == 0:
            affinity["podAffinity"] = {
                "preferredDuringSchedulingIgnoredDuringExecution": [{
                    "weight": 10,
                    "podAffinityTerm": {
                        "labelSelector": sel,
                        "topologyKey":
                            "failure-domain.beta.kubernetes.io/zone"}}]}
        if affinity:
            spec["affinity"] = affinity
        out.append(Pod.from_dict({"metadata": meta, "spec": spec}))
    return out


def make_services(n: int, namespace: str = "default") -> list:
    """Services selecting the app groups of make_pods(app_groups=n) — the
    SelectorSpread / PodTopologySpread-analog config's workload objects."""
    from kubernetes_tpu.api.objects import Service

    return [Service.from_dict({
        "metadata": {"name": f"svc-{i}", "namespace": namespace},
        "spec": {"selector": {"app": f"app-{i}"}}})
        for i in range(n)]
