"""L4 controllers: the reconcile plane (SURVEY.md §2.4)."""

from kubernetes_tpu.controllers.base import (
    Expectations,
    ReconcileController,
    slow_start_batch,
)
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.gc import GarbageCollector
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.controllers.replicaset import ReplicaManager

__all__ = [
    "ControllerManager",
    "DeploymentController",
    "Expectations",
    "GarbageCollector",
    "ReconcileController",
    "ReplicaManager",
    "slow_start_batch",
]
