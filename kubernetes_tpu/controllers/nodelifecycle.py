"""Node lifecycle controller: heartbeat monitoring + rate-limited eviction.

The NodeController analog (reference pkg/controller/node/node_controller.go:185
monitorNodeStatus, :587 heartbeat-age checks): kubelets heartbeat their Node's
Ready condition; when a heartbeat goes stale past the grace period the
controller marks Ready Unknown (the control plane's view of a dead kubelet),
and once the node has been not-Ready past the pod-eviction timeout its pods
are deleted through a rate-limited queue
(node/scheduler/rate_limited_queue.go:1 — per-tick token pacing so a zone
outage doesn't delete every pod at once). Deleted pods flow back through
their ReplicaSet (recreate) and the scheduler (re-place on live nodes) —
closing the failure-recovery loop SURVEY.md §5.3 describes.

Scheduling-side containment is immediate and separate: the Ready=Unknown
write reaches the scheduler's statedb through the node informer, where
CheckNodeCondition rejects new placements (ops/predicates.py).

Defaults mirror the reference componentconfig: 5s monitor period
(--node-monitor-period), 40s grace (--node-monitor-grace-period), 5m pod
eviction timeout (--pod-eviction-timeout), 0.1 evictions/s
(--node-eviction-rate).
"""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.api.objects import NodeCondition, Taint
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.taintmanager import (
    NOT_READY_TAINT,
    UNREACHABLE_TAINT,
)
from kubernetes_tpu.utils.events import EventRecorder

log = logging.getLogger(__name__)

MONITOR_PERIOD = 5.0        # nodeMonitorPeriod
GRACE_PERIOD = 40.0         # nodeMonitorGracePeriod
STARTUP_GRACE_PERIOD = 60.0  # nodeStartupGracePeriod
EVICTION_TIMEOUT = 300.0    # podEvictionTimeout
EVICTION_RATE = 0.1         # evictionLimiterQPS
SECONDARY_EVICTION_RATE = 0.01   # secondaryEvictionLimiterQPS
UNHEALTHY_ZONE_THRESHOLD = 0.55  # unhealthyZoneThreshold
LARGE_CLUSTER_THRESHOLD = 50     # largeClusterSizeThreshold (nodes/zone)

ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"

# zone states (node_controller.go:170 ZoneState)
ZONE_NORMAL = "Normal"
ZONE_PARTIAL = "PartialDisruption"
ZONE_FULL = "FullDisruption"


class NodeLifecycleController:
    """Not a keyed reconcile loop: one periodic monitor pass over every
    node (exactly monitorNodeStatus's shape) + one paced eviction worker."""

    name = "node-lifecycle"

    def __init__(self, store: ObjectStore, node_informer: Informer,
                 pod_informer: Informer, *,
                 monitor_period: float = MONITOR_PERIOD,
                 grace_period: float = GRACE_PERIOD,
                 startup_grace_period: float = STARTUP_GRACE_PERIOD,
                 eviction_timeout: float = EVICTION_TIMEOUT,
                 eviction_rate: float = EVICTION_RATE,
                 taint_based_evictions: bool = True,
                 secondary_eviction_rate: float = SECONDARY_EVICTION_RATE,
                 unhealthy_zone_threshold: float = UNHEALTHY_ZONE_THRESHOLD,
                 large_cluster_threshold: int = LARGE_CLUSTER_THRESHOLD,
                 cloud=None):
        self.store = store
        self.nodes = node_informer
        self.pods = pod_informer
        # cloud-instance GC (node_controller.go:411 cloud-node existence
        # check): a Node whose backing instance is gone — autoscaler
        # delete_nodes, manual pool shrink — is deleted instead of sitting
        # NotReady for the eviction timeout. Only nodes stamped with the
        # group label (cloud-created) are eligible: membership itself
        # vanishes with the instance, but the label survives on the Node
        # object; unmanaged/static nodes never GC.
        self.cloud = cloud
        self.monitor_period = monitor_period
        self.grace_period = grace_period
        self.startup_grace_period = startup_grace_period
        self.eviction_timeout = eviction_timeout
        self.eviction_rate = eviction_rate
        # stamp NotReady/unreachable NoExecute taints so the taint manager
        # can run its tolerationSeconds eviction flow
        # (node_controller.go:274-302, alpha TaintBasedEvictions)
        self.taint_based_evictions = taint_based_evictions
        # per-zone disruption handling (node_controller.go:170 zone states
        # + handleDisruption): a zone where >= unhealthy_zone_threshold of
        # nodes are not ready is PartialDisruption — large zones evict at
        # the reduced secondary rate, small zones halt; when EVERY zone is
        # fully down the controller assumes it is the partitioned one and
        # stops evicting entirely
        self.secondary_eviction_rate = secondary_eviction_rate
        self.unhealthy_zone_threshold = unhealthy_zone_threshold
        self.large_cluster_threshold = large_cluster_threshold
        self.zone_states: dict[str, str] = {}
        self.zone_sizes: dict[str, int] = {}
        self._all_zones_full = False
        self.events = EventRecorder(store, component="node-controller")
        # node -> wall time the controller first saw it not-Ready
        self._not_ready_since: dict[str, float] = {}
        self._eviction_q: asyncio.Queue[str] = asyncio.Queue()
        self._queued: set[str] = set()
        # drained dead nodes: not re-queued (each re-eviction would burn a
        # rate token doing nothing) unless pods land on them again; cleared
        # on recovery
        self._evicted: set[str] = set()
        self._tasks: list[asyncio.Task] = []
        self.evicted_pods = 0  # observability counter

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._monitor_loop()),
                       loop.create_task(self._eviction_loop())]

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()

    # ---- heartbeat monitoring ----

    def _compute_zone_states(self) -> None:
        """Classify every zone from the informer's current Ready conditions
        (handleDisruption's zoneState computation)."""
        tally: dict[str, list[int]] = {}   # zone -> [ready, not_ready]
        for node in self.nodes.items():
            zone = node.metadata.labels.get(ZONE_LABEL, "")
            ready = next((c for c in node.status.conditions
                          if c.type == "Ready"), None)
            ok = ready is not None and ready.status == "True"
            counts = tally.setdefault(zone, [0, 0])
            counts[0 if ok else 1] += 1
        states: dict[str, str] = {}
        for zone, (ready, not_ready) in tally.items():
            total = ready + not_ready
            if not_ready == total and total > 0:
                states[zone] = ZONE_FULL
            elif not_ready / total >= self.unhealthy_zone_threshold:
                states[zone] = ZONE_PARTIAL
            else:
                states[zone] = ZONE_NORMAL
            self.zone_sizes[zone] = total
        self.zone_states = states
        self._all_zones_full = bool(states) and all(
            s == ZONE_FULL for s in states.values())

    def _zone_of(self, name: str) -> str:
        node = self.nodes.get(name)
        if node is None:
            return ""
        return node.metadata.labels.get(ZONE_LABEL, "")

    def monitor_once(self, now: float | None = None) -> None:
        """One monitorNodeStatus pass (exposed for tests)."""
        now = time.time() if now is None else now
        self._gc_cloud_nodes()
        self._compute_zone_states()
        pods_on: dict[str, int] = {}
        for p in self.pods.items():
            if p.spec.node_name:
                pods_on[p.spec.node_name] = pods_on.get(p.spec.node_name,
                                                        0) + 1
        seen = set()
        for node in self.nodes.items():
            name = node.metadata.name
            seen.add(name)
            ready = next((c for c in node.status.conditions
                          if c.type == "Ready"), None)
            if ready is None:
                # registered but never heartbeated: startup grace from the
                # Node's creation (node_controller.go:640)
                age = now - (node.metadata.creation_timestamp or now)
                if age > self.startup_grace_period:
                    self._mark_unknown(name, now)
                    self._track_not_ready(name, now)
                continue
            hb = ready.last_heartbeat_time or node.metadata.creation_timestamp
            if ready.status == "True":
                if now - hb > self.grace_period:
                    self._mark_unknown(name, now)
                    self._track_not_ready(name, now)
                    self._ensure_condition_taint(name, UNREACHABLE_TAINT)
                else:
                    # healthy: clear tracking, cancel any pending eviction
                    self._not_ready_since.pop(name, None)
                    self._queued.discard(name)
                    self._evicted.discard(name)
                    self._ensure_condition_taint(name, None)
            else:
                # not ready: Unknown (stale heartbeat) taints unreachable,
                # False (the kubelet itself reports NotReady) taints
                # notReady (node_controller.go:274-302)
                self._ensure_condition_taint(
                    name, UNREACHABLE_TAINT if ready.status == "Unknown"
                    else NOT_READY_TAINT)
                since = self._track_not_ready(
                    name, min(now, ready.last_transition_time or now))
                if now - since > self.eviction_timeout \
                        and name not in self._queued \
                        and (name not in self._evicted
                             or pods_on.get(name)):
                    self._queued.add(name)
                    self._eviction_q.put_nowait(name)
        # pods bound to a Node object that no longer exists are stranded the
        # same way a dead kubelet strands them — evict (the reference's
        # deleteNode path, node_controller.go:426). Grace-period the first
        # sighting: a bind may race ahead of its node's ADDED event.
        missing = set(pods_on) - seen
        for name in missing:
            since = self._track_not_ready(name, now)
            if now - since > self.grace_period and name not in self._queued:
                self._queued.add(name)
                self._eviction_q.put_nowait(name)
        for gone in set(self._not_ready_since) - seen - missing:
            # keep any queued eviction: a deleted Node's pods still need
            # deleting even though tracking ends here
            self._not_ready_since.pop(gone, None)

    def _gc_cloud_nodes(self) -> None:
        """Delete Node objects whose cloud instance no longer exists (the
        cloud node lifecycle's shouldDeleteNode). Pods are NOT deleted
        here: the node DELETED event cascades through the stranded-pods
        path below on the next pass."""
        if self.cloud is None:
            return
        from kubernetes_tpu.cloudprovider.interface import NODE_GROUP_LABEL

        for node in self.nodes.items():
            name = node.metadata.name
            if NODE_GROUP_LABEL not in node.metadata.labels:
                continue
            if self.cloud.instance_exists(name):
                continue
            try:
                self.store.delete("Node", name, "default")
            except NotFound:
                continue
            self.events.record(
                node, "Normal", "DeletingNode",
                f"Node {name} no longer exists in the cloud provider")
            log.info("node %s: cloud instance gone, deleted Node object",
                     name)

    def _track_not_ready(self, name: str, when: float) -> float:
        return self._not_ready_since.setdefault(name, when)

    def _ensure_condition_taint(self, name: str, want: str | None) -> None:
        """Converge the node's condition taints to exactly `want` (one of
        the NoExecute condition taints, or None for a healthy node)."""
        if not self.taint_based_evictions:
            return
        node = self.nodes.get(name)
        if node is None:
            return
        have = {t.key for t in node.spec.taints
                if t.effect == "NoExecute"
                and t.key in (NOT_READY_TAINT, UNREACHABLE_TAINT)}
        if have == ({want} if want else set()):
            return

        def mutate(n):
            n.spec.taints = [
                t for t in n.spec.taints
                if not (t.effect == "NoExecute"
                        and t.key in (NOT_READY_TAINT, UNREACHABLE_TAINT))]
            if want:
                n.spec.taints.append(Taint(key=want, effect="NoExecute"))
            return n

        try:
            self.store.guaranteed_update("Node", name, "default", mutate)
        except (NotFound, Conflict):
            pass

    def _mark_unknown(self, name: str, now: float) -> None:
        """Ready -> Unknown (NodeStatusUnknown, node_controller.go:684)."""
        def mutate(node):
            ready = next((c for c in node.status.conditions
                          if c.type == "Ready"), None)
            if ready is None:
                ready = NodeCondition(type="Ready")
                node.status.conditions.append(ready)
            if ready.status != "Unknown":
                ready.status = "Unknown"
                ready.reason = "NodeStatusUnknown"
                ready.last_transition_time = now
            return node

        try:
            self.store.guaranteed_update("Node", name, "default", mutate)
        except (NotFound, Conflict):
            return
        log.info("node %s: heartbeat stale, Ready -> Unknown", name)

    # ---- rate-limited eviction ----

    def _still_dead(self, name: str) -> bool:
        node = self.nodes.get(name)
        if node is None:
            return True  # node object deleted: its pods are stranded
        ready = next((c for c in node.status.conditions
                      if c.type == "Ready"), None)
        return ready is None or ready.status != "True"

    def evict_node_pods(self, name: str) -> int:
        """Delete every pod bound to `name` (deletePods,
        node_controller.go:757). Returns pods deleted."""
        deleted = 0
        for pod in list(self.pods.items()):
            if pod.spec.node_name != name:
                continue
            try:
                self.store.delete("Pod", pod.metadata.name,
                                  pod.metadata.namespace)
            except NotFound:
                continue
            deleted += 1
            self.events.record(
                pod, "Normal", "NodeControllerEviction",
                f"Marking for deletion Pod {pod.key} from Node {name}")
        if deleted:
            self.evicted_pods += deleted
            log.info("node %s: evicted %d pods", name, deleted)
        return deleted

    async def _monitor_loop(self) -> None:
        while True:
            await asyncio.sleep(self.monitor_period)
            try:
                self.monitor_once()
            except Exception:  # noqa: BLE001 — monitoring must not die
                log.exception("monitor pass failed")

    async def _eviction_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            name = await self._eviction_q.get()
            if name not in self._queued:
                continue  # cancelled by a recovery before the token came up
            zone_state = self.zone_states.get(self._zone_of(name),
                                              ZONE_NORMAL)
            small = self.zone_sizes.get(self._zone_of(name), 0) \
                <= self.large_cluster_threshold
            if self._all_zones_full or (zone_state == ZONE_PARTIAL
                                        and small):
                # halted (handleDisruption): every zone down looks like OUR
                # network partition; a small partially-disrupted zone waits
                # out the disruption instead of evicting what's left —
                # re-check after the next monitor pass
                loop.call_later(self.monitor_period,
                                self._eviction_q.put_nowait, name)
                await asyncio.sleep(0)
                continue
            self._queued.discard(name)
            if self._still_dead(name):
                self.evict_node_pods(name)
                self._evicted.add(name)
            # token pacing: partial disruption in a large zone drains at
            # the reduced secondary rate (secondaryEvictionLimiterQPS)
            rate = self.secondary_eviction_rate \
                if zone_state == ZONE_PARTIAL else self.eviction_rate
            await asyncio.sleep(1.0 / max(rate, 1e-9))
