"""CertificateSigningRequest controllers: approve + sign.

The pkg/controller/certificates analog (csrapproving + csrsigning wired
at cmd/kube-controller-manager/app/controllermanager.go:315-339): kubelets
bootstrapping TLS post a CSR object; the approving controller
auto-approves requests from the bootstrap group (the reference's
sufficient-permissions check collapsed to the group convention), and the
signing controller issues a certificate from the cluster CA and writes it
to status.certificate. Signing is REAL x509 via the openssl binary (the
reference uses Go's crypto/x509; the native boundary here is the same
shape as the proxier's iptables exec)."""

from __future__ import annotations

import base64
import logging
import subprocess
import tempfile

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

log = logging.getLogger(__name__)

BOOTSTRAP_GROUP = "system:bootstrappers"
NODES_GROUP_NAME = "system:nodes"
AUTO_APPROVED_USAGES = {"digital signature", "key encipherment",
                        "client auth", "server auth"}


def generate_ca(cn: str = "kubernetes-tpu-ca") -> tuple[bytes, bytes]:
    """(ca_cert_pem, ca_key_pem) — a self-signed cluster CA."""
    with tempfile.TemporaryDirectory() as tmp:
        crt, key = f"{tmp}/ca.crt", f"{tmp}/ca.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", crt, "-days", "365",
             "-subj", f"/CN={cn}"],
            check=True, capture_output=True, timeout=60)
        with open(crt, "rb") as f:
            cert_pem = f.read()
        with open(key, "rb") as f:
            key_pem = f.read()
    return cert_pem, key_pem


class CSRController(ReconcileController):
    """Approve bootstrap-group CSRs, then sign approved ones."""

    workers = 1

    def __init__(self, store: ObjectStore, csr_informer: Informer,
                 ca_cert_pem: bytes | None = None,
                 ca_key_pem: bytes | None = None):
        super().__init__()
        self.name = "certificate-controller"
        self.store = store
        self.csrs = csr_informer
        # the CA generates lazily on the first signing: RSA keygen costs
        # real time and most processes never see a CSR. Configuring only
        # half a CA is a config error, caught now rather than at sign time.
        if (ca_cert_pem is None) != (ca_key_pem is None):
            raise ValueError("ca_cert_pem and ca_key_pem must be "
                             "given together")
        self._ca_cert_pem = ca_cert_pem
        self._ca_key_pem = ca_key_pem
        csr_informer.add_handler(
            lambda e: self.enqueue(e.obj.metadata.name))

    @property
    def ca_cert_pem(self) -> bytes:
        if self._ca_cert_pem is None:
            self._ca_cert_pem, self._ca_key_pem = generate_ca()
        return self._ca_cert_pem

    @property
    def ca_key_pem(self) -> bytes:
        if self._ca_key_pem is None:
            self.ca_cert_pem  # noqa: B018 — triggers generation
        return self._ca_key_pem

    @staticmethod
    def _has(conditions, cond_type: str) -> bool:
        return any(c.get("type") == cond_type for c in conditions)

    def _approvable(self, csr) -> bool:
        """The csrapproving policy collapsed to the bootstrap convention:
        requestor in system:bootstrappers (or a node user) asking for
        standard usages only. The PEM subject check (`_subject_allowed`)
        runs separately — this is just the cheap identity/usages gate."""
        spec = csr.spec
        groups = set(spec.get("groups") or [])
        username = spec.get("username", "")
        usages = set(spec.get("usages") or [])
        subject_ok = BOOTSTRAP_GROUP in groups \
            or username.startswith("system:node:")
        return subject_ok and usages <= AUTO_APPROVED_USAGES

    @staticmethod
    def _csr_subject(request_pem: bytes) -> tuple[str, list[str]]:
        """(CN, [O...]) parsed from the CSR PEM via openssl RFC2253."""
        out = subprocess.run(
            ["openssl", "req", "-noout", "-subject", "-nameopt", "RFC2253"],
            input=request_pem, check=True, capture_output=True, timeout=60)
        text = out.stdout.decode().strip()
        text = text.partition("=")[2] if text.startswith("subject") else text
        cn, orgs = "", []
        for part in text.split(","):
            key, _, value = part.strip().partition("=")
            if key == "CN":
                cn = value
            elif key == "O":
                orgs.append(value)
        return cn, orgs

    def _subject_allowed(self, csr, cn: str, orgs: list[str]) -> bool:
        """What the signer refuses to mint: auto-approval only covers NODE
        client identities (CN=system:node:<x>, O=[system:nodes]) — the
        reference's isNodeClientCert/isSelfNodeClientCert recognizers
        (pkg/controller/certificates/approver/sarapprove.go:150). Without
        this, a bootstrap token could post a CSR whose PEM says CN=admin,
        get it signed, and walk through the x509 authenticator as admin —
        the stamped spec.username is the REQUESTER, not the requested
        subject, and both must be checked. A renewal (requester already a
        node) must ask for its own identity."""
        if not cn.startswith("system:node:") or orgs != [NODES_GROUP_NAME]:
            return False
        username = csr.spec.get("username", "")
        if username.startswith("system:node:") and username != cn:
            return False
        return True

    def _sign(self, request_pem: bytes) -> bytes:
        with tempfile.TemporaryDirectory() as tmp:
            paths = {n: f"{tmp}/{n}" for n in
                     ("req.csr", "ca.crt", "ca.key", "out.crt")}
            with open(paths["req.csr"], "wb") as f:
                f.write(request_pem)
            with open(paths["ca.crt"], "wb") as f:
                f.write(self.ca_cert_pem)
            with open(paths["ca.key"], "wb") as f:
                f.write(self.ca_key_pem)
            subprocess.run(
                ["openssl", "x509", "-req", "-in", paths["req.csr"],
                 "-CA", paths["ca.crt"], "-CAkey", paths["ca.key"],
                 "-CAcreateserial", "-days", "30",
                 "-out", paths["out.crt"]],
                check=True, capture_output=True, timeout=60)
            with open(paths["out.crt"], "rb") as f:
                return f.read()

    async def sync(self, key: str) -> None:
        import asyncio

        csr = self.csrs.get(key)
        if csr is None:
            return
        conditions = list(csr.status.get("conditions") or [])
        if self._has(conditions, "Denied"):
            return
        if not self._has(conditions, "Approved"):
            if not self._approvable(csr):
                return  # left Pending for manual approval
            try:
                cn, orgs = await asyncio.to_thread(
                    self._csr_subject,
                    base64.b64decode(csr.spec.get("request", "")))
            except (ValueError, subprocess.SubprocessError) as e:
                log.warning("CSR %s: unparseable request: %s", key, e)
                return  # left Pending
            if not self._subject_allowed(csr, cn, orgs):
                log.warning("CSR %s: subject %r/%r not auto-approvable",
                            key, cn, orgs)
                return  # left Pending for manual review

            def approve(obj):
                conds = obj.status.setdefault("conditions", [])
                if not any(c.get("type") == "Approved" for c in conds):
                    conds.append({"type": "Approved",
                                  "reason": "AutoApproved",
                                  "message": "bootstrap auto-approval"})
                return obj

            try:
                self.store.guaranteed_update(
                    "CertificateSigningRequest", key, "default", approve)
            except (NotFound, Conflict):
                return
            # the approval's MODIFIED watch event re-enqueues for signing
            # once the informer cache carries it — re-enqueueing HERE would
            # spin against the stale cache and starve the informer
            return
        if csr.status.get("certificate"):
            return  # already issued
        request_b64 = csr.spec.get("request", "")
        try:
            # keygen + signing are real subprocess work: off the shared
            # controller-manager loop (leader renewal must not stall)
            request_pem = base64.b64decode(request_b64)
            cert_pem = await asyncio.to_thread(self._sign, request_pem)
        except (ValueError, subprocess.SubprocessError) as e:
            log.warning("CSR %s: signing failed: %s", key, e)
            return

        def put_cert(obj):
            obj.status["certificate"] = \
                base64.b64encode(cert_pem).decode()
            return obj

        try:
            self.store.guaranteed_update(
                "CertificateSigningRequest", key, "default", put_cert)
            log.info("CSR %s: certificate issued", key)
        except (NotFound, Conflict):
            pass
