"""DaemonSet controller: one pod per eligible node, scheduler bypassed.

Analog of pkg/controller/daemon/daemon_controller.go: the daemon controller
places its own pods — it evaluates fit directly (nodeShouldRunDaemonPod
:1327 calls predicates.GeneralPredicates) and creates pods with
spec.nodeName already set, so they never enter the scheduler queue. Fit
here = node Ready (or pod tolerates being there), nodeSelector + required
node-affinity match, NoSchedule/NoExecute taints tolerated, and the pod's
resource requests fit in allocatable minus the node's active pods.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Node, Pod, parse_node_affinity
from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController, slow_start_batch
from kubernetes_tpu.controllers.replicaset import (
    controller_ref,
    is_active,
    make_controller_ref,
)
from kubernetes_tpu.state.cluster_state import match_requirement


def _node_ready(node: Node) -> bool:
    return any(c.type == "Ready" and c.status == "True"
               for c in node.status.conditions)


def _affinity_matches(pod: Pod, node: Node) -> bool:
    labels = node.metadata.labels
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    req_terms, _ = parse_node_affinity(pod.spec.affinity)
    if req_terms is None:
        return True
    for term in req_terms:
        if all(match_requirement(labels, e.get("key", ""),
                                 e.get("operator", "In"),
                                 tuple(e.get("values") or ()))
               for e in term):
            return True
    return False


def _tolerates_taints(pod: Pod, node: Node) -> bool:
    for taint in node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in pod.spec.tolerations):
            return False
    return True


def _pod_requests(pod: Pod) -> tuple:
    cpu = mem = 0
    for c in pod.spec.containers:
        if "cpu" in c.requests:
            cpu += parse_quantity(c.requests["cpu"])
        if "memory" in c.requests:
            mem += parse_quantity(c.requests["memory"])
    return cpu, mem


def _fits_resources(pod: Pod, node: Node, node_pods: list[Pod]) -> bool:
    alloc = node.status.effective_allocatable()
    free_cpu = parse_quantity(alloc.get("cpu", "0"))
    free_mem = parse_quantity(alloc.get("memory", "0"))
    for other in node_pods:
        cpu, mem = _pod_requests(other)
        free_cpu -= cpu
        free_mem -= mem
    cpu, mem = _pod_requests(pod)
    return cpu <= free_cpu and mem <= free_mem


def _node_fingerprint(node: Node) -> tuple:
    """The fields node_should_run reads — heartbeats that change only
    condition timestamps hash equal and are ignored."""
    return (
        _node_ready(node),
        tuple(sorted(node.metadata.labels.items())),
        tuple(sorted((t.key, t.value, t.effect)
                     for t in node.spec.taints)),
        tuple(sorted(node.status.effective_allocatable().items())),
    )


def node_should_run(pod: Pod, node: Node, node_pods: list[Pod]) -> bool:
    """nodeShouldRunDaemonPod (daemon_controller.go:1327): the host-side
    GeneralPredicates subset that matters without the scheduler."""
    if not _node_ready(node):
        return False
    if not _affinity_matches(pod, node):
        return False
    if not _tolerates_taints(pod, node):
        return False
    return _fits_resources(pod, node, node_pods)


def _daemon_pod_name(ds_name: str, node_name: str) -> str:
    """Deterministic per-(ds, node) pod name within the 63-char limit.
    Over-long names keep a unique suffix hash instead of a bare prefix
    truncation, which would collide distinct daemonsets on one node."""
    name = f"{ds_name}-{node_name}"
    if len(name) <= 63:
        return name
    import hashlib

    digest = hashlib.sha1(name.encode()).hexdigest()[:10]
    return f"{name[:52].rstrip('-.')}-{digest}"


class DaemonSetController(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, ds_informer: Informer,
                 pod_informer: Informer, node_informer: Informer):
        super().__init__()
        self.name = "daemonset-controller"
        self.store = store
        self.daemonsets = ds_informer
        self.pods = pod_informer
        self.nodes = node_informer
        self._node_fp: dict[str, tuple] = {}
        ds_informer.add_handler(self._on_ds)
        pod_informer.add_handler(self._on_pod)
        node_informer.add_handler(self._on_node)

    def _on_ds(self, event) -> None:
        if event.type == "DELETED":
            self.expectations.forget(event.obj.key)
        self.enqueue(event.obj.key)

    def _on_pod(self, event) -> None:
        ref = controller_ref(event.obj)
        if ref is None or ref.get("kind") != "DaemonSet":
            return
        key = f"{event.obj.metadata.namespace}/{ref.get('name')}"
        if event.type == "ADDED":
            self.expectations.creation_observed(key)
        elif event.type == "DELETED":
            self.expectations.deletion_observed(key)
        self.enqueue(key)

    def _on_node(self, event) -> None:
        # Node events fan out to every daemonset — but heartbeat-only
        # MODIFIED events (the overwhelming majority at kubemark scale:
        # every hollow node PATCHes conditions on a timer) are dropped by
        # fingerprinting the fit-relevant fields. The reference reacts only
        # to relevant node changes too (daemon_controller.go updateNode).
        node = event.obj
        name = node.metadata.name
        if event.type == "DELETED":
            self._node_fp.pop(name, None)
        else:
            fp = _node_fingerprint(node)
            if event.type == "MODIFIED" and self._node_fp.get(name) == fp:
                return
            self._node_fp[name] = fp
        for ds in self.daemonsets.items():
            self.enqueue(ds.key)

    def _template_pod(self, ds) -> Pod:
        import copy

        d = copy.deepcopy(ds.spec.get("template") or {})
        d.setdefault("metadata", {})
        return Pod.from_dict(d)

    def _owned_by_node(self, ds) -> dict[str, list[Pod]]:
        out: dict[str, list[Pod]] = {}
        for pod in self.pods.items():
            if pod.metadata.namespace != ds.metadata.namespace:
                continue
            ref = controller_ref(pod)
            if ref is None or ref.get("uid") != ds.metadata.uid:
                continue
            out.setdefault(pod.spec.node_name or "", []).append(pod)
        return out

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        ds = self.daemonsets.get(name, ns)
        if ds is None:
            self.expectations.forget(key)
            return
        if not self.expectations.satisfied(key):
            return
        probe = self._template_pod(ds)
        by_node = self._owned_by_node(ds)
        pods_per_node: dict[str, list[Pod]] = {}
        for pod in self.pods.items():
            if pod.spec.node_name and is_active(pod):
                pods_per_node.setdefault(pod.spec.node_name, []).append(pod)

        to_create: list[str] = []
        to_delete: list[Pod] = []
        seen_nodes = set()
        for node in self.nodes.items():
            seen_nodes.add(node.metadata.name)
            mine = [p for p in by_node.get(node.metadata.name, ())
                    if is_active(p)]
            others = [p for p in pods_per_node.get(node.metadata.name, ())
                      if not any(p is m for m in mine)]
            should = node_should_run(probe, node, others)
            if should and not mine:
                to_create.append(node.metadata.name)
            elif not should and mine:
                to_delete.extend(mine)
            elif len(mine) > 1:
                # duplicates: keep the oldest (manage :1030)
                mine.sort(key=lambda p: p.metadata.creation_timestamp)
                to_delete.extend(mine[1:])
        # pods on nodes that no longer exist
        for node_name, pods in by_node.items():
            if node_name and node_name not in seen_nodes:
                to_delete.extend(p for p in pods if is_active(p))

        if to_delete:
            self.expectations.expect(key, dels=len(to_delete))
            for pod in to_delete:
                try:
                    self.store.delete("Pod", pod.metadata.name, ns)
                except NotFound:
                    self.expectations.deletion_observed(key)
        if to_create:
            self.expectations.expect(key, adds=len(to_create))
            queue = list(to_create)

            async def create_one() -> bool:
                node_name = queue.pop()
                pod = self._template_pod(ds)
                pod.metadata.name = _daemon_pod_name(ds.metadata.name,
                                                     node_name)
                pod.metadata.namespace = ns
                pod.metadata.owner_references = [make_controller_ref(ds)]
                if not pod.metadata.labels:
                    pod.metadata.labels = dict(
                        (ds.selector.get("matchLabels")) or {})
                pod.spec.node_name = node_name  # the scheduler bypass
                try:
                    self.store.create(pod)
                    return True
                except Exception:  # noqa: BLE001
                    self.expectations.creation_observed(key)
                    return False

            _ok, attempted = await slow_start_batch(len(to_create), create_one)
            for _ in range(len(to_create) - attempted):
                self.expectations.creation_observed(key)

        self._update_status(ds, by_node, seen_nodes, probe, pods_per_node)

    def _update_status(self, ds, by_node, seen_nodes, probe,
                       pods_per_node) -> None:
        desired = current = ready = 0
        for node in self.nodes.items():
            others = [p for p in pods_per_node.get(node.metadata.name, ())
                      if controller_ref(p) is None
                      or (controller_ref(p) or {}).get("uid")
                      != ds.metadata.uid]
            if node_should_run(probe, node, others):
                desired += 1
            mine = [p for p in by_node.get(node.metadata.name, ())
                    if is_active(p)]
            if mine:
                current += 1
                if any(p.status.phase == "Running" for p in mine):
                    ready += 1
        status = {"desiredNumberScheduled": desired,
                  "currentNumberScheduled": current,
                  "numberReady": ready}
        fresh = self.daemonsets.get(ds.metadata.name, ds.metadata.namespace)
        if fresh is None or fresh.status == status:
            return
        fresh = fresh.clone()
        fresh.status = status
        try:
            # CAS against the informer-cache version: stale loses and the
            # next resync writes the recomputed status
            self.store.update(fresh)
        except Exception:  # noqa: BLE001 — status write is best-effort
            pass
