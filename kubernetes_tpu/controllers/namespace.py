"""Namespace controller: cascade deletion of namespace contents.

The pkg/controller/namespace analog (namespace_controller.go syncNamespace
-> deletion.go deleteAllContent): when a Namespace enters Terminating, the
controller deletes every namespaced object inside it across all known
kinds, then finalizes by removing the Namespace object itself."""

from __future__ import annotations

import time

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

def _namespaced_kinds() -> tuple[str, ...]:
    """Derived from the serving tables (deletion.go discovers resources
    dynamically): every served kind that is neither cluster-scoped nor a
    virtual subresource payload. One source of truth with discovery, so
    the sweep and `namespaced:` in APIResourceList can't drift."""
    from kubernetes_tpu.apiserver.http import RESOURCES, APIServer

    return tuple(sorted(
        kind for kind in set(RESOURCES.values())
        if kind not in APIServer.CLUSTER_SCOPED and kind != "Binding"))


# the namespaced kinds swept on termination
NAMESPACED_KINDS = _namespaced_kinds()


class NamespaceController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, ns_informer: Informer):
        super().__init__()
        self.name = "namespace-controller"
        self.store = store
        self.namespaces = ns_informer
        ns_informer.add_handler(self._on_namespace)

    def _on_namespace(self, event) -> None:
        if event.type != "DELETED":
            self.enqueue(event.obj.metadata.name)

    async def sync(self, key: str) -> None:
        ns_obj = self.namespaces.get(key)
        if ns_obj is None:
            return
        if ns_obj.phase != "Terminating" \
                and ns_obj.metadata.deletion_timestamp is None:
            return
        if ns_obj.phase != "Terminating":
            # phase transition first, so admission rejects new content
            # while the sweep runs (syncNamespace :154)
            def mark(obj):
                obj.status["phase"] = "Terminating"
                return obj

            try:
                self.store.guaranteed_update("Namespace", key, "default",
                                             mark)
            except (NotFound, Conflict):
                return
        remaining = 0
        # CRD-backed custom resources are namespaced content too
        # (deleteAllContent discovers resources dynamically)
        for kind in namespace_kinds(self.store):
            for obj in list(self.store.list(kind, namespace=key,
                                            copy_objects=False)):
                try:
                    self.store.delete(kind, obj.metadata.name, key)
                except NotFound:
                    continue
                remaining += 1
        if remaining:
            self.enqueue_after(key, 0.05)  # re-check until empty
            return
        # finalize: the namespace object itself goes away (deletion.go
        # retryOnConflictError(finalizeNamespace) then delete)
        try:
            self.store.delete("Namespace", key)
        except NotFound:
            pass


def namespace_kinds(store: ObjectStore) -> list[str]:
    """Every namespaced kind, including CRD-backed custom resources."""
    kinds = list(NAMESPACED_KINDS)
    for crd in store.list("CustomResourceDefinition", copy_objects=False):
        if crd.target_kind:
            kinds.append(crd.target_kind)
    return kinds


def namespace_is_empty(store: ObjectStore, name: str) -> bool:
    return not any(store.list(kind, namespace=name, copy_objects=False)
                   for kind in namespace_kinds(store))


def request_namespace_deletion(store: ObjectStore, name: str) -> None:
    """The DELETE-namespace API semantics: set deletionTimestamp +
    Terminating instead of removing the object, letting the controller
    cascade (registry namespace strategy)."""
    def mutate(obj):
        obj.metadata.deletion_timestamp = time.time()
        obj.status["phase"] = "Terminating"
        return obj

    store.guaranteed_update("Namespace", name, "default", mutate)
