"""PersistentVolume binder + attach/detach controllers.

PersistentVolumeBinder — analog of pkg/controller/volume/persistentvolume/
pv_controller.go: pair pending PVCs with the smallest satisfying Available
PV (findBestMatchForClaim semantics: capacity >= request, accessModes
superset, label selector matches, storageClassName equal), write the
bidirectional bind (pv.spec.claimRef <-> pvc.spec.volumeName) and the
Bound phases; on claim deletion apply persistentVolumeReclaimPolicy
(Retain -> Released, Recycle -> scrub back to Available, Delete -> remove
the PV object).

AttachDetachController — analog of pkg/controller/volume/attachdetach/
attach_detach_controller.go: the desired world is every scheduled,
non-terminal pod's PV-backed volumes on its node; the actual world is
node.status.volumesAttached. Reconcile by updating the node status through
the store (the kubelet volumemanager then mounts what is attached).
"""

from __future__ import annotations

import logging

from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import is_active
from kubernetes_tpu.state.podaffinity import (
    PARSE_ERROR,
    canonical_selector,
    selector_matches,
)

log = logging.getLogger(__name__)

ACCESS_MODES = ("ReadWriteOnce", "ReadOnlyMany", "ReadWriteMany")


def _capacity(obj_spec: dict):
    cap = ((obj_spec.get("capacity") or {}).get("storage")
           or ((obj_spec.get("resources") or {}).get("requests")
               or {}).get("storage") or "0")
    return parse_quantity(str(cap))


def _modes(spec: dict) -> frozenset[str]:
    return frozenset(spec.get("accessModes") or ())


def pv_matches_claim(pv, pvc) -> bool:
    """findBestMatchForClaim's per-volume predicate (index.go
    findMatchingVolume semantics at this vintage)."""
    if pv.spec.get("claimRef"):
        return False
    if _capacity(pv.spec) < _capacity(pvc.spec):
        return False
    if not _modes(pvc.spec) <= _modes(pv.spec):
        return False
    # storageClassName must agree (annotation-era: volume.beta... class)
    if (pv.spec.get("storageClassName") or "") != \
            (pvc.spec.get("storageClassName") or ""):
        return False
    sel = pvc.spec.get("selector")
    if sel:
        canon = canonical_selector(sel)
        if canon == PARSE_ERROR or not selector_matches(
                canon, pv.metadata.labels):
            return False
    return True


PROVISIONED_BY_ANNOTATION = "pv.kubernetes.io/provisioned-by"
# annotation-era class reference (the 1.8 wire still honors it alongside
# spec.storageClassName, pv_controller.go GetClaimStorageClass)
BETA_CLASS_ANNOTATION = "volume.beta.kubernetes.io/storage-class"
FAKE_PROVISIONER = "kubernetes.io/fake"


def fake_provision(claim, storage_class: dict, pv_name: str) -> dict:
    """Default provisioner SPI implementation — the fake-cloud analog of
    the gce-pd/aws-ebs provisioners (pkg/cloudprovider-backed plugins'
    Provision(): allocate a disk sized to the claim, return a PV spec).
    `storage_class` is the StorageClass body (provisioner/parameters/
    reclaimPolicy); parameters.type names the fake disk family."""
    requests = (claim.spec.get("resources") or {}).get("requests") or {}
    params = storage_class.get("parameters") or {}
    return {
        "capacity": {"storage": requests.get("storage", "1Gi")},
        "accessModes": list(claim.spec.get("accessModes")
                            or ["ReadWriteOnce"]),
        "persistentVolumeReclaimPolicy":
            storage_class.get("reclaimPolicy", "Delete"),
        "gcePersistentDisk": {"pdName": f"{params.get('type', 'fake')}-"
                                        f"{pv_name}",
                              "fsType": params.get("fsType", "ext4")},
    }


class PersistentVolumeBinder(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, pvc_informer: Informer,
                 pv_informer: Informer, provisioners: dict | None = None):
        super().__init__()
        self.name = "persistentvolume-binder"
        self.store = store
        self.claims = pvc_informer
        self.volumes = pv_informer
        # provisioner name -> fn(claim, class_body, pv_name) -> pv spec
        # (the dynamic-provisioning half of the volume SPI)
        self.provisioners = {FAKE_PROVISIONER: fake_provision}
        self.provisioners.update(provisioners or {})
        pvc_informer.add_handler(self._on_claim)
        pv_informer.add_handler(self._on_volume)

    def _on_claim(self, event) -> None:
        if event.type == "DELETED":
            self._release(event.obj)
            return
        self.enqueue(event.obj.key)

    def _on_volume(self, event) -> None:
        if event.type == "DELETED":
            return
        # a new/updated volume may satisfy a pending claim
        for pvc in self.claims.items():
            if not pvc.volume_name:
                self.enqueue(pvc.key)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pvc = self.claims.get(name, ns)
        if pvc is None or pvc.volume_name:
            return
        # a volume already claimRef'd to THIS claim finishes its half-done
        # bind first (the provision-then-crash resume path,
        # pv_controller.go syncUnboundClaim's found-by-claimref branch)
        for pv in self.volumes.items():
            if (pv.spec.get("claimRef") or {}).get("uid") \
                    == pvc.metadata.uid:
                self._finish_bind(pvc, pv.metadata.name)
                return
        # smallest satisfying Available volume wins (pv_matches_claim
        # already excludes claimRef'd volumes)
        candidates = [pv for pv in self.volumes.items()
                      if pv_matches_claim(pv, pvc)]
        if not candidates:
            # dynamic provisioning (pv_controller.go:1230 provisionClaim):
            # a claim naming a StorageClass gets a volume minted by the
            # class's provisioner instead of waiting forever
            if self._provision(pvc):
                return
            self._set_phase_pvc(pvc, "Pending")
            return
        best = min(candidates, key=lambda pv: (_capacity(pv.spec),
                                               pv.metadata.name))
        claim_ref = {"kind": "PersistentVolumeClaim", "namespace": ns,
                     "name": name, "uid": pvc.metadata.uid}

        def bind_pv(obj):
            if obj.spec.get("claimRef"):
                raise Conflict(f"{obj.metadata.name} already claimed")
            obj.spec["claimRef"] = claim_ref
            obj.status["phase"] = "Bound"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolume",
                                         best.metadata.name, "default",
                                         bind_pv)
        except (NotFound, Conflict):
            self.enqueue_after(key, 0.05)  # raced another claim: retry
            return

        def bind_pvc(obj):
            obj.spec["volumeName"] = best.metadata.name
            obj.status["phase"] = "Bound"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolumeClaim", name, ns,
                                         bind_pvc)
        except (NotFound, Conflict):
            # claim vanished mid-bind: roll the volume back
            self._scrub(best.metadata.name)

    def _provision(self, pvc) -> bool:
        """provisionClaimOperation (pv_controller.go:1282): create a PV
        from the class's provisioner, PRE-BOUND to the claim (claimRef set
        at creation so no other claim can race onto it), then point the
        claim at it. Returns True when the claim is being handled by
        provisioning (even if a step raced — the next sync retries)."""
        cls_name = (pvc.spec.get("storageClassName")
                    or pvc.metadata.annotations.get(BETA_CLASS_ANNOTATION)
                    or "")
        if not cls_name:
            return False
        try:
            storage_class = self.store.get("StorageClass", cls_name)
        except NotFound:
            return False
        body = getattr(storage_class, "body", None) or {}
        provision = self.provisioners.get(body.get("provisioner", ""))
        if provision is None:
            log.warning("claim %s: no provisioner %r registered",
                        pvc.key, body.get("provisioner"))
            return False
        pv_name = f"pvc-{pvc.metadata.uid}"
        claim_ref = {"kind": "PersistentVolumeClaim",
                     "namespace": pvc.metadata.namespace,
                     "name": pvc.metadata.name, "uid": pvc.metadata.uid}
        try:
            self.store.get("PersistentVolume", pv_name)
        except NotFound:
            from kubernetes_tpu.api.objects import PersistentVolume

            spec = provision(pvc, body, pv_name)
            spec["claimRef"] = claim_ref
            spec["storageClassName"] = cls_name
            pv = PersistentVolume.from_dict({
                "metadata": {"name": pv_name,
                             "annotations": {PROVISIONED_BY_ANNOTATION:
                                             body.get("provisioner", "")}},
                "spec": spec})
            # born Pending like the reference's provisioned volumes
            # (pv_controller.go ctrl.provisionClaimOperation creates with
            # no phase); _finish_bind flips it to Bound only once the
            # claim side of the bind actually lands
            pv.status["phase"] = "Pending"
            try:
                self.store.create(pv)
            except AlreadyExists:
                pass  # another worker won the race: fall through to bind

        self._finish_bind(pvc, pv_name)
        return True

    def _finish_bind(self, pvc, pv_name: str) -> None:
        """Point the claim at a volume that already claimRefs it."""
        def bind_pvc(obj):
            obj.spec["volumeName"] = pv_name
            obj.status["phase"] = "Bound"
            return obj

        try:
            self.store.guaranteed_update(
                "PersistentVolumeClaim", pvc.metadata.name,
                pvc.metadata.namespace, bind_pvc)
        except Conflict:
            # a CAS miss only means SOMEONE ELSE wrote the claim — it is
            # still there. Treating it as "claim vanished" (and deleting
            # the freshly provisioned volume) would strand the claim;
            # retry the bind on a later sync instead.
            self.enqueue_after(pvc.key, 0.05)
            return
        except NotFound:
            # claim genuinely vanished mid-bind: a dynamically PROVISIONED
            # volume honors its Delete reclaim policy (pv_controller
            # deletes orphaned provisioned volumes — recycling one as
            # Available would hand a future claim a used fake disk);
            # pre-existing volumes just free up
            try:
                pv = self.store.get("PersistentVolume", pv_name)
            except NotFound:
                return
            if PROVISIONED_BY_ANNOTATION in pv.metadata.annotations \
                    and pv.spec.get("persistentVolumeReclaimPolicy") \
                    == "Delete":
                try:
                    self.store.delete("PersistentVolume", pv_name)
                except NotFound:
                    pass
            else:
                self._scrub(pv_name)
            return

        def pv_bound(obj):
            obj.status["phase"] = "Bound"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolume", pv_name,
                                         "default", pv_bound)
        except (NotFound, Conflict):
            pass

    def _set_phase_pvc(self, pvc, phase: str) -> None:
        if pvc.phase == phase:
            return

        def mutate(obj):
            obj.status["phase"] = phase
            return obj

        try:
            self.store.guaranteed_update(
                "PersistentVolumeClaim", pvc.metadata.name,
                pvc.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass

    def _scrub(self, pv_name: str) -> None:
        def mutate(obj):
            obj.spec.pop("claimRef", None)
            obj.status["phase"] = "Available"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolume", pv_name,
                                         "default", mutate)
        except (NotFound, Conflict):
            pass

    def _release(self, pvc) -> None:
        """Claim deleted: apply the bound volume's reclaim policy
        (pv_controller.go reclaimVolume)."""
        if not pvc.volume_name:
            return
        try:
            pv = self.store.get("PersistentVolume", pvc.volume_name)
        except NotFound:
            return
        ref = pv.spec.get("claimRef") or {}
        if ref.get("uid") != pvc.metadata.uid:
            return  # already rebound elsewhere
        policy = pv.spec.get("persistentVolumeReclaimPolicy", "Retain")
        if policy == "Delete":
            try:
                self.store.delete("PersistentVolume", pv.metadata.name)
            except NotFound:
                pass
        elif policy == "Recycle":
            self._scrub(pv.metadata.name)
        else:  # Retain: released, needs admin action before reuse
            def mutate(obj):
                obj.status["phase"] = "Released"
                return obj

            try:
                self.store.guaranteed_update("PersistentVolume",
                                             pv.metadata.name, "default",
                                             mutate)
            except (NotFound, Conflict):
                pass


def _attached_name(pv_name: str) -> str:
    return f"kubernetes.io/pv/{pv_name}"


class AttachDetachController(ReconcileController):
    """Keyed by node name; sync reconciles that node's volumesAttached
    against the PV-backed volumes of its active pods."""

    workers = 1

    def __init__(self, store: ObjectStore, node_informer: Informer,
                 pod_informer: Informer, pvc_informer: Informer):
        super().__init__()
        self.name = "attachdetach-controller"
        self.store = store
        self.nodes = node_informer
        self.pods = pod_informer
        self.claims = pvc_informer
        node_informer.add_handler(self._on_node)
        pod_informer.add_handler(self._on_pod)
        pvc_informer.add_handler(self._on_claim)

    def _on_node(self, event) -> None:
        if event.type == "ADDED":
            self.enqueue(event.obj.metadata.name)

    def _on_pod(self, event) -> None:
        node = event.obj.spec.node_name
        if node:
            self.enqueue(node)

    def _on_claim(self, event) -> None:
        # a claim binding late must attach for already-scheduled pods —
        # re-sync the nodes of pods referencing it
        name = event.obj.metadata.name
        ns = event.obj.metadata.namespace
        for pod in self.pods.items():
            if not pod.spec.node_name or pod.metadata.namespace != ns:
                continue
            if any((v.get("persistentVolumeClaim") or {}).get("claimName")
                   == name for v in pod.spec.volumes):
                self.enqueue(pod.spec.node_name)

    def _desired(self, node_name: str) -> list[str]:
        out: set[str] = set()
        for pod in self.pods.items():
            if pod.spec.node_name != node_name or not is_active(pod):
                continue
            for vol in pod.spec.volumes:
                claim = (vol.get("persistentVolumeClaim") or {}).get(
                    "claimName")
                if not claim:
                    continue
                pvc = self.claims.get(claim, pod.metadata.namespace)
                if pvc is not None and pvc.volume_name:
                    out.add(pvc.volume_name)
        return sorted(out)

    async def sync(self, key: str) -> None:
        node = self.nodes.get(key)
        if node is None:
            return
        want = [{"name": _attached_name(pv), "devicePath": f"/dev/disk/{pv}"}
                for pv in self._desired(key)]
        if node.status.volumes_attached == want:
            return

        def mutate(obj):
            obj.status.volumes_attached = want
            return obj

        try:
            self.store.guaranteed_update("Node", key, "default", mutate)
        except (NotFound, Conflict):
            pass
