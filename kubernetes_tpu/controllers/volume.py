"""PersistentVolume binder + attach/detach controllers.

PersistentVolumeBinder — analog of pkg/controller/volume/persistentvolume/
pv_controller.go: pair pending PVCs with the smallest satisfying Available
PV (findBestMatchForClaim semantics: capacity >= request, accessModes
superset, label selector matches, storageClassName equal), write the
bidirectional bind (pv.spec.claimRef <-> pvc.spec.volumeName) and the
Bound phases; on claim deletion apply persistentVolumeReclaimPolicy
(Retain -> Released, Recycle -> scrub back to Available, Delete -> remove
the PV object).

AttachDetachController — analog of pkg/controller/volume/attachdetach/
attach_detach_controller.go: the desired world is every scheduled,
non-terminal pod's PV-backed volumes on its node; the actual world is
node.status.volumesAttached. Reconcile by updating the node status through
the store (the kubelet volumemanager then mounts what is attached).
"""

from __future__ import annotations

from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import is_active
from kubernetes_tpu.state.podaffinity import (
    PARSE_ERROR,
    canonical_selector,
    selector_matches,
)

ACCESS_MODES = ("ReadWriteOnce", "ReadOnlyMany", "ReadWriteMany")


def _capacity(obj_spec: dict):
    cap = ((obj_spec.get("capacity") or {}).get("storage")
           or ((obj_spec.get("resources") or {}).get("requests")
               or {}).get("storage") or "0")
    return parse_quantity(str(cap))


def _modes(spec: dict) -> frozenset[str]:
    return frozenset(spec.get("accessModes") or ())


def pv_matches_claim(pv, pvc) -> bool:
    """findBestMatchForClaim's per-volume predicate (index.go
    findMatchingVolume semantics at this vintage)."""
    if pv.spec.get("claimRef"):
        return False
    if _capacity(pv.spec) < _capacity(pvc.spec):
        return False
    if not _modes(pvc.spec) <= _modes(pv.spec):
        return False
    # storageClassName must agree (annotation-era: volume.beta... class)
    if (pv.spec.get("storageClassName") or "") != \
            (pvc.spec.get("storageClassName") or ""):
        return False
    sel = pvc.spec.get("selector")
    if sel:
        canon = canonical_selector(sel)
        if canon == PARSE_ERROR or not selector_matches(
                canon, pv.metadata.labels):
            return False
    return True


class PersistentVolumeBinder(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, pvc_informer: Informer,
                 pv_informer: Informer):
        super().__init__()
        self.name = "persistentvolume-binder"
        self.store = store
        self.claims = pvc_informer
        self.volumes = pv_informer
        pvc_informer.add_handler(self._on_claim)
        pv_informer.add_handler(self._on_volume)

    def _on_claim(self, event) -> None:
        if event.type == "DELETED":
            self._release(event.obj)
            return
        self.enqueue(event.obj.key)

    def _on_volume(self, event) -> None:
        if event.type == "DELETED":
            return
        # a new/updated volume may satisfy a pending claim
        for pvc in self.claims.items():
            if not pvc.volume_name:
                self.enqueue(pvc.key)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pvc = self.claims.get(name, ns)
        if pvc is None or pvc.volume_name:
            return
        # smallest satisfying Available volume wins
        candidates = [pv for pv in self.volumes.items()
                      if pv_matches_claim(pv, pvc)]
        if not candidates:
            self._set_phase_pvc(pvc, "Pending")
            return
        best = min(candidates, key=lambda pv: (_capacity(pv.spec),
                                               pv.metadata.name))
        claim_ref = {"kind": "PersistentVolumeClaim", "namespace": ns,
                     "name": name, "uid": pvc.metadata.uid}

        def bind_pv(obj):
            if obj.spec.get("claimRef"):
                raise Conflict(f"{obj.metadata.name} already claimed")
            obj.spec["claimRef"] = claim_ref
            obj.status["phase"] = "Bound"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolume",
                                         best.metadata.name, "default",
                                         bind_pv)
        except (NotFound, Conflict):
            self.enqueue_after(key, 0.05)  # raced another claim: retry
            return

        def bind_pvc(obj):
            obj.spec["volumeName"] = best.metadata.name
            obj.status["phase"] = "Bound"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolumeClaim", name, ns,
                                         bind_pvc)
        except (NotFound, Conflict):
            # claim vanished mid-bind: roll the volume back
            self._scrub(best.metadata.name)

    def _set_phase_pvc(self, pvc, phase: str) -> None:
        if pvc.phase == phase:
            return

        def mutate(obj):
            obj.status["phase"] = phase
            return obj

        try:
            self.store.guaranteed_update(
                "PersistentVolumeClaim", pvc.metadata.name,
                pvc.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass

    def _scrub(self, pv_name: str) -> None:
        def mutate(obj):
            obj.spec.pop("claimRef", None)
            obj.status["phase"] = "Available"
            return obj

        try:
            self.store.guaranteed_update("PersistentVolume", pv_name,
                                         "default", mutate)
        except (NotFound, Conflict):
            pass

    def _release(self, pvc) -> None:
        """Claim deleted: apply the bound volume's reclaim policy
        (pv_controller.go reclaimVolume)."""
        if not pvc.volume_name:
            return
        try:
            pv = self.store.get("PersistentVolume", pvc.volume_name)
        except NotFound:
            return
        ref = pv.spec.get("claimRef") or {}
        if ref.get("uid") != pvc.metadata.uid:
            return  # already rebound elsewhere
        policy = pv.spec.get("persistentVolumeReclaimPolicy", "Retain")
        if policy == "Delete":
            try:
                self.store.delete("PersistentVolume", pv.metadata.name)
            except NotFound:
                pass
        elif policy == "Recycle":
            self._scrub(pv.metadata.name)
        else:  # Retain: released, needs admin action before reuse
            def mutate(obj):
                obj.status["phase"] = "Released"
                return obj

            try:
                self.store.guaranteed_update("PersistentVolume",
                                             pv.metadata.name, "default",
                                             mutate)
            except (NotFound, Conflict):
                pass


def _attached_name(pv_name: str) -> str:
    return f"kubernetes.io/pv/{pv_name}"


class AttachDetachController(ReconcileController):
    """Keyed by node name; sync reconciles that node's volumesAttached
    against the PV-backed volumes of its active pods."""

    workers = 1

    def __init__(self, store: ObjectStore, node_informer: Informer,
                 pod_informer: Informer, pvc_informer: Informer):
        super().__init__()
        self.name = "attachdetach-controller"
        self.store = store
        self.nodes = node_informer
        self.pods = pod_informer
        self.claims = pvc_informer
        node_informer.add_handler(self._on_node)
        pod_informer.add_handler(self._on_pod)
        pvc_informer.add_handler(self._on_claim)

    def _on_node(self, event) -> None:
        if event.type == "ADDED":
            self.enqueue(event.obj.metadata.name)

    def _on_pod(self, event) -> None:
        node = event.obj.spec.node_name
        if node:
            self.enqueue(node)

    def _on_claim(self, event) -> None:
        # a claim binding late must attach for already-scheduled pods —
        # re-sync the nodes of pods referencing it
        name = event.obj.metadata.name
        ns = event.obj.metadata.namespace
        for pod in self.pods.items():
            if not pod.spec.node_name or pod.metadata.namespace != ns:
                continue
            if any((v.get("persistentVolumeClaim") or {}).get("claimName")
                   == name for v in pod.spec.volumes):
                self.enqueue(pod.spec.node_name)

    def _desired(self, node_name: str) -> list[str]:
        out: set[str] = set()
        for pod in self.pods.items():
            if pod.spec.node_name != node_name or not is_active(pod):
                continue
            for vol in pod.spec.volumes:
                claim = (vol.get("persistentVolumeClaim") or {}).get(
                    "claimName")
                if not claim:
                    continue
                pvc = self.claims.get(claim, pod.metadata.namespace)
                if pvc is not None and pvc.volume_name:
                    out.add(pvc.volume_name)
        return sorted(out)

    async def sync(self, key: str) -> None:
        node = self.nodes.get(key)
        if node is None:
            return
        want = [{"name": _attached_name(pv), "devicePath": f"/dev/disk/{pv}"}
                for pv in self._desired(key)]
        if node.status.volumes_attached == want:
            return

        def mutate(obj):
            obj.status.volumes_attached = want
            return obj

        try:
            self.store.guaranteed_update("Node", key, "default", mutate)
        except (NotFound, Conflict):
            pass
