"""Node IPAM (pod-CIDR allocation) + cloud route controllers.

NodeIpamController — analog of the CIDR allocator half of the reference
node controller (pkg/controller/node/cidr_allocator.go): carve the
cluster CIDR into per-node /`node_mask` subnets and write each new node's
spec.podCIDR; released on node delete, reused for new nodes.

RouteController — analog of pkg/controller/route/routecontroller.go:
reconcile the cloud's route table against the nodes' pod CIDRs — a route
per (node, podCIDR), stale routes (node gone or CIDR changed) deleted.
"""

from __future__ import annotations

import ipaddress
import logging

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

log = logging.getLogger(__name__)


class NodeIpamController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, node_informer: Informer,
                 cluster_cidr: str = "10.244.0.0/16",
                 node_mask: int = 24):
        super().__init__()
        self.name = "node-ipam-controller"
        self.store = store
        self.nodes = node_informer
        net = ipaddress.ip_network(cluster_cidr)
        self._subnets = [str(s) for s in net.subnets(
            new_prefix=node_mask)]
        self._assigned: dict[str, str] = {}  # node -> cidr
        self._starved: set[str] = set()  # waiting on pool exhaustion
        # allocation is a monotonic pointer + a free stack (a linear scan
        # of the subnet list per allocation is O(N^2) across a large
        # cluster's startup); adopted CIDRs are skipped at hand-out time
        self._next = 0
        self._free: list[str] = []
        self._starved_logged = False
        node_informer.add_handler(self._on_node)

    def _on_node(self, event) -> None:
        name = event.obj.metadata.name
        if event.type == "DELETED":
            freed = self._assigned.pop(name, None)
            if freed is not None:
                self._free.append(freed)  # cidr returns to the pool
                self._starved_logged = False
            self._starved.discard(name)  # a dead node stops waiting
            # a freed subnet may unblock a node starved at exhaustion
            for starved in list(self._starved):
                self.enqueue(starved)
            return
        if not event.obj.spec.pod_cidr:
            self.enqueue(name)
        else:
            # adopt pre-assigned CIDRs (restart path: the informer relist
            # replays every node) so the pool doesn't double-allocate
            self._assigned.setdefault(name, event.obj.spec.pod_cidr)

    def _alloc(self, in_use: set[str]) -> str | None:
        while self._free:
            s = self._free.pop()
            if s not in in_use:
                return s
        while self._next < len(self._subnets):
            s = self._subnets[self._next]
            self._next += 1
            if s not in in_use:  # adopted by a restarted node: skip
                return s
        return None

    async def sync(self, key: str) -> None:
        if key in self._assigned:
            return  # already allocated; a stale-cache re-run must not
            # reassign an immutable podCIDR (heartbeat raced our write)
        node = self.nodes.get(key)
        if node is None or node.spec.pod_cidr:
            return
        in_use = set(self._assigned.values())
        cidr = self._alloc(in_use)
        if cidr is None:
            if not self._starved_logged:
                log.error("node-ipam: cluster CIDR exhausted at %d nodes",
                          len(in_use))
                self._starved_logged = True
            self._starved.add(key)  # re-enqueued when a node frees one
            return
        self._starved.discard(key)
        self._assigned[key] = cidr

        def mutate(obj):
            obj.spec.pod_cidr = cidr
            return obj

        try:
            self.store.guaranteed_update("Node", key, "default", mutate)
        except (NotFound, Conflict):
            self._assigned.pop(key, None)


class RouteController(ReconcileController):
    workers = 1
    RESYNC = 10.0  # the reference loops every 10s (routecontroller.go)

    def __init__(self, store: ObjectStore, cloud, node_informer: Informer,
                 resync_period: float = RESYNC):
        super().__init__()
        self.name = "route-controller"
        self.store = store
        self.cloud = cloud
        self.nodes = node_informer
        self.resync_period = resync_period
        self._resync_task = None
        node_informer.add_handler(self._on_node)

    async def start(self) -> None:
        await super().start()
        # ONE dedicated periodic task (the quota controller's pattern):
        # rescheduling from sync() would spawn a new timer chain per
        # event-triggered sync and multiply the reconcile rate without
        # bound under node heartbeats
        import asyncio

        self.enqueue("reconcile")
        self._resync_task = asyncio.get_running_loop().create_task(
            self._resync_loop())

    def stop(self) -> None:
        if self._resync_task is not None:
            self._resync_task.cancel()
            self._resync_task = None
        super().stop()

    async def _resync_loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.resync_period)
            self.enqueue("reconcile")

    def _on_node(self, event) -> None:
        self.enqueue("reconcile")

    async def sync(self, key: str) -> None:
        want = {n.metadata.name: n.spec.pod_cidr
                for n in self.nodes.items() if n.spec.pod_cidr}
        have = self.cloud.list_routes()
        for node, cidr in want.items():
            if have.get(node) != cidr:
                if node in have:
                    # replace, don't rely on provider upsert semantics: a
                    # table keyed by destination CIDR would keep routing
                    # the STALE subnet to this node
                    self.cloud.delete_route(node)
                self.cloud.create_route(node, cidr)
        for node in have:
            if node not in want:
                self.cloud.delete_route(node)
