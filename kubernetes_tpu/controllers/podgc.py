"""Pod garbage collection: bound terminated-pod accumulation.

The pkg/controller/podgc analog (gc_controller.go): when the number of
terminated (Succeeded/Failed) pods exceeds the configured threshold, delete
the oldest beyond it (--terminated-pod-gc-threshold, default 12500). Keeps
the finished-pod record bounded so Jobs can run forever without the store
growing unbounded."""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.apiserver.store import NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer

log = logging.getLogger(__name__)

TERMINATED_POD_GC_THRESHOLD = 12500  # gc_controller.go flag default


class PodGCController:
    """Periodic sweep (gcc.gc runs every gcCheckPeriod=20s)."""

    name = "podgc-controller"

    def __init__(self, store: ObjectStore, pod_informer: Informer, *,
                 threshold: int = TERMINATED_POD_GC_THRESHOLD,
                 check_period: float = 20.0):
        self.store = store
        self.pods = pod_informer
        self.threshold = threshold
        self.check_period = check_period
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def gc_once(self) -> int:
        """One sweep; returns pods deleted (gcTerminated,
        gc_controller.go:115: sort by creation, delete oldest overflow)."""
        terminated = [p for p in self.pods.items()
                      if p.status.phase in ("Succeeded", "Failed")]
        overflow = len(terminated) - self.threshold
        if overflow <= 0:
            return 0
        terminated.sort(key=lambda p: p.metadata.creation_timestamp)
        deleted = 0
        for pod in terminated[:overflow]:
            try:
                self.store.delete("Pod", pod.metadata.name,
                                  pod.metadata.namespace)
                deleted += 1
            except NotFound:
                pass
        if deleted:
            log.info("podgc: deleted %d terminated pods over threshold %d",
                     deleted, self.threshold)
        return deleted

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.check_period)
            try:
                self.gc_once()
            except Exception:  # noqa: BLE001 — the sweep must not die
                log.exception("podgc sweep failed")
