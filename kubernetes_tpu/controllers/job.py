"""Job controller: run-to-completion workloads.

The pkg/controller/job/jobcontroller.go analog (syncJob :436, manageJob
:593): keep `parallelism` active pods while fewer than `completions` have
Succeeded; count Succeeded/Failed into status; on completion, add the
Complete condition and delete nothing (finished pods are the record). Uses
the shared expectations + slow-start machinery the way the reference does.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController, slow_start_batch
from kubernetes_tpu.controllers.replicaset import (
    controller_ref,
    is_active,
    pod_from_template,
)
from kubernetes_tpu.state.podaffinity import (
    PARSE_ERROR,
    canonical_selector,
    selector_matches,
)
from kubernetes_tpu.utils.clock import SYSTEM_CLOCK, Clock


class JobController(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, job_informer: Informer,
                 pod_informer: Informer, clock: Clock = SYSTEM_CLOCK):
        super().__init__()
        self.name = "job-controller"
        # injected clock: deadline/stamp math replays under a warped test
        # clock (and keeps lint R4 extensible to controllers)
        self.clock = clock
        self.store = store
        self.jobs = job_informer
        self.pods = pod_informer
        job_informer.add_handler(self._on_job)
        pod_informer.add_handler(self._on_pod)

    def _on_job(self, event) -> None:
        if event.obj.kind == "Job":
            if event.type == "DELETED":
                self.expectations.forget(event.obj.key)
            self.enqueue(event.obj.key)

    def _on_pod(self, event) -> None:
        ref = controller_ref(event.obj)
        if ref is None or ref.get("kind") != "Job":
            return
        key = f"{event.obj.metadata.namespace}/{ref.get('name')}"
        if event.type == "ADDED":
            self.expectations.creation_observed(key)
        elif event.type == "DELETED":
            self.expectations.deletion_observed(key)
        self.enqueue(key)

    def _owned(self, job) -> list[Pod]:
        canon = canonical_selector(job.selector or None)
        out = []
        for pod in self.pods.items():
            if pod.metadata.namespace != job.metadata.namespace:
                continue
            ref = controller_ref(pod)
            if ref is not None and ref.get("uid") == job.metadata.uid:
                out.append(pod)
            elif ref is None and canon not in ((), PARSE_ERROR) \
                    and selector_matches(canon, pod.metadata.labels):
                out.append(pod)
        return out

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        job = self.jobs.get(name, ns)
        if job is None:
            self.expectations.forget(key)
            return
        if not self.expectations.satisfied(key):
            return
        if any(c.get("type") == "Failed" and c.get("status") == "True"
               for c in job.status.get("conditions", [])):
            return  # terminally failed: never respawn workers
        pods = self._owned(job)
        succeeded = sum(1 for p in pods if p.status.phase == "Succeeded")
        failed = sum(1 for p in pods if p.status.phase == "Failed")
        active = [p for p in pods if is_active(p)]
        complete = succeeded >= job.completions

        # activeDeadlineSeconds (syncJob :474 pastActiveDeadline): a job
        # over its wall-clock budget fails — kill workers, mark Failed
        deadline = job.spec.get("activeDeadlineSeconds")
        started = job.status.get("startTime")
        if not complete and deadline is not None and started is not None \
                and self.clock.now() - float(started) > float(deadline):
            for pod in active:
                try:
                    self.store.delete("Pod", pod.metadata.name, ns)
                except NotFound:
                    pass
            self._mark_failed(job, "DeadlineExceeded",
                              "Job was active longer than specified "
                              "deadline")
            return
        if not complete and deadline is not None and started is not None:
            # re-check when the deadline lapses even with no events
            remaining = float(started) + float(deadline) - self.clock.now()
            self.enqueue_after(key, max(0.05, remaining))

        if complete:
            # excess active workers are no longer needed (syncJob :520)
            for pod in active:
                try:
                    self.store.delete("Pod", pod.metadata.name, ns)
                except NotFound:
                    pass
        else:
            # keep `parallelism` workers, but never more than the work left
            want = min(job.parallelism,
                       job.completions - succeeded) - len(active)
            if want < 0:
                # parallelism reduced (or an over-create raced): delete the
                # excess, worst candidates first (manageJob, :593)
                from kubernetes_tpu.controllers.replicaset import (
                    deletion_order_key,
                )

                victims = sorted(active, key=deletion_order_key)[:-want]
                self.expectations.expect(key, dels=len(victims))
                for pod in victims:
                    try:
                        self.store.delete("Pod", pod.metadata.name, ns)
                    except NotFound:
                        self.expectations.deletion_observed(key)
            if want > 0:
                self.expectations.expect(key, adds=want)
                template = job.spec.get("template") or {}

                async def create_one() -> bool:
                    pod = pod_from_template(job, template)
                    if not pod.metadata.labels:
                        pod.metadata.labels = dict(
                            (job.selector or {}).get("matchLabels") or {})
                    # job pods must not restart forever (validation defaults
                    # them to OnFailure/Never)
                    if pod.spec.restart_policy == "Always":
                        pod.spec.restart_policy = "OnFailure"
                    try:
                        self.store.create(pod)
                        return True
                    except Exception:  # noqa: BLE001
                        self.expectations.creation_observed(key)
                        return False

                _ok, attempted = await slow_start_batch(want, create_one)
                for _ in range(want - attempted):
                    self.expectations.creation_observed(key)

        self._update_status(job, len(active), succeeded, failed, complete)

    def _mark_failed(self, job, reason: str, message: str) -> None:
        # mutate the STORE object via CAS: an informer-stale overwrite
        # would clobber succeeded/failed counts forever, since the Failed
        # guard stops all later status syncs
        try:
            current = self.store.get("Job", job.metadata.name,
                                     job.metadata.namespace)
        except NotFound:
            return
        if any(c.get("type") == "Failed"
               for c in current.status.get("conditions", [])):
            return

        def mutate(obj):
            obj.status.setdefault("conditions", []).append({
                "type": "Failed", "status": "True", "reason": reason,
                "message": message,
                "lastTransitionTime": self.clock.now()})
            obj.status["active"] = 0
            return obj

        try:
            self.store.guaranteed_update("Job", job.metadata.name,
                                         job.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass

    def _update_status(self, job, active: int, succeeded: int, failed: int,
                       complete: bool) -> None:
        fresh = self.jobs.get(job.metadata.name, job.metadata.namespace)
        if fresh is None:
            return
        status = dict(fresh.status)
        status.update({"active": active, "succeeded": succeeded,
                       "failed": failed})
        status.setdefault("startTime", self.clock.now())
        if complete and not any(
                c.get("type") == "Complete"
                for c in status.get("conditions", [])):
            status.setdefault("conditions", []).append({
                "type": "Complete", "status": "True",
                "lastTransitionTime": self.clock.now()})
            status["completionTime"] = self.clock.now()
            status["active"] = 0
        if status == fresh.status:
            return
        fresh = fresh.clone()
        fresh.status = status
        try:
            self.store.update(fresh)
        except Exception:  # noqa: BLE001 — status write is best-effort
            pass
