"""TTL controller: scale secret/configmap re-read pressure with cluster size.

Analog of pkg/controller/ttl/ttlcontroller.go: annotate every Node with
`node.alpha.kubernetes.io/ttl`, the seconds a kubelet may cache secrets/
configmaps before re-reading. Bigger clusters get longer TTLs so apiserver
read load stays flat (tiers at ttlcontroller.go:53-60); transitions are
hysteretic — the controller only steps one tier at a time per node write.
"""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

# (cluster size at/above which the tier applies, ttl seconds) — the
# reference's ttlBoundaries ladder
TIERS = ((0, 0), (100, 15), (500, 30), (1000, 60), (2000, 300))


def desired_ttl(num_nodes: int) -> int:
    ttl = 0
    for threshold, seconds in TIERS:
        if num_nodes >= threshold:
            ttl = seconds
    return ttl


class TTLController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, node_informer: Informer):
        super().__init__()
        self.name = "ttl-controller"
        self.store = store
        self.nodes = node_informer
        node_informer.add_handler(self._on_node)

    def _on_node(self, event) -> None:
        name = event.obj.metadata.name
        if event.type in ("ADDED", "DELETED"):
            # track membership in the handler itself (relist replays fire
            # handlers BEFORE the informer swaps its cache, so reading the
            # cache size here undercounts), and fan out to every node ONLY
            # when the count crossed a TTL tier boundary — an
            # unconditional fan-out made startup O(N^2) at 15k nodes
            known = getattr(self, "_known_nodes", None)
            if known is None:
                known = self._known_nodes = set()
            if event.type == "ADDED":
                known.add(name)
            else:
                known.discard(name)
            ttl = desired_ttl(len(known))
            if ttl != getattr(self, "_last_ttl", None):
                self._last_ttl = ttl
                for node_name in known:
                    self.enqueue(node_name)
            elif event.type == "ADDED":
                self.enqueue(name)
        else:
            self.enqueue(name)

    async def sync(self, key: str) -> None:
        node = self.nodes.get(key)
        if node is None:
            return
        count = len(getattr(self, "_known_nodes", ())) \
            or len(self.nodes.items())
        want = str(desired_ttl(count))
        if node.metadata.annotations.get(TTL_ANNOTATION) == want:
            return

        def mutate(obj):
            obj.metadata.annotations[TTL_ANNOTATION] = want
            return obj

        try:
            self.store.guaranteed_update("Node", key, "default", mutate)
        except (NotFound, Conflict):
            pass
