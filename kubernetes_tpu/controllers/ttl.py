"""TTL controller: scale secret/configmap re-read pressure with cluster size.

Analog of pkg/controller/ttl/ttlcontroller.go: annotate every Node with
`node.alpha.kubernetes.io/ttl`, the seconds a kubelet may cache secrets/
configmaps before re-reading. Bigger clusters get longer TTLs so apiserver
read load stays flat (tiers at ttlcontroller.go:53-60); transitions are
hysteretic — the controller only steps one tier at a time per node write.
"""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

# (cluster size at/above which the tier applies, ttl seconds) — the
# reference's ttlBoundaries ladder
TIERS = ((0, 0), (100, 15), (500, 30), (1000, 60), (2000, 300))


def desired_ttl(num_nodes: int) -> int:
    ttl = 0
    for threshold, seconds in TIERS:
        if num_nodes >= threshold:
            ttl = seconds
    return ttl


class TTLController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, node_informer: Informer):
        super().__init__()
        self.name = "ttl-controller"
        self.store = store
        self.nodes = node_informer
        node_informer.add_handler(self._on_node)

    def _on_node(self, event) -> None:
        if event.type == "ADDED" or event.type == "DELETED":
            # cluster size changed: every node may need a new tier
            for node in self.nodes.items():
                self.enqueue(node.metadata.name)
        elif event.type == "MODIFIED":
            self.enqueue(event.obj.metadata.name)

    async def sync(self, key: str) -> None:
        node = self.nodes.get(key)
        if node is None:
            return
        want = str(desired_ttl(len(self.nodes.items())))
        if node.metadata.annotations.get(TTL_ANNOTATION) == want:
            return

        def mutate(obj):
            obj.metadata.annotations[TTL_ANNOTATION] = want
            return obj

        try:
            self.store.guaranteed_update("Node", key, "default", mutate)
        except (NotFound, Conflict):
            pass
