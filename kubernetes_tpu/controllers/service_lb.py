"""Service (cloud load balancer) controller.

The pkg/controller/service analog: Services of type LoadBalancer get a
cloud balancer ensured across the cluster's nodes, their ingress IP written
to status.loadBalancer; deletion (or type change) tears the balancer down
(servicecontroller.go syncService/createLoadBalancerIfNeeded)."""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.cloudprovider import CloudProvider
from kubernetes_tpu.controllers.base import ReconcileController


class ServiceLBController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, cloud: CloudProvider,
                 service_informer: Informer, node_informer: Informer):
        super().__init__()
        self.name = "service-lb-controller"
        self.store = store
        self.cloud = cloud
        self.services = service_informer
        self.nodes = node_informer
        self._known_nodes: frozenset = frozenset()
        service_informer.add_handler(self._on_service)
        node_informer.add_handler(self._on_node)

    def _on_service(self, event) -> None:
        self.enqueue(event.obj.key)

    def _on_node(self, event) -> None:
        # only node-set MEMBERSHIP changes re-ensure balancers — heartbeat
        # MODIFIED events (constant at scale) cannot change the set, so
        # they don't even pay a membership recompute (nodeSyncLoop compares
        # host lists, servicecontroller.go:600)
        if event.type == "MODIFIED":
            return
        names = frozenset(n.metadata.name for n in self.nodes.items())
        if names == self._known_nodes:
            return
        self._known_nodes = names
        for svc in self.services.items():
            if (svc.spec.get("type") == "LoadBalancer"):
                self.enqueue(svc.key)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        svc = self.services.get(name, ns)
        if svc is None or svc.spec.get("type") != "LoadBalancer":
            # deleted or no longer wants a balancer: tear down
            probe = svc if svc is not None else _DeletedService(key)
            if self.cloud.get_load_balancer(probe) is not None:
                self.cloud.ensure_load_balancer_deleted(probe)
                if svc is not None:
                    self._clear_status(svc)
            return
        node_names = [n.metadata.name for n in self.nodes.items()]
        status = self.cloud.ensure_load_balancer(svc, node_names)
        want = {"ingress": [{"ip": status.ingress_ip}]}
        if svc.status.get("loadBalancer") == want:
            return  # no-op: a status write would re-trigger our own sync

        def mutate(obj):
            obj.status["loadBalancer"] = dict(want)
            return obj

        try:
            self.store.guaranteed_update("Service", name, ns, mutate)
        except (NotFound, Conflict):
            pass

    def _clear_status(self, svc) -> None:
        def mutate(obj):
            obj.status.pop("loadBalancer", None)
            return obj

        try:
            self.store.guaranteed_update(
                "Service", svc.metadata.name, svc.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass


class _DeletedService:
    """Key-only stand-in so teardown can address the cloud's records."""

    def __init__(self, key: str):
        self.key = key
