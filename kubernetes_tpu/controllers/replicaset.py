"""ReplicaSet / ReplicationController reconcile loops.

Mirrors pkg/controller/replicaset/replica_set.go (queue wiring :112, event
handlers :147-266, worker :405, syncReplicaSet :543, manageReplicas :459)
and pkg/controller/replication (same logic over v1.RC with a map selector).
One generic manager covers both kinds — the reference keeps two copies only
because Go lacks the generic.

Semantics kept:
- expectations gate the sync (no double-creates while writes are in the
  watch pipe), slow-start create bursts, burstReplicas clamp (:66, 500);
- pod adoption/release by selector + controllerRef (ClaimPods,
  controller_utils.go:1000: adopt selector-matching orphans, release owned
  pods that stopped matching);
- deletion victims ranked by ActivePods order (controller_utils.go:695):
  unassigned first, then Pending < Unknown < Running, then not-ready,
  then youngest.
"""

from __future__ import annotations

import uuid
from typing import Any

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController, slow_start_batch
from kubernetes_tpu.state.podaffinity import (
    PARSE_ERROR,
    canonical_selector,
    map_selector,
    selector_matches,
)

BURST_REPLICAS = 500  # replica_set.go:66
_PHASE_RANK = {"Pending": 0, "Unknown": 1, "Running": 2}


def workload_selector_canon(obj) -> Any:
    """Canonical selector for a workload object: RC uses a map selector,
    RS/StatefulSet/Deployment a LabelSelector."""
    if obj.kind == "ReplicationController":
        return map_selector(obj.selector or {})
    return canonical_selector(obj.selector or None)


def controller_ref(pod: Pod) -> dict | None:
    """The pod's controller ownerRef (metav1.GetControllerOf)."""
    for ref in pod.metadata.owner_references:
        if ref.get("controller"):
            return ref
    return None


def make_controller_ref(obj) -> dict:
    return {"apiVersion": obj.api_version, "kind": obj.kind,
            "name": obj.metadata.name, "uid": obj.metadata.uid,
            "controller": True, "blockOwnerDeletion": True}


def is_active(pod: Pod) -> bool:
    """controller.FilterActivePods (controller_utils.go:700): terminal or
    terminating pods don't count toward replicas."""
    return (pod.status.phase not in ("Succeeded", "Failed")
            and pod.metadata.deletion_timestamp is None)


def pod_ready(pod: Pod) -> bool:
    return any(c.get("type") == "Ready" and c.get("status") == "True"
               for c in pod.status.conditions)


def deletion_order_key(pod: Pod):
    """ActivePods Less (controller_utils.go:695) — pods sorting FIRST are
    deleted first."""
    return (
        0 if not pod.spec.node_name else 1,
        _PHASE_RANK.get(pod.status.phase, 1),
        1 if pod_ready(pod) else 0,
        -pod.metadata.creation_timestamp,  # youngest first
    )


def pod_from_template(owner, template: dict) -> Pod:
    """GetPodFromTemplate (controller_utils.go:500): template + generated
    name + controller ownerRef."""
    import copy

    d = copy.deepcopy(template or {})
    meta = d.setdefault("metadata", {})
    # 10 hex chars: a 500-pod burst had ~10% AlreadyExists odds at 5 chars
    # (ADVICE r2 #3); the reference survives collisions via apiserver retry
    meta["name"] = f"{owner.metadata.name}-{uuid.uuid4().hex[:10]}"
    meta["namespace"] = owner.metadata.namespace
    meta.pop("uid", None)
    meta.setdefault("labels", {})
    refs = [r for r in meta.get("ownerReferences", [])
            if not r.get("controller")]
    refs.append(make_controller_ref(owner))
    meta["ownerReferences"] = refs
    return Pod.from_dict(d)


class ReplicaManager(ReconcileController):
    """Shared RS/RC reconcile loop; `kind` picks the workload bucket."""

    workers = 4

    def __init__(self, store: ObjectStore, kind: str,
                 workload_informer: Informer, pod_informer: Informer):
        super().__init__()
        self.name = f"{kind.lower()}-controller"
        self.store = store
        self.kind = kind
        self.workloads = workload_informer
        self.pods = pod_informer
        # namespace -> workload keys, so the orphan-adoption scan per pod
        # event touches same-namespace workloads only (VERDICT r2 weak #7)
        self._by_ns: dict[str, set[str]] = {}
        workload_informer.add_handler(self._on_workload)
        pod_informer.add_handler(self._on_pod)

    # ---- informer handlers (replica_set.go:147-266) ----

    def _on_workload(self, event) -> None:
        obj = event.obj
        if obj.kind != self.kind:
            return
        ns = obj.metadata.namespace
        if event.type == "DELETED":
            self.expectations.forget(obj.key)
            keys = self._by_ns.get(ns)
            if keys is not None:
                keys.discard(obj.key)
                if not keys:
                    del self._by_ns[ns]
        else:
            self._by_ns.setdefault(ns, set()).add(obj.key)
        self.enqueue(obj.key)

    def _key_for(self, pod: Pod) -> str | None:
        ref = controller_ref(pod)
        if ref is not None:
            if ref.get("kind") != self.kind:
                return None
            return f"{pod.metadata.namespace}/{ref.get('name')}"
        # orphan: every selector-matching same-namespace workload may adopt
        ns = pod.metadata.namespace
        for key in self._by_ns.get(ns, ()):
            w = self.workloads.get(key.split("/", 1)[1], ns)
            if w is None:
                continue
            canon = workload_selector_canon(w)
            if canon not in ((), PARSE_ERROR) \
                    and selector_matches(canon, pod.metadata.labels):
                return w.key
        return None

    def _on_pod(self, event) -> None:
        pod: Pod = event.obj
        key = self._key_for(pod)
        if key is None:
            return
        if event.type == "ADDED":
            self.expectations.creation_observed(key)
        elif event.type == "DELETED":
            self.expectations.deletion_observed(key)
        self.enqueue(key)

    # ---- reconcile (syncReplicaSet, replica_set.go:543) ----

    def _claim_pods(self, rs) -> list[Pod]:
        """ClaimPods (controller_utils.go:1000): owned+matching stay; owned
        non-matching are released; matching orphans are adopted."""
        canon = workload_selector_canon(rs)
        if canon in ((), PARSE_ERROR):
            return []  # invalid/empty selector matches nothing for claims
        ns = rs.metadata.namespace
        claimed = []
        for pod in self.pods.items():
            if pod.metadata.namespace != ns or not is_active(pod):
                continue
            ref = controller_ref(pod)
            owned = (ref is not None and ref.get("uid") == rs.metadata.uid)
            matches = selector_matches(canon, pod.metadata.labels)
            if owned and matches:
                claimed.append(pod)
            elif owned and not matches:
                self._release(pod)
            elif matches and ref is None:
                adopted = self._adopt(rs, pod)
                if adopted is not None:
                    claimed.append(adopted)
        return claimed

    def _adopt(self, rs, pod: Pod) -> Pod | None:
        fresh = pod.clone()
        fresh.metadata.owner_references.append(make_controller_ref(rs))
        try:
            return self.store.update(fresh)
        except (Conflict, NotFound):
            return None  # raced; next sync retries

    def _release(self, pod: Pod) -> None:
        fresh = pod.clone()
        fresh.metadata.owner_references = [
            r for r in fresh.metadata.owner_references
            if not r.get("controller")]
        try:
            self.store.update(fresh)
        except (Conflict, NotFound):
            pass

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        rs = self.workloads.get(name, ns)
        if rs is None:
            self.expectations.forget(key)
            return
        if not self.expectations.satisfied(key):
            return  # our own writes are still in the watch pipe
        pods = self._claim_pods(rs)
        await self._manage(rs, key, pods)
        self._update_status(rs, pods)

    async def _manage(self, rs, key: str, pods: list[Pod]) -> None:
        """manageReplicas (replica_set.go:459)."""
        diff = len(pods) - rs.replicas
        if diff < 0:
            want = min(-diff, BURST_REPLICAS)
            self.expectations.expect(key, adds=want)
            template = rs.spec.get("template") or {}

            async def create_one() -> bool:
                pod = pod_from_template(rs, template)
                if self.kind != "ReplicationController" and not \
                        pod.metadata.labels:
                    pod.metadata.labels = dict(
                        (rs.spec.get("selector") or {}).get("matchLabels")
                        or {})
                try:
                    self.store.create(pod)
                    return True
                except Exception:  # noqa: BLE001
                    self.expectations.creation_observed(key)  # lower burden
                    return False

            _ok, attempted = await slow_start_batch(want, create_one)
            # expectations for never-attempted creates must be released or
            # the RS is ignored until the 5-minute TTL (skippedPods,
            # replica_set.go:478; ADVICE r2 #1)
            for _ in range(want - attempted):
                self.expectations.creation_observed(key)
        elif diff > 0:
            want = min(diff, BURST_REPLICAS)
            victims = sorted(pods, key=deletion_order_key)[:want]
            self.expectations.expect(key, dels=want)
            for pod in victims:
                try:
                    self.store.delete("Pod", pod.metadata.name,
                                      pod.metadata.namespace)
                except NotFound:
                    self.expectations.deletion_observed(key)

    def _update_status(self, rs, pods: list[Pod]) -> None:
        """calculateStatus subset (replica_set_utils.go): observed replica
        counts on the workload object."""
        fresh = self.workloads.get(rs.metadata.name, rs.metadata.namespace)
        if fresh is None:
            return
        status = {
            "replicas": len(pods),
            "readyReplicas": sum(1 for p in pods if pod_ready(p)),
            "availableReplicas": sum(1 for p in pods if pod_ready(p)),
            "fullyLabeledReplicas": len(pods),
        }
        if fresh.status == status:
            return
        fresh = fresh.clone()
        fresh.status = status
        try:
            self.store.update(fresh)
        except (Conflict, NotFound):
            pass
