"""Controller manager: shared informers + the controller set.

The kube-controller-manager analog (cmd/kube-controller-manager/app/
controllermanager.go:315-339 registers the loops against one shared
informer factory). start() syncs informers once, then every controller's
workers run against the shared caches."""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.endpoints import EndpointController
from kubernetes_tpu.controllers.gc import GarbageCollector
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.replicaset import ReplicaManager
from kubernetes_tpu.controllers.statefulset import StatefulSetController


class ControllerManager:
    def __init__(self, store: ObjectStore, enable_gc: bool = True,
                 enable_node_lifecycle: bool = True,
                 node_lifecycle_kwargs: dict | None = None,
                 node_ipam_kwargs: dict | None = None,
                 cloud=None, hpa_metrics=None,
                 podgc_threshold: int | None = None,
                 enable_autoscaler: bool = True,
                 autoscaler_kwargs: dict | None = None,
                 enable_monitor: bool = False,
                 monitor_kwargs: dict | None = None,
                 enable_descheduler: bool = False,
                 descheduler_kwargs: dict | None = None):
        self.store = store
        # embedded monitoring plane (obs/monitor.py): scrapes the store's
        # kubelet endpoints + the process registry, and becomes the HPA's
        # resource-metrics source unless the caller injected one
        self.monitor = None
        if enable_monitor:
            from kubernetes_tpu.obs.monitor import Monitor

            self.monitor = Monitor(store, **(monitor_kwargs or {}))
        self.informers: dict[str, Informer] = {
            kind: Informer(store, kind)
            for kind in ("Pod", "Node", "Service", "ReplicaSet",
                         "ReplicationController", "StatefulSet",
                         "Deployment", "Job", "Namespace",
                         "ServiceAccount", "ResourceQuota", "CronJob",
                         "HorizontalPodAutoscaler", "PodDisruptionBudget",
                         "DaemonSet", "PersistentVolume",
                         "PersistentVolumeClaim",
                         "CertificateSigningRequest")}
        pods = self.informers["Pod"]
        self.replicaset = ReplicaManager(
            store, "ReplicaSet", self.informers["ReplicaSet"], pods)
        self.replication = ReplicaManager(
            store, "ReplicationController",
            self.informers["ReplicationController"], pods)
        self.deployment = DeploymentController(
            store, self.informers["Deployment"], self.informers["ReplicaSet"])
        self.statefulset = StatefulSetController(
            store, self.informers["StatefulSet"], pods)
        self.job = JobController(store, self.informers["Job"], pods)
        self.endpoints = EndpointController(
            store, self.informers["Service"], pods,
            node_informer=self.informers["Node"])
        from kubernetes_tpu.controllers.namespace import NamespaceController
        from kubernetes_tpu.controllers.podgc import PodGCController

        self.namespace = NamespaceController(store,
                                             self.informers["Namespace"])
        self.podgc = PodGCController(
            store, pods, **({} if podgc_threshold is None
                            else {"threshold": podgc_threshold}))
        from kubernetes_tpu.controllers.cronjob import CronJobController
        from kubernetes_tpu.controllers.daemonset import DaemonSetController
        from kubernetes_tpu.controllers.disruption import DisruptionController
        from kubernetes_tpu.controllers.hpa import (
            HorizontalController,
            MonitorMetrics,
            StaticMetrics,
        )
        from kubernetes_tpu.controllers.quota import ResourceQuotaController
        from kubernetes_tpu.controllers.serviceaccount import (
            ServiceAccountController,
        )
        from kubernetes_tpu.controllers.ttl import TTLController

        self.serviceaccount = ServiceAccountController(
            store, self.informers["Namespace"],
            self.informers["ServiceAccount"])
        self.resourcequota = ResourceQuotaController(
            store, self.informers["ResourceQuota"], pods)
        self.ttl = TTLController(store, self.informers["Node"])
        self.disruption = DisruptionController(
            store, self.informers["PodDisruptionBudget"], pods)
        if hpa_metrics is None:
            # with an embedded monitor the HPA reads live usage from its
            # TSDB (annotation fallback inside); without one the hollow
            # StaticMetrics stand-in stays, as before
            hpa_metrics = MonitorMetrics(self.monitor) \
                if self.monitor is not None else StaticMetrics()
        self.hpa = HorizontalController(
            store, self.informers["HorizontalPodAutoscaler"], pods,
            hpa_metrics)
        self.cronjob = CronJobController(
            store, self.informers["CronJob"], self.informers["Job"])
        self.daemonset = DaemonSetController(
            store, self.informers["DaemonSet"], pods,
            self.informers["Node"])
        from kubernetes_tpu.controllers.volume import (
            AttachDetachController,
            PersistentVolumeBinder,
        )

        self.pv_binder = PersistentVolumeBinder(
            store, self.informers["PersistentVolumeClaim"],
            self.informers["PersistentVolume"])
        self.attach_detach = AttachDetachController(
            store, self.informers["Node"], pods,
            self.informers["PersistentVolumeClaim"])
        self.controllers = [self.replicaset, self.replication,
                            self.deployment, self.statefulset, self.job,
                            self.endpoints, self.namespace, self.podgc,
                            self.serviceaccount, self.resourcequota,
                            self.ttl, self.disruption, self.hpa,
                            self.cronjob, self.daemonset, self.pv_binder,
                            self.attach_detach]
        if enable_gc:
            self.gc = GarbageCollector(
                store,
                {"Pod": pods, "Job": self.informers["Job"]},
                {k: v for k, v in self.informers.items()
                 if k not in ("Pod", "Node", "Service")})
            self.controllers.append(self.gc)
        if enable_node_lifecycle:
            self.node_lifecycle = NodeLifecycleController(
                store, self.informers["Node"], pods,
                **{"cloud": cloud, **(node_lifecycle_kwargs or {})})
            self.controllers.append(self.node_lifecycle)
            from kubernetes_tpu.controllers.taintmanager import (
                NoExecuteTaintManager,
            )

            self.taint_manager = NoExecuteTaintManager(
                store, self.informers["Node"], pods)
            self.controllers.append(self.taint_manager)
        from kubernetes_tpu.controllers.nodeipam import (
            NodeIpamController,
            RouteController,
        )

        self.node_ipam = NodeIpamController(store, self.informers["Node"],
                                            **(node_ipam_kwargs or {}))
        self.controllers.append(self.node_ipam)
        from kubernetes_tpu.controllers.certificates import CSRController

        self.csr = CSRController(
            store, self.informers["CertificateSigningRequest"])
        self.controllers.append(self.csr)
        from kubernetes_tpu.gang.controller import GangController

        # gang/PodGroup reconciliation (materializes groups from annotated
        # parallel workloads; carries its own informers — it watches
        # PodGroup, which the shared factory set predates)
        self.gang = GangController(store)
        self.controllers.append(self.gang)
        if cloud is not None:
            from kubernetes_tpu.controllers.service_lb import (
                ServiceLBController,
            )

            self.service_lb = ServiceLBController(
                store, cloud, self.informers["Service"],
                self.informers["Node"])
            self.controllers.append(self.service_lb)
            self.route = RouteController(store, cloud,
                                         self.informers["Node"])
            self.controllers.append(self.route)
            # cluster autoscaler: only when the provider actually exposes
            # node groups — a group-less cloud (every pre-existing test)
            # pays nothing, not even a JAX import
            if enable_autoscaler and cloud.node_groups():
                from kubernetes_tpu.autoscaler import ClusterAutoscaler

                self.autoscaler = ClusterAutoscaler(
                    store, cloud, node_informer=self.informers["Node"],
                    pod_informer=pods, **(autoscaler_kwargs or {}))
                self.controllers.append(self.autoscaler)
        # gang-defragmentation descheduler: opt-in (it costs a JAX import
        # and a private simulator twin), sharing the factory's informers
        if enable_descheduler:
            from kubernetes_tpu.descheduler import Descheduler

            self.descheduler = Descheduler(
                store, node_informer=self.informers["Node"],
                pod_informer=pods, **(descheduler_kwargs or {}))
            self.controllers.append(self.descheduler)

    @property
    def synced(self) -> bool:
        """All shared informers have completed their initial list — the
        controller-manager's /readyz signal."""
        return all(inf._synced.is_set() for inf in self.informers.values())

    async def start(self) -> None:
        for informer in self.informers.values():
            informer.start()
        for informer in self.informers.values():
            await informer.wait_for_sync()
        for controller in self.controllers:
            await controller.start()
        if self.monitor is not None:
            await self.monitor.start()
        # reconcile pre-existing objects that predate the watch
        for obj in self.informers["ReplicaSet"].items():
            self.replicaset.enqueue(obj.key)
        for obj in self.informers["ReplicationController"].items():
            self.replication.enqueue(obj.key)
        for obj in self.informers["Deployment"].items():
            self.deployment.enqueue(obj.key)
        for obj in self.informers["StatefulSet"].items():
            self.statefulset.enqueue(obj.key)
        for obj in self.informers["Job"].items():
            self.job.enqueue(obj.key)
        for obj in self.informers["Service"].items():
            self.endpoints.enqueue(obj.key)
        for obj in self.informers["Namespace"].items():
            self.serviceaccount.enqueue(obj.metadata.name)
        for obj in self.informers["PodDisruptionBudget"].items():
            self.disruption.enqueue(obj.key)
        for obj in self.informers["DaemonSet"].items():
            self.daemonset.enqueue(obj.key)
        for obj in self.informers["Node"].items():
            self.ttl.enqueue(obj.metadata.name)
            self.attach_detach.enqueue(obj.metadata.name)
        for obj in self.informers["PersistentVolumeClaim"].items():
            self.pv_binder.enqueue(obj.key)

    def stop(self) -> None:
        if self.monitor is not None:
            self.monitor.stop()
        for controller in self.controllers:
            controller.stop()
        for informer in self.informers.values():
            informer.stop()
