"""Horizontal pod autoscaler.

Analog of pkg/controller/podautoscaler/horizontal.go: every sync period,
for each HPA read the scale target's current replicas, get per-pod CPU
utilization from a metrics source (the reference queries heapster through
metrics_client.go; here the source is injectable — tests provide one, the
hollow agent reports fake usage), and set

    desired = ceil(current * avgUtilization / targetUtilization)

clamped to [minReplicas, maxReplicas], skipping changes inside the 10%
tolerance band (horizontal.go:251 tolerance = 0.1). Scaling writes
spec.replicas through the workload kinds' scale shape (the reference's
/scale subresource).

Downscale stabilization (the reference's
--horizontal-pod-autoscaler-downscale-stabilization, replicacalculator's
stabilizeRecommendation): each sync records the raw desired-replica
recommendation; a scale DOWN only goes to the maximum recommendation seen
inside the stabilization window, so a transient dip in load can't flap the
workload — it shrinks only after the recommendation has stayed low for the
whole window. Scale-ups apply immediately.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from typing import Callable, Protocol

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.replicaset import workload_selector_canon
from kubernetes_tpu.state.podaffinity import PARSE_ERROR, selector_matches

log = logging.getLogger(__name__)

TOLERANCE = 0.1  # horizontal.go tolerance
DOWNSCALE_STABILIZATION = 300.0  # downscaleStabilisationWindow (5m)
SCALABLE_KINDS = ("ReplicationController", "ReplicaSet", "Deployment",
                  "StatefulSet")


class MetricsSource(Protocol):
    def utilization(self, namespace: str, pods: list) -> dict[str, float]:
        """pod name → CPU utilization fraction of request (1.0 = 100%).
        `pods` are the informer-cached Pod objects — a source must not do
        per-pod I/O on the event loop (an HPA over hundreds of pods syncs
        every 30s)."""


class AnnotationMetrics:
    """Cluster-fed metrics source: pods carry their CPU utilization (as a
    fraction of request) in the `kubernetes-tpu/cpu-usage` annotation —
    the hollow/fake kubelet's stand-in for the heapster pipeline the
    reference queries (metrics_client.go). Reads straight off the
    informer-cached pod objects: zero I/O per sync. Pods without the
    annotation report nothing, so the controller skips rather than
    guesses."""

    ANNOTATION = "kubernetes-tpu/cpu-usage"

    def __init__(self, store=None):
        # `store` accepted for constructor symmetry; unused (the informer
        # pods carry the annotation)
        self.store = store

    def utilization(self, namespace: str, pods: list) -> dict[str, float]:
        out: dict[str, float] = {}
        for pod in pods:
            raw = pod.metadata.annotations.get(self.ANNOTATION)
            if raw is None:
                continue
            try:
                out[pod.metadata.name] = float(raw)
            except ValueError:
                continue
        return out


class MonitorMetrics:
    """The real resource-metrics pipeline: query an in-process Monitor's
    TSDB for `pod_cpu_usage_ratio` — the series its scraper ingests from
    kubelet /stats/summary — and fall back to the annotation stand-in
    when no Monitor runs (or it has not scraped usage yet). The TSDB read
    is an in-memory instant lookup: zero I/O per sync, per the
    MetricsSource contract. Pods whose kubelet reports no live cpu sample
    are absent from the result, so the controller's skip-on-incomplete-
    coverage guard keeps holding."""

    def __init__(self, monitor=None, fallback: MetricsSource | None = None):
        self.monitor = monitor
        self.fallback = fallback if fallback is not None \
            else AnnotationMetrics()

    def utilization(self, namespace: str, pods: list) -> dict[str, float]:
        if self.monitor is not None:
            try:
                vec = self.monitor.query(
                    f'pod_cpu_usage_ratio{{namespace="{namespace}"}}')
            except Exception:  # noqa: BLE001 — no data -> fallback
                vec = []
            names = {p.metadata.name for p in pods}
            usage = {lbl["pod"]: v for lbl, v in vec
                     if lbl.get("pod") in names}
            if usage:
                return usage
        return self.fallback.utilization(namespace, pods) \
            if self.fallback is not None else {}


class StaticMetrics:
    """Test/hollow metrics source: explicit per-pod utilization, with an
    optional default for unknown pods. default=None reports nothing for
    unknown pods — the controller then skips reconciliation rather than
    scaling on absent data (the reference aborts the sync when the metrics
    query fails, horizontal.go:293)."""

    def __init__(self, default: float | None = None):
        self.default = default
        self.per_pod: dict[str, float] = {}

    def set(self, pod_name: str, utilization: float) -> None:
        self.per_pod[pod_name] = utilization

    def utilization(self, namespace: str, pods: list) -> dict[str, float]:
        names = [p.metadata.name for p in pods]
        if self.default is None:
            return {n: self.per_pod[n] for n in names
                    if n in self.per_pod}
        return {n: self.per_pod.get(n, self.default) for n in names}


class HorizontalController:
    name = "horizontalpodautoscaler-controller"

    def __init__(self, store: ObjectStore, hpa_informer: Informer,
                 pod_informer: Informer, metrics: MetricsSource,
                 sync_period: float = 30.0,
                 stabilization_window_s: float = DOWNSCALE_STABILIZATION,
                 now: Callable[[], float] = time.time):
        self.store = store
        self.hpas = hpa_informer
        self.pods = pod_informer
        self.metrics = metrics
        self.sync_period = sync_period
        self.stabilization_window_s = stabilization_window_s
        self.now = now
        # hpa key -> [(timestamp, raw desired)] recommendations inside the
        # stabilization window (horizontal.go recommendations map)
        self._recommendations: dict[str, list[tuple[float, int]]] = {}
        hpa_informer.add_handler(self._on_hpa)
        self._task: asyncio.Task | None = None

    def _on_hpa(self, event) -> None:
        if event.type == "DELETED":
            self._recommendations.pop(event.obj.key, None)

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.sync_period)
            try:
                self.sync_all()
            except Exception:  # noqa: BLE001 — the loop must not die
                log.exception("hpa sync failed")

    def sync_all(self) -> None:
        for hpa in self.hpas.items():
            try:
                self.reconcile(hpa)
            except Exception:  # noqa: BLE001 — per-HPA isolation
                log.exception("hpa %s reconcile failed", hpa.key)

    def _target(self, hpa):
        ref = hpa.target_ref
        kind = ref.get("kind", "")
        if kind not in SCALABLE_KINDS:
            return None
        try:
            return self.store.get(kind, ref.get("name", ""),
                                  hpa.metadata.namespace)
        except NotFound:
            return None

    def _target_pods(self, hpa, target) -> list:
        canon = workload_selector_canon(target)
        if canon in ((), PARSE_ERROR):
            return []
        return [p for p in self.pods.items()
                if p.metadata.namespace == hpa.metadata.namespace
                and p.status.phase == "Running"
                and selector_matches(canon, p.metadata.labels)]

    def reconcile(self, hpa) -> None:
        target = self._target(hpa)
        if target is None:
            return
        current = target.replicas
        if current == 0:
            # reference: autoscaling is disabled at 0 (horizontal.go:273) —
            # an operator-zeroed workload must NOT be scaled back up, so the
            # min/max clamp never applies here
            self._write_status(hpa, current, current, None)
            return
        pods = self._target_pods(hpa, target)
        if not pods:
            # rollout in flight (pods Pending) — no data, no action; the
            # reference aborts the sync when metrics are unavailable
            return
        usage = self.metrics.utilization(hpa.metadata.namespace, pods)
        if len(usage) < len(pods):
            # partial coverage must not drive fleet-wide scaling (one hot
            # sample would double the workload); the reference aborts the
            # sync when metrics are incomplete
            return
        desired = current
        avg = sum(usage.values()) / len(usage)
        avg_pct = 100.0 * avg
        ratio = avg_pct / hpa.target_utilization
        if abs(ratio - 1.0) > TOLERANCE:
            desired = math.ceil(current * ratio)
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
        desired = self._stabilize(hpa.key, current, desired)
        if desired != current:
            def scale(obj):
                obj.spec["replicas"] = desired
                return obj

            try:
                self.store.guaranteed_update(
                    target.kind, target.metadata.name,
                    hpa.metadata.namespace, scale)
            except (NotFound, Conflict):
                return
        self._write_status(hpa, current, desired, avg_pct)

    def _stabilize(self, key: str, current: int, desired: int) -> int:
        """Record this sync's recommendation and clamp a downscale to the
        window's maximum (stabilizeRecommendation): the workload only
        shrinks to a size every recommendation in the window agreed on."""
        now = self.now()
        window = [(t, d) for t, d in self._recommendations.get(key, [])
                  if now - t < self.stabilization_window_s]
        window.append((now, desired))
        self._recommendations[key] = window
        if desired < current:
            desired = min(current, max(d for _t, d in window))
        return desired

    def _write_status(self, hpa, current: int, desired: int,
                      avg_pct: float | None) -> None:
        status = {"currentReplicas": current, "desiredReplicas": desired}
        if avg_pct is not None:
            status["currentCPUUtilizationPercentage"] = int(round(avg_pct))
        if desired != current:
            status["lastScaleTime"] = self.now()
        elif "lastScaleTime" in hpa.status:
            status["lastScaleTime"] = hpa.status["lastScaleTime"]
        if {k: v for k, v in hpa.status.items()} == status:
            return

        def mutate(obj):
            obj.status = status
            return obj

        try:
            self.store.guaranteed_update(
                "HorizontalPodAutoscaler", hpa.metadata.name,
                hpa.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass
