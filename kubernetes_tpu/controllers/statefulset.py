"""StatefulSet controller: ordered, stably-named pods.

The pkg/controller/statefulset/stateful_set.go analog (sync loop
:syncStatefulSet -> stateful_set_control.go UpdateStatefulSet): replicas
get ordinal identities `<name>-0 .. <name>-(N-1)`; scale-up creates the
lowest missing ordinal only after every lower ordinal is Running and Ready
(OrderedReady semantics, stateful_set_control.go:428); scale-down deletes
the highest ordinal first, one at a time, and only when every remaining pod
is healthy (:464). Identity is stable: a deleted ordinal is recreated with
the same name.
"""

from __future__ import annotations

import copy
import re

from kubernetes_tpu.api.objects import PersistentVolumeClaim, Pod
from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import (
    controller_ref,
    is_active,
    make_controller_ref,
    pod_ready,
)


def ordinal_of(set_name: str, pod_name: str) -> int | None:
    """getOrdinal (stateful_set_utils.go:53): <setname>-<ordinal>."""
    m = re.fullmatch(re.escape(set_name) + r"-(\d+)", pod_name)
    return int(m.group(1)) if m else None


class StatefulSetController(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, set_informer: Informer,
                 pod_informer: Informer):
        super().__init__()
        self.name = "statefulset-controller"
        self.store = store
        self.sets = set_informer
        self.pods = pod_informer
        set_informer.add_handler(self._on_set)
        pod_informer.add_handler(self._on_pod)

    def _on_set(self, event) -> None:
        if event.obj.kind == "StatefulSet":
            self.enqueue(event.obj.key)

    def _on_pod(self, event) -> None:
        ref = controller_ref(event.obj)
        if ref is not None and ref.get("kind") == "StatefulSet":
            self.enqueue(f"{event.obj.metadata.namespace}/{ref.get('name')}")

    def _owned_by_ordinal(self, sts) -> dict[int, Pod]:
        owned: dict[int, Pod] = {}
        for pod in self.pods.items():
            if pod.metadata.namespace != sts.metadata.namespace \
                    or not is_active(pod):
                continue
            ref = controller_ref(pod)
            if ref is None or ref.get("uid") != sts.metadata.uid:
                continue
            ordinal = ordinal_of(sts.metadata.name, pod.metadata.name)
            if ordinal is not None:
                owned[ordinal] = pod
        return owned

    def _ensure_claims(self, sts, ordinal: int) -> None:
        """volumeClaimTemplates → one PVC per (template, ordinal), named
        `<tpl>-<set>-<ordinal>` (stateful_set_utils.go:118
        getPersistentVolumeClaimName). Claims are created with the pod and
        deliberately RETAINED on scale-down — the ordinal's storage
        identity survives (createPersistentVolumeClaims semantics).
        Claim labels come from the set's selector matchLabels
        (getPersistentVolumeClaims sets claim.Labels from
        set.Spec.Selector.MatchLabels)."""
        set_labels = dict((sts.spec.get("selector") or {})
                          .get("matchLabels") or {})
        for tpl_name, vct in self._claim_templates(sts).items():
            claim_name = f"{tpl_name}-{sts.metadata.name}-{ordinal}"
            try:
                self.store.get("PersistentVolumeClaim", claim_name,
                               sts.metadata.namespace)
                continue
            except NotFound:
                pass
            pvc = PersistentVolumeClaim.from_dict({
                "metadata": {"name": claim_name,
                             "namespace": sts.metadata.namespace,
                             "labels": set_labels},
                "spec": copy.deepcopy(vct.get("spec") or {})})
            try:
                self.store.create(pvc)
            except AlreadyExists:
                pass

    @staticmethod
    def _claim_templates(sts) -> dict:
        """name → template, deduplicated (a duplicate/defaulted name must
        not yield duplicate pod volumes over one PVC)."""
        out: dict = {}
        for vct in sts.spec.get("volumeClaimTemplates") or []:
            out.setdefault((vct.get("metadata") or {}).get("name", "data"),
                           vct)
        return out

    def _make_pod(self, sts, ordinal: int) -> Pod:
        d = copy.deepcopy(sts.spec.get("template") or {})
        meta = d.setdefault("metadata", {})
        meta["name"] = f"{sts.metadata.name}-{ordinal}"
        meta["namespace"] = sts.metadata.namespace
        meta.pop("uid", None)
        labels = meta.setdefault("labels", {})
        if not labels:
            labels.update((sts.spec.get("selector") or {})
                          .get("matchLabels") or {})
        # the stable-identity labels (stateful_set_utils.go:95)
        labels["statefulset.kubernetes.io/pod-name"] = meta["name"]
        meta["ownerReferences"] = [make_controller_ref(sts)]
        # wire the ordinal's claims in as volumes (updateStorage,
        # stateful_set_utils.go:135): the claim REPLACES any same-named
        # template volume — persistent identity wins over an ephemeral
        # stand-in the template happened to declare
        spec = d.setdefault("spec", {})
        claim_names = set(self._claim_templates(sts))
        volumes = [v for v in spec.get("volumes") or []
                   if v.get("name") not in claim_names]
        for tpl_name in claim_names:
            volumes.append({
                "name": tpl_name,
                "persistentVolumeClaim": {
                    "claimName":
                        f"{tpl_name}-{sts.metadata.name}-{ordinal}"}})
        spec["volumes"] = volumes
        return Pod.from_dict(d)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        sts = self.sets.get(name, ns)
        if sts is None:
            return
        owned = self._owned_by_ordinal(sts)
        want = sts.replicas

        # scale up: create the LOWEST missing ordinal < want, but only once
        # every lower ordinal is Running and Ready (OrderedReady)
        for ordinal in range(want):
            pod = owned.get(ordinal)
            if pod is None:
                if all(pod_ready(owned[i]) for i in range(ordinal)
                       if i in owned):
                    self._ensure_claims(sts, ordinal)
                    try:
                        self.store.create(self._make_pod(sts, ordinal))
                    except AlreadyExists:
                        pass
                # one create per sync; the pod's events re-enqueue us
                self._update_status(sts, owned)
                return
            if not pod_ready(pod):
                # wait for this ordinal before creating higher ones
                self._update_status(sts, owned)
                return

        # scale down: delete the HIGHEST ordinal >= want, one at a time
        extra = sorted((o for o in owned if o >= want), reverse=True)
        if extra:
            victim = owned[extra[0]]
            try:
                self.store.delete("Pod", victim.metadata.name, ns)
            except NotFound:
                pass
        self._update_status(sts, owned)

    def _update_status(self, sts, owned: dict[int, Pod]) -> None:
        fresh = self.sets.get(sts.metadata.name, sts.metadata.namespace)
        if fresh is None:
            return
        status = {"replicas": len(owned),
                  "readyReplicas": sum(1 for p in owned.values()
                                       if pod_ready(p))}
        if fresh.status == status:
            return
        fresh = fresh.clone()
        fresh.status = status
        try:
            self.store.update(fresh)
        except Exception:  # noqa: BLE001 — status write is best-effort
            pass
