"""StatefulSet controller: ordered, stably-named pods.

The pkg/controller/statefulset/stateful_set.go analog (sync loop
:syncStatefulSet -> stateful_set_control.go UpdateStatefulSet): replicas
get ordinal identities `<name>-0 .. <name>-(N-1)`; scale-up creates the
lowest missing ordinal only after every lower ordinal is Running and Ready
(OrderedReady semantics, stateful_set_control.go:428); scale-down deletes
the highest ordinal first, one at a time, and only when every remaining pod
is healthy (:464). Identity is stable: a deleted ordinal is recreated with
the same name.
"""

from __future__ import annotations

import copy
import re

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import (
    controller_ref,
    is_active,
    make_controller_ref,
    pod_ready,
)


def ordinal_of(set_name: str, pod_name: str) -> int | None:
    """getOrdinal (stateful_set_utils.go:53): <setname>-<ordinal>."""
    m = re.fullmatch(re.escape(set_name) + r"-(\d+)", pod_name)
    return int(m.group(1)) if m else None


class StatefulSetController(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, set_informer: Informer,
                 pod_informer: Informer):
        super().__init__()
        self.name = "statefulset-controller"
        self.store = store
        self.sets = set_informer
        self.pods = pod_informer
        set_informer.add_handler(self._on_set)
        pod_informer.add_handler(self._on_pod)

    def _on_set(self, event) -> None:
        if event.obj.kind == "StatefulSet":
            self.enqueue(event.obj.key)

    def _on_pod(self, event) -> None:
        ref = controller_ref(event.obj)
        if ref is not None and ref.get("kind") == "StatefulSet":
            self.enqueue(f"{event.obj.metadata.namespace}/{ref.get('name')}")

    def _owned_by_ordinal(self, sts) -> dict[int, Pod]:
        owned: dict[int, Pod] = {}
        for pod in self.pods.items():
            if pod.metadata.namespace != sts.metadata.namespace \
                    or not is_active(pod):
                continue
            ref = controller_ref(pod)
            if ref is None or ref.get("uid") != sts.metadata.uid:
                continue
            ordinal = ordinal_of(sts.metadata.name, pod.metadata.name)
            if ordinal is not None:
                owned[ordinal] = pod
        return owned

    def _make_pod(self, sts, ordinal: int) -> Pod:
        d = copy.deepcopy(sts.spec.get("template") or {})
        meta = d.setdefault("metadata", {})
        meta["name"] = f"{sts.metadata.name}-{ordinal}"
        meta["namespace"] = sts.metadata.namespace
        meta.pop("uid", None)
        labels = meta.setdefault("labels", {})
        if not labels:
            labels.update((sts.spec.get("selector") or {})
                          .get("matchLabels") or {})
        # the stable-identity labels (stateful_set_utils.go:95)
        labels["statefulset.kubernetes.io/pod-name"] = meta["name"]
        meta["ownerReferences"] = [make_controller_ref(sts)]
        return Pod.from_dict(d)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        sts = self.sets.get(name, ns)
        if sts is None:
            return
        owned = self._owned_by_ordinal(sts)
        want = sts.replicas

        # scale up: create the LOWEST missing ordinal < want, but only once
        # every lower ordinal is Running and Ready (OrderedReady)
        for ordinal in range(want):
            pod = owned.get(ordinal)
            if pod is None:
                if all(pod_ready(owned[i]) for i in range(ordinal)
                       if i in owned):
                    try:
                        self.store.create(self._make_pod(sts, ordinal))
                    except AlreadyExists:
                        pass
                # one create per sync; the pod's events re-enqueue us
                self._update_status(sts, owned)
                return
            if not pod_ready(pod):
                # wait for this ordinal before creating higher ones
                self._update_status(sts, owned)
                return

        # scale down: delete the HIGHEST ordinal >= want, one at a time
        extra = sorted((o for o in owned if o >= want), reverse=True)
        if extra:
            victim = owned[extra[0]]
            try:
                self.store.delete("Pod", victim.metadata.name, ns)
            except NotFound:
                pass
        self._update_status(sts, owned)

    def _update_status(self, sts, owned: dict[int, Pod]) -> None:
        fresh = self.sets.get(sts.metadata.name, sts.metadata.namespace)
        if fresh is None:
            return
        status = {"replicas": len(owned),
                  "readyReplicas": sum(1 for p in owned.values()
                                       if pod_ready(p))}
        if fresh.status == status:
            return
        fresh = fresh.clone()
        fresh.status = status
        try:
            self.store.update(fresh)
        except Exception:  # noqa: BLE001 — status write is best-effort
            pass
