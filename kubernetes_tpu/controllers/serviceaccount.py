"""ServiceAccount + token controllers.

Analog of pkg/controller/serviceaccount: ServiceAccountsController
(serviceaccounts_controller.go:112) guarantees every Active namespace holds
the accounts in its managed set (just "default"), recreating them on
deletion; TokensController (tokens_controller.go:106) guarantees every
ServiceAccount owns at least one token Secret and that the account's
`secrets` list references it.
"""

from __future__ import annotations

import secrets as _secrets

from kubernetes_tpu.api.objects import Secret, ServiceAccount
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

MANAGED_ACCOUNTS = ("default",)
TOKEN_TYPE = "kubernetes.io/service-account-token"


class ServiceAccountController(ReconcileController):
    """Keyed by namespace name; sync ensures the managed accounts exist
    and each account has a token Secret."""

    workers = 1

    def __init__(self, store: ObjectStore, ns_informer: Informer,
                 sa_informer: Informer):
        super().__init__()
        self.name = "serviceaccount-controller"
        self.store = store
        self.namespaces = ns_informer
        self.accounts = sa_informer
        ns_informer.add_handler(self._on_namespace)
        sa_informer.add_handler(self._on_account)

    def _on_namespace(self, event) -> None:
        if event.type != "DELETED":
            self.enqueue(event.obj.metadata.name)

    def _on_account(self, event) -> None:
        # account deleted (or token list mutated) → re-ensure its namespace
        self.enqueue(event.obj.metadata.namespace)

    async def sync(self, key: str) -> None:
        ns = self.namespaces.get(key)
        if ns is None or ns.phase == "Terminating":
            return
        for name in MANAGED_ACCOUNTS:
            sa = self.accounts.get(name, key)
            if sa is None:
                try:
                    sa = self.store.create(ServiceAccount.from_dict(
                        {"metadata": {"name": name, "namespace": key}}))
                except AlreadyExists:
                    sa = self.store.get("ServiceAccount", name, key)
            self._ensure_token(sa)

    def _ensure_token(self, sa: ServiceAccount) -> None:
        """TokensController.syncServiceAccount: a token Secret bound to the
        account via the conventional annotations, referenced in sa.secrets."""
        ns = sa.metadata.namespace
        live = []
        for ref in sa.secrets:
            try:
                sec = self.store.get("Secret", ref.get("name", ""), ns)
            except NotFound:
                continue
            if sec.type == TOKEN_TYPE:
                live.append(ref)
        if live:
            if live != sa.secrets:
                self._set_secrets(sa, live)
            return
        token = Secret.from_dict({
            "metadata": {
                "name": f"{sa.metadata.name}-token-{_secrets.token_hex(4)}",
                "namespace": ns,
                "annotations": {
                    "kubernetes.io/service-account.name": sa.metadata.name,
                    "kubernetes.io/service-account.uid": sa.metadata.uid,
                }},
            "type": TOKEN_TYPE,
            "data": {"token": _secrets.token_urlsafe(32)}})
        try:
            created = self.store.create(token)
        except AlreadyExists:
            return
        self._set_secrets(sa, [{"name": created.metadata.name}])

    def _set_secrets(self, sa: ServiceAccount, refs: list[dict]) -> None:
        def mutate(obj):
            obj.secrets = refs
            return obj

        try:
            self.store.guaranteed_update("ServiceAccount", sa.metadata.name,
                                         sa.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass
