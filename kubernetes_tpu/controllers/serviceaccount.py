"""ServiceAccount + token controllers.

Analog of pkg/controller/serviceaccount: ServiceAccountsController
(serviceaccounts_controller.go:112) guarantees every Active namespace holds
the accounts in its managed set (just "default"), recreating them on
deletion; TokensController (tokens_controller.go:106) guarantees every
ServiceAccount owns at least one token Secret and that the account's
`secrets` list references it.
"""

from __future__ import annotations

import secrets as _secrets

from kubernetes_tpu.api.objects import Secret, ServiceAccount
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

MANAGED_ACCOUNTS = ("default",)
TOKEN_TYPE = "kubernetes.io/service-account-token"


class ServiceAccountController(ReconcileController):
    """Keyed by namespace name; sync ensures the managed accounts exist
    and each account has a token Secret."""

    workers = 1

    def __init__(self, store: ObjectStore, ns_informer: Informer,
                 sa_informer: Informer):
        super().__init__()
        self.name = "serviceaccount-controller"
        self.store = store
        self.namespaces = ns_informer
        self.accounts = sa_informer
        # namespace -> account names index, maintained from the watch —
        # sync() must not scan every account cluster-wide per namespace
        self._by_ns: dict[str, set[str]] = {}
        ns_informer.add_handler(self._on_namespace)
        sa_informer.add_handler(self._on_account)

    def _on_namespace(self, event) -> None:
        if event.type != "DELETED":
            self.enqueue(event.obj.metadata.name)

    def _on_account(self, event) -> None:
        # account deleted (or token list mutated) → re-ensure its namespace
        ns = event.obj.metadata.namespace
        name = event.obj.metadata.name
        if event.type == "DELETED":
            self._by_ns.get(ns, set()).discard(name)
        else:
            self._by_ns.setdefault(ns, set()).add(name)
        self.enqueue(ns)

    async def sync(self, key: str) -> None:
        ns = self.namespaces.get(key)
        if ns is None or ns.phase == "Terminating":
            return
        for name in MANAGED_ACCOUNTS:
            sa = self.accounts.get(name, key)
            if sa is None:
                try:
                    self.store.create(ServiceAccount.from_dict(
                        {"metadata": {"name": name, "namespace": key}}))
                except AlreadyExists:
                    pass
        # EVERY account in the namespace owns a token Secret — user-created
        # ones included (tokens_controller.go syncServiceAccount covers all
        # accounts, not just the managed 'default'); the ns index keeps
        # this O(accounts in namespace), not O(accounts cluster-wide)
        for name in list(self._by_ns.get(key, ())):
            sa = self.accounts.get(name, key)
            if sa is not None:
                self._ensure_token(sa)
        for name in MANAGED_ACCOUNTS:
            # a just-created managed account may not have reached the
            # informer cache yet: ensure its token from the store copy
            if self.accounts.get(name, key) is None:
                try:
                    self._ensure_token(
                        self.store.get("ServiceAccount", name, key))
                except NotFound:
                    pass

    def _ensure_token(self, sa: ServiceAccount) -> None:
        """TokensController.syncServiceAccount: a token Secret bound to the
        account via the conventional annotations, referenced in sa.secrets."""
        ns = sa.metadata.namespace
        live = []
        for ref in sa.secrets:
            try:
                sec = self.store.get("Secret", ref.get("name", ""), ns)
            except NotFound:
                continue
            if sec.type == TOKEN_TYPE:
                live.append(ref)
        if live:
            if live != sa.secrets:
                self._set_secrets(sa, live)
            return
        token = Secret.from_dict({
            "metadata": {
                "name": f"{sa.metadata.name}-token-{_secrets.token_hex(4)}",
                "namespace": ns,
                "annotations": {
                    "kubernetes.io/service-account.name": sa.metadata.name,
                    "kubernetes.io/service-account.uid": sa.metadata.uid,
                }},
            "type": TOKEN_TYPE,
            "data": {"token": _secrets.token_urlsafe(32)}})
        try:
            created = self.store.create(token)
        except AlreadyExists:
            return
        self._set_secrets(sa, [{"name": created.metadata.name}])

    def _set_secrets(self, sa: ServiceAccount, refs: list[dict]) -> None:
        def mutate(obj):
            obj.secrets = refs
            return obj

        try:
            self.store.guaranteed_update("ServiceAccount", sa.metadata.name,
                                         sa.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass
