"""Deployment controller: template-hashed ReplicaSets + rollout strategies.

Mirrors pkg/controller/deployment (sync at deployment_controller.go:560,
getNewReplicaSet sync.go:196, rolling math rolling.go:22 NewRSNewReplicas /
:57 reconcileOldReplicaSets, recreate.go): a Deployment owns one ReplicaSet
per pod-template revision, named {deployment}-{template-hash}; RollingUpdate
scales the new RS up within maxSurge and old RSs down within
maxUnavailable, Recreate kills all old replicas before scaling up the new.

Availability feeds from the RS controller's status (readyReplicas), which in
turn reads pod Ready conditions reported by the node agent."""

from __future__ import annotations

import hashlib
import json
from typing import Any

from kubernetes_tpu.api.objects import ReplicaSet
from kubernetes_tpu.apiserver.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import controller_ref, make_controller_ref

HASH_LABEL = "pod-template-hash"  # extensions.DefaultDeploymentUniqueLabelKey
REVISION_ANNOTATION = "deployment.kubernetes.io/revision"  # util.RevisionAnnotation


def template_hash(template: dict) -> str:
    """Pod-template revision hash (controller.ComputeHash analog): stable
    digest of the canonicalized template, excluding the hash label itself."""
    import copy

    t = copy.deepcopy(template or {})
    labels = (t.get("metadata") or {}).get("labels")
    if labels:
        labels.pop(HASH_LABEL, None)
    blob = json.dumps(t, sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


def parse_intstr(value: Any, total: int, default: str, round_up: bool) -> int:
    """intstr.GetValueFromIntOrPercent: ints pass through, "25%" scales by
    `total` (surge rounds up, unavailable rounds down)."""
    if value is None:
        value = default
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if s.endswith("%"):
        frac = int(s[:-1]) * total
        return -(-frac // 100) if round_up else frac // 100
    return int(s)


class DeploymentController(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, deploy_informer: Informer,
                 rs_informer: Informer):
        super().__init__()
        self.name = "deployment-controller"
        self.store = store
        self.deployments = deploy_informer
        self.replicasets = rs_informer
        deploy_informer.add_handler(self._on_deployment)
        rs_informer.add_handler(self._on_rs)

    def _on_deployment(self, event) -> None:
        self.enqueue(event.obj.key)

    def _on_rs(self, event) -> None:
        ref = controller_ref(event.obj)
        if ref is not None and ref.get("kind") == "Deployment":
            self.enqueue(f"{event.obj.metadata.namespace}/{ref.get('name')}")

    # ---- helpers ----

    def _owned_rss(self, deploy) -> list[ReplicaSet]:
        out = []
        for rs in self.replicasets.items():
            if rs.metadata.namespace != deploy.metadata.namespace:
                continue
            ref = controller_ref(rs)
            if ref is not None and ref.get("uid") == deploy.metadata.uid:
                out.append(rs)
        return out

    def _new_rs(self, deploy, rss: list[ReplicaSet]) -> ReplicaSet | None:
        want = template_hash(deploy.spec.get("template") or {})
        for rs in rss:
            if rs.metadata.labels.get(HASH_LABEL) == want \
                    or template_hash(rs.spec.get("template") or {}) == want:
                return rs
        return None

    def _create_new_rs(self, deploy, initial_replicas: int) -> ReplicaSet:
        """getNewReplicaSet's create path (sync.go:271): template + hash
        label baked into selector, template labels, and RS labels."""
        import copy

        template = copy.deepcopy(deploy.spec.get("template") or {})
        h = template_hash(template)
        tmeta = template.setdefault("metadata", {})
        tmeta.setdefault("labels", {})
        tmeta["labels"][HASH_LABEL] = h
        selector = copy.deepcopy(deploy.spec.get("selector") or {})
        selector.setdefault("matchLabels", {})[HASH_LABEL] = h
        revision = 1 + max(
            (int(r.metadata.annotations.get(REVISION_ANNOTATION, 0) or 0)
             for r in self._owned_rss(deploy)), default=0)
        rs = ReplicaSet.from_dict({
            "metadata": {
                "name": f"{deploy.metadata.name}-{h}",
                "namespace": deploy.metadata.namespace,
                "labels": dict(tmeta["labels"]),
                "annotations": {REVISION_ANNOTATION: str(revision)},
                "ownerReferences": [make_controller_ref(deploy)],
            },
            "spec": {"replicas": initial_replicas, "selector": selector,
                     "template": template},
        })
        try:
            return self.store.create(rs)
        except AlreadyExists:
            return self.store.get("ReplicaSet", rs.metadata.name,
                                  rs.metadata.namespace)

    def _scale_rs(self, rs: ReplicaSet, replicas: int) -> None:
        if rs.replicas == replicas:
            return
        fresh = rs.clone()
        fresh.spec["replicas"] = replicas
        try:
            self.store.update(fresh)
        except (Conflict, NotFound):
            self.enqueue_after(
                f"{rs.metadata.namespace}/{rs.metadata.name}", 0.05)

    # ---- reconcile ----

    def _rollback(self, deploy, rss: list[ReplicaSet]) -> bool:
        """spec.rollbackTo (rollback.go rollback): point the deployment's
        template at the target revision's RS template and clear the marker;
        the normal rolling machinery then rolls 'forward' to it."""
        import copy

        target_rev = int((deploy.spec.get("rollbackTo") or {}).get(
            "revision", 0) or 0)
        by_rev = sorted(
            rss, key=lambda r: int(
                r.metadata.annotations.get(REVISION_ANNOTATION, 0) or 0))
        current_hash = template_hash(deploy.spec.get("template") or {})
        candidates = [r for r in by_rev
                      if template_hash(r.spec.get("template") or {})
                      != current_hash]
        if target_rev:
            pick = next(
                (r for r in by_rev
                 if int(r.metadata.annotations.get(REVISION_ANNOTATION, 0)
                        or 0) == target_rev), None)
        else:
            pick = candidates[-1] if candidates else None  # last revision
        def clear(obj):
            obj.spec.pop("rollbackTo", None)
            if pick is not None:
                template = copy.deepcopy(pick.spec.get("template") or {})
                labels = (template.get("metadata") or {}).get("labels")
                if labels:
                    labels.pop(HASH_LABEL, None)
                obj.spec["template"] = template
            return obj

        try:
            self.store.guaranteed_update(
                "Deployment", deploy.metadata.name,
                deploy.metadata.namespace, clear)
        except (NotFound, Conflict):
            return False
        return True

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        deploy = self.deployments.get(name, ns)
        if deploy is None:
            return
        rss = self._owned_rss(deploy)
        if deploy.spec.get("rollbackTo") is not None:
            # rewrite the spec, then reconcile the NEXT observation of it
            self._rollback(deploy, rss)
            self.enqueue_after(key, 0.05)
            return
        new_rs = self._new_rs(deploy, rss)
        if new_rs is not None:
            # a rollback re-activated an old template: its RS is "new"
            # again and takes the next revision number (rollback.go
            # updates the revision on rollback)
            max_rev = max(
                (int(r.metadata.annotations.get(REVISION_ANNOTATION, 0)
                     or 0) for r in rss), default=0)
            my_rev = int(new_rs.metadata.annotations.get(
                REVISION_ANNOTATION, 0) or 0)
            if my_rev < max_rev:
                def bump(obj):
                    obj.metadata.annotations[REVISION_ANNOTATION] = str(
                        max_rev + 1)
                    return obj

                try:
                    self.store.guaranteed_update(
                        "ReplicaSet", new_rs.metadata.name, ns, bump)
                except (NotFound, Conflict):
                    pass
        old_rss = [rs for rs in rss if new_rs is None
                   or rs.metadata.uid != new_rs.metadata.uid]
        desired = deploy.replicas

        if deploy.strategy_type == "Recreate":
            # recreate.go: all old replicas down, then the new RS up
            for rs in old_rss:
                self._scale_rs(rs, 0)
            old_active = sum(int((rs.status or {}).get("replicas", 0))
                             for rs in old_rss)
            if old_active > 0:
                self.enqueue_after(key, 0.05)  # wait for teardown
            else:
                if new_rs is None:
                    new_rs = self._create_new_rs(deploy, desired)
                self._scale_rs(new_rs, desired)
            self._update_status(deploy, new_rs, old_rss)
            return

        # RollingUpdate (rolling.go)
        params = (deploy.spec.get("strategy") or {}).get("rollingUpdate") or {}
        max_surge = parse_intstr(params.get("maxSurge"), desired, "25%", True)
        max_unavail = parse_intstr(params.get("maxUnavailable"), desired,
                                   "25%", False)
        if max_surge == 0 and max_unavail == 0:
            max_unavail = 1  # validation forbids both zero; stay live
        if new_rs is None:
            new_rs = self._create_new_rs(
                deploy, desired if not old_rss else 0)
            rss = rss + [new_rs]

        # scale up new within surge (NewRSNewReplicas, rolling.go:22)
        total = sum(rs.replicas for rs in rss)
        headroom = desired + max_surge - total
        if headroom > 0 and new_rs.replicas < desired:
            self._scale_rs(new_rs, min(desired, new_rs.replicas + headroom))

        # scale down old within availability budget (rolling.go:57)
        total_available = sum(int((rs.status or {}).get("availableReplicas", 0))
                              for rs in rss)
        min_available = desired - max_unavail
        budget = total_available - min_available
        if budget > 0:
            for rs in sorted(old_rss,
                             key=lambda r: r.metadata.creation_timestamp):
                if budget <= 0:
                    break
                down = min(rs.replicas, budget)
                if down > 0:
                    self._scale_rs(rs, rs.replicas - down)
                    budget -= down
        if any(rs.replicas > 0 for rs in old_rss) \
                or new_rs.replicas < desired:
            self.enqueue_after(key, 0.05)  # rollout still progressing
        self._update_status(deploy, new_rs, old_rss)

    def _update_status(self, deploy, new_rs, old_rss) -> None:
        rss = ([new_rs] if new_rs is not None else []) + list(old_rss)
        status = {
            "replicas": sum(int((r.status or {}).get("replicas", 0))
                            for r in rss),
            "updatedReplicas": int((new_rs.status or {}).get("replicas", 0))
            if new_rs is not None else 0,
            "readyReplicas": sum(int((r.status or {}).get("readyReplicas", 0))
                                 for r in rss),
            "availableReplicas": sum(
                int((r.status or {}).get("availableReplicas", 0))
                for r in rss),
        }
        fresh = self.deployments.get(deploy.metadata.name,
                                     deploy.metadata.namespace)
        if fresh is None or fresh.status == status:
            return
        fresh = fresh.clone()
        fresh.status = status
        try:
            self.store.update(fresh)
        except (Conflict, NotFound):
            pass
