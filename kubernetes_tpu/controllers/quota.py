"""ResourceQuota controller: asynchronous usage recalculation.

Analog of pkg/controller/resourcequota/resource_quota_controller.go: the
admission plugin (apiserver/admission.py ResourceQuotaPlugin) charges usage
eagerly on CREATE, but only this controller *replenishes* — when pods are
deleted or reach a terminal phase, it recomputes the namespace's true usage
and rewrites quota status (replenishment_controller.go registers exactly
those deletion/terminal triggers). A periodic full resync bounds drift.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.apiserver.admission import ResourceQuotaPlugin
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

log = logging.getLogger(__name__)


class ResourceQuotaController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, quota_informer: Informer,
                 pod_informer: Informer, resync_period: float = 30.0):
        super().__init__()
        self.name = "resourcequota-controller"
        self.store = store
        self.quotas = quota_informer
        self.resync_period = resync_period
        self._usage = ResourceQuotaPlugin()
        self._resync_task: asyncio.Task | None = None
        quota_informer.add_handler(self._on_quota)
        pod_informer.add_handler(self._on_pod)

    def _on_quota(self, event) -> None:
        if event.type != "DELETED":
            self.enqueue(event.obj.key)

    def _on_pod(self, event) -> None:
        # replenishment triggers: pod deleted or turned terminal
        terminal = event.obj.status.phase in ("Succeeded", "Failed")
        if event.type == "DELETED" or terminal:
            ns = event.obj.metadata.namespace
            for quota in self.quotas.items():
                if quota.metadata.namespace == ns:
                    self.enqueue(quota.key)

    async def start(self) -> None:
        await super().start()
        self._resync_task = asyncio.get_running_loop().create_task(
            self._resync_loop())
        for quota in self.quotas.items():
            self.enqueue(quota.key)

    def stop(self) -> None:
        if self._resync_task is not None:
            self._resync_task.cancel()
            self._resync_task = None
        super().stop()

    async def _resync_loop(self) -> None:
        while True:
            await asyncio.sleep(self.resync_period)
            for quota in self.quotas.items():
                self.enqueue(quota.key)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        quota = self.quotas.get(name, ns)
        if quota is None:
            return
        used = self._usage._namespace_usage(self.store, ns)
        hard = quota.spec.get("hard") or {}
        status = {"hard": dict(hard),
                  "used": {res: str(used.get(res, 0))
                           for res in ResourceQuotaPlugin.TRACKED
                           if res in hard}}
        if quota.status == status:
            return

        def mutate(obj):
            obj.status = status
            return obj

        try:
            self.store.guaranteed_update("ResourceQuota", name, ns, mutate)
        except (NotFound, Conflict):
            pass
