"""CronJob controller.

Analog of pkg/controller/cronjob/cronjob_controller.go: a 10s `syncAll`
sweep (not informer-driven — the reference polls deliberately, :96) that,
for every CronJob, computes unmet fire times since the last schedule
(utils.go getRecentUnmetScheduleTimes), applies the concurrency policy
(Allow | Forbid: skip while a spawned Job is still active | Replace: delete
the active Jobs first), creates one Job per latest unmet time with the
conventional scheduled-time-derived name (so a concurrently-running second
controller can't double-spawn: the create collides), and records
status.lastScheduleTime.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable

from kubernetes_tpu.api.objects import Job
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.replicaset import make_controller_ref
from kubernetes_tpu.utils.cron import CronError, CronSchedule

log = logging.getLogger(__name__)


class CronJobController:
    name = "cronjob-controller"

    def __init__(self, store: ObjectStore, cronjob_informer: Informer,
                 job_informer: Informer, sync_period: float = 10.0,
                 now: Callable[[], float] = time.time):
        self.store = store
        self.cronjobs = cronjob_informer
        self.jobs = job_informer
        self.sync_period = sync_period
        self.now = now
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.sync_period)
            try:
                self.sync_all()
            except Exception:  # noqa: BLE001 — the sweep must not die
                log.exception("cronjob sync failed")

    def sync_all(self) -> None:
        for cj in self.cronjobs.items():
            try:
                self.sync_one(cj)
            except Exception:  # noqa: BLE001 — per-object isolation
                log.exception("cronjob %s sync failed", cj.key)

    def _owned_jobs(self, cj) -> list[Job]:
        out = []
        for job in self.jobs.items():
            if job.metadata.namespace != cj.metadata.namespace:
                continue
            if any(r.get("uid") == cj.metadata.uid
                   for r in job.metadata.owner_references):
                out.append(job)
        return out

    @staticmethod
    def _job_active(job) -> bool:
        # IsJobFinished (job utils): Complete OR Failed ends a job —
        # a deadline-failed job must not wedge Forbid forever
        return not any(c.get("type") in ("Complete", "Failed")
                       and c.get("status") == "True"
                       for c in job.status.get("conditions", []))

    def sync_one(self, cj) -> None:
        if cj.suspend:
            return
        try:
            schedule = CronSchedule(cj.schedule)
        except CronError as e:
            log.warning("cronjob %s: bad schedule: %s", cj.key, e)
            return
        now = self.now()
        last = cj.status.get("lastScheduleTime")
        # never look further back than creation; fresh objects fire from now
        start = max(float(last) if last else cj.metadata.creation_timestamp
                    or now, now - 24 * 3600)
        unmet = schedule.fire_times(start, now, limit=100)
        if not unmet:
            return
        fire = unmet[-1]  # only the most recent unmet time (syncOne :244)
        owned = self._owned_jobs(cj)
        active = [j for j in owned if self._job_active(j)]
        policy = cj.concurrency_policy
        if policy == "Forbid" and active:
            # leave lastScheduleTime alone: the slot stays unmet and fires
            # once the active Job completes (the reference returns without
            # touching status, cronjob_controller.go syncOne :253)
            return
        if policy == "Replace":
            for job in active:
                try:
                    self.store.delete("Job", job.metadata.name,
                                      job.metadata.namespace)
                except NotFound:
                    pass
        self._spawn(cj, fire)
        self._record_schedule(cj, fire)

    def _spawn(self, cj, fire: float) -> None:
        import copy

        template = copy.deepcopy(cj.spec.get("jobTemplate") or {})
        spec = template.get("spec") or {}
        meta = template.get("metadata") or {}
        # deterministic name from the fire minute (getJobFromTemplate :58):
        # a second controller replica creating the same slot collides
        meta["name"] = f"{cj.metadata.name}-{int(fire) // 60}"
        meta["namespace"] = cj.metadata.namespace
        meta.setdefault("labels", dict(
            ((cj.spec.get("jobTemplate") or {}).get("metadata") or {}
             ).get("labels") or {}))
        meta.setdefault("ownerReferences", []).append(
            make_controller_ref(cj))
        job = Job.from_dict({"metadata": meta, "spec": spec})
        try:
            self.store.create(job)
        except AlreadyExists:
            pass

    def _record_schedule(self, cj, fire: float) -> None:
        def mutate(obj):
            obj.status["lastScheduleTime"] = fire
            return obj

        try:
            self.store.guaranteed_update("CronJob", cj.metadata.name,
                                         cj.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass
