"""Orphan garbage collection: pods whose controller owner is gone.

The pod-edge subset of the reference's ownerRef garbage collector
(pkg/controller/garbagecollector: a dependency graph over ownerReferences;
orphaned dependents are deleted on owner deletion) — here the only
dependents are pods and the owners are the workload kinds, so a keyed
reconcile over pods suffices; the graph degenerates to one lookup."""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import controller_ref

OWNER_KINDS = ("ReplicaSet", "ReplicationController", "StatefulSet",
               "Deployment", "Job")


class GarbageCollector(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, pod_informer: Informer,
                 owner_informers: dict[str, Informer]):
        super().__init__()
        self.name = "garbage-collector"
        self.store = store
        self.pods = pod_informer
        self.owners = owner_informers
        # owner uid -> owned pod keys: the degenerate dependency graph's
        # reverse edges, so an owner deletion touches only ITS pods instead
        # of sweeping every pod (VERDICT r2 weak #7)
        self._pods_by_owner: dict[str, set[str]] = {}
        pod_informer.add_handler(self._on_pod)
        for informer in owner_informers.values():
            informer.add_handler(self._on_owner)

    def _on_pod(self, event) -> None:
        pod = event.obj
        ref = controller_ref(pod)
        if ref is None:
            return
        uid = ref.get("uid", "")
        if event.type == "DELETED":
            owned = self._pods_by_owner.get(uid)
            if owned is not None:
                owned.discard(pod.key)
                if not owned:
                    del self._pods_by_owner[uid]
            return
        self._pods_by_owner.setdefault(uid, set()).add(pod.key)
        self.enqueue(pod.key)

    def _on_owner(self, event) -> None:
        # an owner deletion orphans its pods: re-check exactly those
        if event.type != "DELETED":
            return
        for key in self._pods_by_owner.get(event.obj.metadata.uid, ()):
            self.enqueue(key)

    def _owner_exists(self, namespace: str, ref: dict) -> bool:
        kind = ref.get("kind", "")
        informer = self.owners.get(kind)
        if informer is None:
            return True  # unmanaged kind: never collect
        owner = informer.get(ref.get("name", ""), namespace)
        return owner is not None and owner.metadata.uid == ref.get("uid")

    def _owner_live(self, namespace: str, ref: dict) -> bool:
        """Re-check against the store itself: the pod and owner informers
        ride independent watch streams, so a pod can be observed before its
        just-created owner's ADDED lands — the reference GC confirms absence
        with a live apiserver read before deleting (garbagecollector.go
        attemptToDeleteItem; ADVICE r2 #2)."""
        try:
            owner = self.store.get(ref.get("kind", ""), ref.get("name", ""),
                                   namespace)
        except (NotFound, KeyError):
            return False
        return owner.metadata.uid == ref.get("uid")

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pod = self.pods.get(name, ns)
        if pod is None:
            return
        ref = controller_ref(pod)
        if ref is None or self._owner_exists(ns, ref):
            return
        if self._owner_live(ns, ref):
            return  # informer lag, not a real orphan
        try:
            self.store.delete("Pod", name, ns)
        except NotFound:
            pass
