"""Orphan garbage collection: pods whose controller owner is gone.

The pod-edge subset of the reference's ownerRef garbage collector
(pkg/controller/garbagecollector: a dependency graph over ownerReferences;
orphaned dependents are deleted on owner deletion) — here the only
dependents are pods and the owners are the workload kinds, so a keyed
reconcile over pods suffices; the graph degenerates to one lookup."""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import controller_ref

OWNER_KINDS = ("ReplicaSet", "ReplicationController", "StatefulSet",
               "Deployment", "Job")


class GarbageCollector(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, pod_informer: Informer,
                 owner_informers: dict[str, Informer]):
        super().__init__()
        self.name = "garbage-collector"
        self.store = store
        self.pods = pod_informer
        self.owners = owner_informers
        pod_informer.add_handler(self._on_pod)
        for informer in owner_informers.values():
            informer.add_handler(self._on_owner)

    def _on_pod(self, event) -> None:
        if event.type == "DELETED":
            return
        pod = event.obj
        if controller_ref(pod) is not None:
            self.enqueue(pod.key)

    def _on_owner(self, event) -> None:
        # an owner deletion orphans its pods: re-check every owned pod
        if event.type != "DELETED":
            return
        owner = event.obj
        for pod in self.pods.items():
            ref = controller_ref(pod)
            if (ref is not None and ref.get("uid") == owner.metadata.uid
                    and pod.metadata.namespace == owner.metadata.namespace):
                self.enqueue(pod.key)

    def _owner_exists(self, namespace: str, ref: dict) -> bool:
        kind = ref.get("kind", "")
        informer = self.owners.get(kind)
        if informer is None:
            return True  # unmanaged kind: never collect
        owner = informer.get(ref.get("name", ""), namespace)
        return owner is not None and owner.metadata.uid == ref.get("uid")

    def _owner_live(self, namespace: str, ref: dict) -> bool:
        """Re-check against the store itself: the pod and owner informers
        ride independent watch streams, so a pod can be observed before its
        just-created owner's ADDED lands — the reference GC confirms absence
        with a live apiserver read before deleting (garbagecollector.go
        attemptToDeleteItem; ADVICE r2 #2)."""
        try:
            owner = self.store.get(ref.get("kind", ""), ref.get("name", ""),
                                   namespace)
        except (NotFound, KeyError):
            return False
        return owner.metadata.uid == ref.get("uid")

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pod = self.pods.get(name, ns)
        if pod is None:
            return
        ref = controller_ref(pod)
        if ref is None or self._owner_exists(ns, ref):
            return
        if self._owner_live(ns, ref):
            return  # informer lag, not a real orphan
        try:
            self.store.delete("Pod", name, ns)
        except NotFound:
            pass
