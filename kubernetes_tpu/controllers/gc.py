"""Orphan garbage collection: dependents whose controller owner is gone.

The ownerRef subset of the reference's garbage collector
(pkg/controller/garbagecollector: a dependency graph over ownerReferences;
orphaned dependents are deleted on owner deletion). The live dependent
edges here are Pods owned by the workload kinds and Jobs owned by
CronJobs; the graph is a reverse index from owner uid to dependent keys so
an owner deletion touches only ITS dependents instead of sweeping every
object (VERDICT r2 weak #7)."""

from __future__ import annotations

from kubernetes_tpu.apiserver.store import NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import controller_ref

OWNER_KINDS = ("ReplicaSet", "ReplicationController", "StatefulSet",
               "Deployment", "Job", "DaemonSet", "CronJob")


class GarbageCollector(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore,
                 dependent_informers: dict[str, Informer],
                 owner_informers: dict[str, Informer]):
        super().__init__()
        self.name = "garbage-collector"
        self.store = store
        self.dependents = dependent_informers
        self.owners = owner_informers
        # owner uid -> dependent "Kind|ns/name" keys: the reverse edges
        self._by_owner: dict[str, set[str]] = {}
        for kind, informer in dependent_informers.items():
            informer.add_handler(
                lambda event, _kind=kind: self._on_dependent(_kind, event))
        for informer in owner_informers.values():
            informer.add_handler(self._on_owner)

    def _on_dependent(self, kind: str, event) -> None:
        obj = event.obj
        ref = controller_ref(obj)
        if ref is None:
            return
        uid = ref.get("uid", "")
        key = f"{kind}|{obj.key}"
        if event.type == "DELETED":
            owned = self._by_owner.get(uid)
            if owned is not None:
                owned.discard(key)
                if not owned:
                    del self._by_owner[uid]
            return
        self._by_owner.setdefault(uid, set()).add(key)
        self.enqueue(key)

    def _on_owner(self, event) -> None:
        # an owner deletion orphans its dependents: re-check exactly those
        if event.type != "DELETED":
            return
        for key in self._by_owner.get(event.obj.metadata.uid, ()):
            self.enqueue(key)

    def _owner_exists(self, namespace: str, ref: dict) -> bool:
        kind = ref.get("kind", "")
        informer = self.owners.get(kind)
        if informer is None:
            return True  # unmanaged kind: never collect
        owner = informer.get(ref.get("name", ""), namespace)
        return owner is not None and owner.metadata.uid == ref.get("uid")

    def _owner_live(self, namespace: str, ref: dict) -> bool:
        """Re-check against the store itself: dependent and owner informers
        ride independent watch streams, so a dependent can be observed
        before its just-created owner's ADDED lands — the reference GC
        confirms absence with a live apiserver read before deleting
        (garbagecollector.go attemptToDeleteItem; ADVICE r2 #2)."""
        try:
            owner = self.store.get(ref.get("kind", ""), ref.get("name", ""),
                                   namespace)
        except (NotFound, KeyError):
            return False
        return owner.metadata.uid == ref.get("uid")

    async def sync(self, key: str) -> None:
        kind, _, obj_key = key.partition("|")
        ns, name = obj_key.split("/", 1)
        informer = self.dependents.get(kind)
        obj = informer.get(name, ns) if informer is not None else None
        if obj is None:
            return
        ref = controller_ref(obj)
        if ref is None or self._owner_exists(ns, ref):
            return
        if self._owner_live(ns, ref):
            return  # informer lag, not a real orphan
        try:
            self.store.delete(kind, name, ns)
        except NotFound:
            pass
