"""Generic reconcile machinery: the one pattern every reference controller
follows (SURVEY.md §2.4) — SharedInformer events → rate-limited workqueue of
object keys → worker loops → sync(key) reconciling desired vs observed.

Mirrors pkg/controller/controller_utils.go: ControllerExpectations (:150,
the in-flight create/delete bookkeeping that stops a controller from acting
twice while its own writes are still in the watch pipe) and slowStartBatch
(:744, 1-2-4-... create bursts so a failing kubelet/quota doesn't eat the
whole burst); worker shape per replica_set.go:405 (worker → processNextWorkItem
→ syncHandler with rate-limited requeue on error).

Host-plane only by design: controllers reconcile object counts and write
through the store; the device never sees them (the TPU tier is the
scheduler's filter/score program)."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable

from kubernetes_tpu.client.workqueue import Backoff, BackoffQueue

log = logging.getLogger(__name__)

# controller.ExpectationsTimeout (controller_utils.go:80)
EXPECTATIONS_TTL = 5 * 60.0
# controller.SlowStartInitialBatchSize (controller_utils.go:744 callers)
SLOW_START_INITIAL = 1
# per-item backoff entries older than this are swept from the worker loop
# (Backoff.gc — the reference runs a periodic gc goroutine per backoff)
BACKOFF_GC_PERIOD = 60.0


class Expectations:
    """Per-key in-flight create/delete counts (ControllerExpectations,
    controller_utils.go:150). A sync observes its own previous writes via
    the informer before acting again; expired expectations (5min) unblock a
    controller whose watch stalled."""

    def __init__(self):
        self._exp: dict[str, tuple[int, int, float]] = {}

    def expect(self, key: str, adds: int = 0, dels: int = 0) -> None:
        self._exp[key] = (adds, dels, time.monotonic())

    def creation_observed(self, key: str) -> None:
        adds, dels, ts = self._exp.get(key, (0, 0, 0.0))
        if key in self._exp:
            self._exp[key] = (adds - 1, dels, ts)

    def deletion_observed(self, key: str) -> None:
        adds, dels, ts = self._exp.get(key, (0, 0, 0.0))
        if key in self._exp:
            self._exp[key] = (adds, dels - 1, ts)

    def satisfied(self, key: str) -> bool:
        if key not in self._exp:
            return True
        adds, dels, ts = self._exp[key]
        if adds <= 0 and dels <= 0:
            return True
        return time.monotonic() - ts > EXPECTATIONS_TTL  # expired

    def forget(self, key: str) -> None:
        self._exp.pop(key, None)


async def slow_start_batch(count: int, fn: Callable[[], Awaitable[bool]],
                           initial: int = SLOW_START_INITIAL
                           ) -> tuple[int, int]:
    """slowStartBatch (controller_utils.go:744): run `count` create calls in
    doubling batches, stopping at the first batch with a failure. Returns
    (successes, attempted) — callers must release expectations for the
    `count - attempted` calls that were never made (the reference's
    skippedPods loop, replica_set.go:478)."""
    remaining = count
    successes = 0
    attempted = 0
    batch = initial
    while remaining > 0:
        n = min(batch, remaining)
        results = await asyncio.gather(*(fn() for _ in range(n)),
                                       return_exceptions=True)
        attempted += n
        ok = sum(1 for r in results if r is True)
        successes += ok
        if ok < n:
            break
        remaining -= n
        batch = 2 * batch
    return successes, attempted


class ReconcileController:
    """Informer-fed keyed reconcile loop. Subclasses implement
    `async sync(key)` and call `enqueue(key)` from informer handlers."""

    name = "controller"
    workers = 1

    def __init__(self):
        self.queue = BackoffQueue()
        self.backoff = Backoff(initial=0.01, max_duration=30.0)
        self._tasks: list[asyncio.Task] = []
        self.expectations = Expectations()
        self._last_backoff_gc = time.monotonic()
        self._mx_reconcile = None
        self._mx_errors = None

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def enqueue_after(self, key: str, delay: float) -> None:
        self.queue.add_after(key, delay)

    async def start(self) -> None:
        # subclasses assign self.name after super().__init__, so the
        # queue's metric name and the reconcile families bind here
        from kubernetes_tpu.obs import metrics as obs_metrics

        self.queue.name = self.name
        self._mx_reconcile = obs_metrics.REGISTRY.histogram(
            "controller_reconcile_duration_seconds",
            "How long one sync(key) reconcile takes.",
            ("controller",)).labels(self.name)
        self._mx_errors = obs_metrics.REGISTRY.counter(
            "controller_reconcile_errors_total",
            "Reconciles that failed and were requeued with backoff.",
            ("controller",)).labels(self.name)
        loop = asyncio.get_running_loop()
        for _ in range(self.workers):
            self._tasks.append(loop.create_task(self._worker()))

    def stop(self) -> None:
        self.queue.close()
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()

    async def _worker(self) -> None:
        while True:
            key = await self.queue.get()
            if key is None:
                return
            t0 = time.monotonic()
            try:
                await self.sync(key)
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — requeue w/ backoff
                log.warning("%s: sync(%s) failed: %s", self.name, key, e)
                if self._mx_errors is not None:
                    self._mx_errors.inc()
                    self._mx_reconcile.observe(time.monotonic() - t0)
                self.queue.done(key)
                self.queue.add_after(key, self.backoff.next_delay(key))
                self._maybe_gc_backoff()
                continue
            if self._mx_reconcile is not None:
                self._mx_reconcile.observe(time.monotonic() - t0)
            self.queue.done(key)
            self.backoff.reset(key)
            self._maybe_gc_backoff()

    def _maybe_gc_backoff(self) -> None:
        """Sweep stale per-item backoff entries from the run loop — the
        Backoff map otherwise grows one entry per key that ever failed."""
        now = time.monotonic()
        if now - self._last_backoff_gc >= BACKOFF_GC_PERIOD:
            self._last_backoff_gc = now
            self.backoff.gc()

    async def sync(self, key: str) -> None:  # pragma: no cover - interface
        raise NotImplementedError
