"""Disruption controller: PodDisruptionBudget status + eviction gate.

Analog of pkg/controller/disruption/disruption.go: for each PDB, count the
selector's pods (expectedCount), the healthy (Ready) ones among them, derive
desiredHealthy from spec.minAvailable (integer or "N%"), and publish
disruptionsAllowed = currentHealthy - desiredHealthy. `can_evict` is the
check the eviction subresource applies (pkg/registry/core/pod/storage/
eviction.go:103 checkAndDecrement): an eviction may proceed only while
disruptionsAllowed > 0, and decrements it synchronously so concurrent
evictions can't both spend the same budget.
"""

from __future__ import annotations

import math

from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import is_active, pod_ready
from kubernetes_tpu.state.podaffinity import (
    PARSE_ERROR,
    canonical_selector,
    selector_matches,
)


def _min_available(pdb, expected: int) -> int:
    """spec.minAvailable: integer or percentage string (intstr semantics,
    GetValueFromIntOrPercent with round-up for minAvailable)."""
    v = pdb.spec.get("minAvailable", 0)
    if isinstance(v, str) and v.endswith("%"):
        return math.ceil(expected * int(v[:-1]) / 100.0)
    return int(v)


class DisruptionController(ReconcileController):
    workers = 1

    def __init__(self, store: ObjectStore, pdb_informer: Informer,
                 pod_informer: Informer):
        super().__init__()
        self.name = "disruption-controller"
        self.store = store
        self.pdbs = pdb_informer
        self.pods = pod_informer
        pdb_informer.add_handler(self._on_pdb)
        pod_informer.add_handler(self._on_pod)

    def _on_pdb(self, event) -> None:
        if event.type != "DELETED":
            self.enqueue(event.obj.key)

    def _on_pod(self, event) -> None:
        # any pod change may affect the PDBs selecting it (getPdbForPod)
        pod = event.obj
        for pdb in self.pdbs.items():
            if pdb.metadata.namespace != pod.metadata.namespace:
                continue
            canon = canonical_selector(pdb.selector or None)
            if canon not in ((), PARSE_ERROR) \
                    and selector_matches(canon, pod.metadata.labels):
                self.enqueue(pdb.key)

    def _matching(self, pdb) -> list:
        canon = canonical_selector(pdb.selector or None)
        if canon in ((), PARSE_ERROR):
            return []
        return [p for p in self.pods.items()
                if p.metadata.namespace == pdb.metadata.namespace
                and is_active(p)
                and selector_matches(canon, p.metadata.labels)]

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        pdb = self.pdbs.get(name, ns)
        if pdb is None:
            return
        pods = self._matching(pdb)
        expected = len(pods)
        healthy = sum(1 for p in pods if pod_ready(p))
        desired = _min_available(pdb, expected)
        allowed = max(0, healthy - desired)
        status = {"expectedPods": expected, "currentHealthy": healthy,
                  "desiredHealthy": desired, "disruptionsAllowed": allowed}
        if pdb.status == status:
            return

        def mutate(obj):
            obj.status = status
            return obj

        try:
            self.store.guaranteed_update("PodDisruptionBudget", name, ns,
                                         mutate)
        except (NotFound, Conflict):
            pass


def eviction_allowed(store: ObjectStore, pod) -> bool:
    """Read-only twin of `can_evict`: would every PDB covering the pod
    permit a disruption right now? Spends nothing — the autoscaler's
    what-if phase uses it to rule candidate nodes in or out without
    consuming budget it may never need (the real spend still happens
    through `can_evict` at drain time, so the answer can go stale and the
    drain must re-check)."""
    ns = pod.metadata.namespace
    for pdb in store.list("PodDisruptionBudget", namespace=ns,
                          copy_objects=False):
        canon = canonical_selector(pdb.selector or None)
        if canon in ((), PARSE_ERROR) \
                or not selector_matches(canon, pod.metadata.labels):
            continue
        if int(pdb.status.get("disruptionsAllowed", 0)) <= 0:
            return False
    return True


def can_evict(store: ObjectStore, pod) -> bool:
    """Eviction-subresource budget check: spend one disruption from every
    PDB covering the pod, or refuse without spending anything. Check-all-
    then-spend-all: the whole call runs without yielding (single-loop
    store), so two callers can't both observe the same budget — the analog
    of the reference's retried live decrement (eviction.go:156
    checkAndDecrement)."""
    ns = pod.metadata.namespace
    covering = []
    for pdb in store.list("PodDisruptionBudget", namespace=ns,
                          copy_objects=False):
        canon = canonical_selector(pdb.selector or None)
        if canon in ((), PARSE_ERROR) \
                or not selector_matches(canon, pod.metadata.labels):
            continue
        if int(pdb.status.get("disruptionsAllowed", 0)) <= 0:
            return False
        covering.append(pdb.metadata.name)

    def spend(obj):
        remaining = int(obj.status.get("disruptionsAllowed", 0))
        if remaining <= 0:
            raise Conflict("budget exhausted")
        obj.status["disruptionsAllowed"] = remaining - 1
        return obj

    def refund(obj):
        obj.status["disruptionsAllowed"] = \
            int(obj.status.get("disruptionsAllowed", 0)) + 1
        return obj

    spent: list[str] = []
    for name in covering:
        try:
            store.guaranteed_update("PodDisruptionBudget", name, ns, spend)
            spent.append(name)
        except (NotFound, Conflict):
            for prior in spent:  # no partial spend survives a refusal
                try:
                    store.guaranteed_update("PodDisruptionBudget", prior,
                                            ns, refund)
                except (NotFound, Conflict):
                    pass
            return False
    return True
