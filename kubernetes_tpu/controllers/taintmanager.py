"""NoExecute taint manager: evict running pods from tainted nodes.

The NoExecuteTaintManager analog (reference
pkg/controller/node/scheduler/taint_controller.go:167 NewNoExecuteTaintManager,
:238 handlePodUpdate/handleNodeUpdate; wired into the node controller at
node_controller.go:162,274-302). Semantics:

- a pod on a node with NoExecute taints must tolerate EVERY such taint or
  it is evicted immediately;
- tolerations carrying tolerationSeconds bound the stay: the pod is
  evicted after min(tolerationSeconds over the tolerations used)
  (getMinTolerationTime, taint_controller.go:146) — the timer restarts
  only when the taint set changes;
- tolerations without tolerationSeconds tolerate forever;
- removing the taints cancels pending evictions.

The node lifecycle controller feeds this by tainting NotReady/unreachable
nodes (node_controller.go:274-302's alpha TaintBasedEvictions flow), and
the DefaultTolerationSeconds admission plugin stamps the 300s default
tolerations on every pod — together: node dies -> taint lands -> pods get
300s to be rescued -> taint manager deletes them.
"""

from __future__ import annotations

import asyncio
import logging
import time

from kubernetes_tpu.apiserver.store import NotFound, ObjectStore, WatchEvent
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.utils.events import EventRecorder

log = logging.getLogger(__name__)

# the node-condition taints the node lifecycle controller manages
# (metav1 TaintNodeNotReady/TaintNodeUnreachable at the alpha vintage)
NOT_READY_TAINT = "node.alpha.kubernetes.io/notReady"
UNREACHABLE_TAINT = "node.alpha.kubernetes.io/unreachable"


def noexecute_taints(node) -> list:
    return [t for t in node.spec.taints if t.effect == "NoExecute"]


def _fingerprint(taints) -> tuple:
    return tuple(sorted((t.key, t.value) for t in taints))


def min_toleration_seconds(pod, taints) -> float | None:
    """None = not tolerated (evict now); float('inf') = tolerated forever;
    else seconds until eviction (getMinTolerationTime)."""
    best = float("inf")
    for taint in taints:
        matching = [t for t in pod.spec.tolerations if t.tolerates(taint)]
        if not matching:
            return None
        bounded = [t.toleration_seconds for t in matching
                   if t.toleration_seconds is not None]
        if bounded and not any(t.toleration_seconds is None
                               for t in matching):
            best = min(best, max(0, min(bounded)))
    return best


class NoExecuteTaintManager:
    name = "taint-manager"

    def __init__(self, store: ObjectStore, node_informer: Informer,
                 pod_informer: Informer):
        self.store = store
        self.nodes = node_informer
        self.pods = pod_informer
        self.events = EventRecorder(store, component="taint-controller")
        # pod key -> (taint fingerprint the timer was armed for, timer task)
        self._timers: dict[str, tuple[tuple, asyncio.Task]] = {}
        # node -> last-seen NoExecute taint fingerprint (handleNodeUpdate's
        # old-vs-new diff: heartbeat MODIFIED events with unchanged taints
        # must not trigger a full pod rescan)
        self._node_taints: dict[str, tuple] = {}
        self.evicted_pods = 0
        node_informer.add_handler(self._on_node_event)
        pod_informer.add_handler(self._on_pod_event)

    async def start(self) -> None:
        for pod in self.pods.items():
            self._process_pod(pod)

    def stop(self) -> None:
        for _deadline, task in self._timers.values():
            task.cancel()
        self._timers.clear()

    # ---- informer handlers ----

    def _on_node_event(self, event: WatchEvent) -> None:
        node = event.obj
        name = node.metadata.name
        if event.type == "DELETED":
            taints = []
            self._node_taints.pop(name, None)
            fingerprint = ()
        else:
            taints = noexecute_taints(node)
            fingerprint = _fingerprint(taints)
            if self._node_taints.get(name) == fingerprint:
                return  # heartbeat noise: taint set unchanged
            self._node_taints[name] = fingerprint
        for pod in self.pods.items():
            if pod.spec.node_name == name:
                self._process_pod(pod, taints)

    def _on_pod_event(self, event: WatchEvent) -> None:
        pod = event.obj
        if event.type == "DELETED":
            self._cancel(pod.key)
            return
        if pod.spec.node_name:
            self._process_pod(pod)

    # ---- eviction decisions ----

    def _process_pod(self, pod, taints=None) -> None:
        if not pod.spec.node_name:
            return
        if taints is None:
            node = self.nodes.get(pod.spec.node_name)
            taints = noexecute_taints(node) if node is not None else []
        if not taints:
            self._cancel(pod.key)
            return
        seconds = min_toleration_seconds(pod, taints)
        if seconds is None:
            self._cancel(pod.key)
            self._evict(pod.key)
            return
        if seconds == float("inf"):
            self._cancel(pod.key)
            return
        fingerprint = _fingerprint(taints)
        existing = self._timers.get(pod.key)
        if existing is not None:
            if existing[0] == fingerprint:
                # same taint set: keep the original timer — re-arming on
                # every pod update would let a chatty status writer extend
                # the stay forever
                return
            # the taint set changed (e.g. notReady swapped for
            # unreachable): the old deadline no longer applies
            existing[1].cancel()
        task = asyncio.get_running_loop().create_task(
            self._evict_later(pod.key, seconds))
        self._timers[pod.key] = (fingerprint, task)

    def _cancel(self, pod_key: str) -> None:
        entry = self._timers.pop(pod_key, None)
        if entry is not None:
            entry[1].cancel()

    async def _evict_later(self, pod_key: str, seconds: float) -> None:
        await asyncio.sleep(seconds)
        self._timers.pop(pod_key, None)
        self._evict(pod_key)

    def _evict(self, pod_key: str) -> None:
        ns, name = pod_key.split("/", 1)
        pod = self.pods.get(name, ns)
        try:
            self.store.delete("Pod", name, ns)
        except NotFound:
            return
        self.evicted_pods += 1
        if pod is not None:
            self.events.record(pod, "Normal", "TaintManagerEviction",
                               f"Marking for deletion Pod {pod_key}")
        log.info("taint manager: evicted %s", pod_key)
