"""Endpoint controller: Services acquire endpoints as pods go Ready.

The pkg/controller/endpoint/endpoints_controller.go analog: for every
Service with a selector, maintain one same-named Endpoints object whose
subsets carry the addresses of Ready bound pods matching the selector
(addresses) and matching-but-unready pods (notReadyAddresses), with ports
mapped from the Service spec (:syncService, :420 computeEndpoints shape).
Services without a selector are user-managed (skipped), exactly the
reference's headless/external case.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Endpoints, ObjectMeta, Pod
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.controllers.replicaset import pod_ready


def _pod_address(pod: Pod) -> dict:
    addr = {"targetRef": {"kind": "Pod", "name": pod.metadata.name,
                          "namespace": pod.metadata.namespace,
                          "uid": pod.metadata.uid}}
    # hollow pods have no IPs; hostIP/nodeName identify the backend
    if pod.status.host_ip:
        addr["ip"] = pod.status.host_ip
    if pod.spec.node_name:
        addr["nodeName"] = pod.spec.node_name
    return addr


def _service_ports(service) -> list[dict]:
    out = []
    for p in service.spec.get("ports") or [{}]:
        port = {}
        if p.get("name"):
            port["name"] = p["name"]
        target = p.get("targetPort")
        try:
            number = int(target) if target is not None else None
        except (TypeError, ValueError):
            # named targetPort: the reference resolves it per pod against
            # container ports (endpoints_controller.go:466); without a
            # runtime there is nothing behind the name — fall back to the
            # service port so the subset stays valid
            number = None
        if number is None and p.get("port") is not None:
            number = int(p["port"])
        if number is not None:
            port["port"] = number
        port["protocol"] = p.get("protocol", "TCP")
        out.append(port)
    return out


class EndpointController(ReconcileController):
    workers = 2

    def __init__(self, store: ObjectStore, service_informer: Informer,
                 pod_informer: Informer,
                 node_informer: Informer | None = None):
        super().__init__()
        self.name = "endpoint-controller"
        self.store = store
        self.services = service_informer
        self.pods = pod_informer
        # node hygiene: a deleted Node's pods linger as objects until the
        # lifecycle controller evicts them (minutes) — with a node informer
        # their addresses drop from Endpoints the moment the Node goes,
        # instead of serving traffic to a machine that isn't there. Only
        # OBSERVED deletions count (a pod bound to a node name the watch
        # never saw — hollow setups — keeps serving).
        self.nodes = node_informer
        self._gone_nodes: set[str] = set()
        service_informer.add_handler(self._on_service)
        pod_informer.add_handler(self._on_pod)
        if node_informer is not None:
            node_informer.add_handler(self._on_node)

    def _on_service(self, event) -> None:
        self.enqueue(event.obj.key)

    def _on_node(self, event) -> None:
        name = event.obj.metadata.name
        if event.type != "DELETED":
            self._gone_nodes.discard(name)  # (re)registered: serve again
            return
        # a Node delete orphans its pods' addresses: re-sync every service
        # backed by a pod on that node NOW, not at the next full resync
        self._gone_nodes.add(name)
        for pod in self.pods.items():
            if pod.spec.node_name == name:
                self._enqueue_pod_services(pod)

    def _on_pod(self, event) -> None:
        # enqueue every service whose selector matches the pod's labels
        # (addPod, endpoints_controller.go:150 getPodServiceMemberships)
        self._enqueue_pod_services(event.obj)

    def _enqueue_pod_services(self, pod) -> None:
        for svc in self.services.items():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.selector
            if sel is None:
                continue
            if all(pod.metadata.labels.get(k) == v for k, v in sel.items()):
                self.enqueue(svc.key)

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        svc = self.services.get(name, ns)
        if svc is None:
            # service deleted: its endpoints go with it (syncService :367)
            try:
                self.store.delete("Endpoints", name, ns)
            except NotFound:
                pass
            return
        sel = svc.selector
        if sel is None:
            return  # selector-less services manage their own endpoints

        ready, not_ready = [], []
        for pod in self.pods.items():
            if pod.metadata.namespace != ns or not pod.spec.node_name:
                continue
            if pod.status.phase in ("Succeeded", "Failed"):
                continue
            if pod.spec.node_name in self._gone_nodes:
                continue  # node deleted: the backend machine is gone
            if not all(pod.metadata.labels.get(k) == v
                       for k, v in sel.items()):
                continue
            (ready if pod_ready(pod) else not_ready).append(
                _pod_address(pod))
        subset: dict = {}
        if ready:
            subset["addresses"] = sorted(
                ready, key=lambda a: a["targetRef"]["name"])
        if not_ready:
            subset["notReadyAddresses"] = sorted(
                not_ready, key=lambda a: a["targetRef"]["name"])
        if subset:
            subset["ports"] = _service_ports(svc)
        subsets = [subset] if subset else []

        try:
            current = self.store.get("Endpoints", name, ns)
        except NotFound:
            current = None
        if current is not None and current.subsets == subsets:
            return
        if current is None:
            self.store.create(Endpoints(
                metadata=ObjectMeta(name=name, namespace=ns),
                subsets=subsets))
        else:
            fresh = current.clone()
            fresh.subsets = subsets
            try:
                self.store.update(fresh)
            except Conflict:
                self.enqueue(key)  # retry against the newer version
