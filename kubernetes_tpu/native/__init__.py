"""Native (C) host-plane kernels, built on first import.

The reference's runtime is compiled Go; this package gives the framework's
host plane the same native tier where it does byte-level work — the FNV-1a
hashing kernel behind universe interning (utils/hashing.py) and the
ledger scatter-add behind batch commit (state/statedb.py commit_batch).

Build strategy: compile each .c with the system C compiler into the
package's `_build/` directory the first time it is imported (a few ms,
cached thereafter, keyed by source mtime) and bind it with ctypes — the
image ships g++/cc but not pybind11. Any failure (no compiler, read-only
filesystem) degrades silently to the pure-Python/numpy implementations;
callers check the function for None.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile

log = logging.getLogger(__name__)

fnv1a64 = None          # (bytes) -> int, or None when unavailable
lanes_batch = None      # (list[bytes]) -> (np.uint32[n], np.uint32[n])
scatter_add_cols = None  # (dst2d, src2d, off, rows_i64, width) -> touched
bulk_bind = None        # (bucket, bindings, rv_base, WatchEvent, NotFound,
#                          Conflict) -> (bound, errors, events, rv_end)


def _build_lib(src_name: str, stem: str | None = None,
               extra_flags: tuple[str, ...] = (),
               loader=ctypes.CDLL) -> ctypes.CDLL | None:
    """Compile `src_name` (beside this file) into _build/ if stale and load
    it. Build via a temp file + rename so concurrent importers can race.
    `stem` names the output .so (one source can build several variants,
    e.g. commitops with/without the CPython API); `loader` picks the ctypes
    binding class (PyDLL for functions that call the Python C-API and must
    hold the GIL). Returns None on any failure (callers degrade to pure
    Python)."""
    src = os.path.join(os.path.dirname(__file__), src_name)
    build_dir = os.path.join(os.path.dirname(__file__), "_build")
    if stem is None:
        stem = os.path.splitext(src_name)[0]
    lib_path = os.path.join(build_dir, f"lib{stem}.so")
    try:
        if (not os.path.exists(lib_path)
                or os.path.getmtime(lib_path) < os.path.getmtime(src)):
            os.makedirs(build_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=build_dir, suffix=".so")
            os.close(fd)
            subprocess.run(
                ["cc", "-O2", "-shared", "-fPIC", *extra_flags,
                 "-o", tmp, src],
                check=True, capture_output=True, timeout=60)
            os.replace(tmp, lib_path)
        return loader(lib_path)
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native %s unavailable (%s); using pure Python",
                  src_name, e)
        return None


def _bind_fnv():
    global fnv1a64, lanes_batch

    lib = _build_lib("fnv.c")
    if lib is None:
        return
    try:
        # symbol binding stays inside the guard: a stale .so missing a
        # symbol must degrade to pure Python, not crash the import
        lib.fnv1a64.restype = ctypes.c_uint64
        lib.fnv1a64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.fnv1a64_lanes_batch.restype = None
        lib.fnv1a64_lanes_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32)]
    except AttributeError as e:
        log.debug("native fnv symbols unavailable (%s)", e)
        return

    def _fnv1a64(data: bytes) -> int:
        return lib.fnv1a64(data, len(data))

    def _lanes_batch(items: list[bytes]):
        import numpy as np

        n = len(items)
        blob = b"".join(items)
        offsets = (ctypes.c_size_t * (n + 1))()
        pos = 0
        for i, item in enumerate(items):
            offsets[i] = pos
            pos += len(item)
        offsets[n] = pos
        lo = np.empty(n, np.uint32)
        hi = np.empty(n, np.uint32)
        lib.fnv1a64_lanes_batch(
            blob, offsets, n,
            lo.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            hi.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return lo, hi

    fnv1a64 = _fnv1a64
    lanes_batch = _lanes_batch


def _bind_commitops():
    global scatter_add_cols

    lib = _build_lib("commitops.c")
    if lib is None:
        return
    try:
        lib.scatter_add_cols.restype = ctypes.c_uint64
        lib.scatter_add_cols.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_size_t, ctypes.c_size_t]
    except AttributeError as e:
        log.debug("native commitops symbols unavailable (%s)", e)
        return

    c_float_p = ctypes.POINTER(ctypes.c_float)
    c_int64_p = ctypes.POINTER(ctypes.c_int64)

    def _scatter_add_cols(dst, src, off: int, rows, width: int) -> int:
        """dst[rows[k], :width] += src[k, off:off+width] for every k.

        dst: C-contiguous float32 (N, W>=width); src: C-contiguous float32
        (n, F); rows: int64 (n,). Returns how many k had a nonzero source
        slice."""
        return lib.scatter_add_cols(
            dst.ctypes.data_as(c_float_p), dst.strides[0] // 4,
            src.ctypes.data_as(c_float_p), src.strides[0] // 4, off,
            rows.ctypes.data_as(c_int64_p), len(rows), width)

    scatter_add_cols = _scatter_add_cols


def _bind_bindops():
    """Bulk native bind: commitops.c rebuilt with the CPython API enabled
    (`-DKTPU_HAVE_PYTHON`), bound through PyDLL so the GIL stays held while
    the C pass walks Python objects. Needs the interpreter headers; a
    machine without them (or without cc) just keeps the pure-Python
    bind_many path."""
    global bulk_bind

    import sysconfig

    inc = sysconfig.get_paths().get("include")
    if not inc or not os.path.exists(os.path.join(inc, "Python.h")):
        log.debug("native bulk bind unavailable (no Python.h); "
                  "using pure Python")
        return
    lib = _build_lib("commitops.c", stem="bindops",
                     extra_flags=("-DKTPU_HAVE_PYTHON", f"-I{inc}"),
                     loader=ctypes.PyDLL)
    if lib is None:
        return
    try:
        lib.ktpu_bulk_bind.restype = ctypes.py_object
        lib.ktpu_bulk_bind.argtypes = [
            ctypes.py_object, ctypes.py_object, ctypes.c_ssize_t,
            ctypes.py_object, ctypes.py_object, ctypes.py_object]
    except AttributeError as e:
        log.debug("native bulk bind symbols unavailable (%s)", e)
        return

    bulk_bind = lib.ktpu_bulk_bind


_bind_fnv()
_bind_commitops()
_bind_bindops()
