"""Native (C) host-plane kernels, built on first import.

The reference's runtime is compiled Go; this package gives the framework's
host plane the same native tier where it does byte-level work — currently
the FNV-1a hashing kernel behind universe interning (utils/hashing.py).

Build strategy: compile `fnv.c` with the system C compiler into the
package's `_build/` directory the first time it is imported (a few ms,
cached thereafter, keyed by source mtime) and bind it with ctypes — the
image ships g++/cc but not pybind11. Any failure (no compiler, read-only
filesystem) degrades silently to the pure-Python implementations; callers
check `fnv1a64 is not None`.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile

log = logging.getLogger(__name__)

fnv1a64 = None          # (bytes) -> int, or None when unavailable
lanes_batch = None      # (list[bytes]) -> (np.uint32[n], np.uint32[n])


def _build_and_bind():
    global fnv1a64, lanes_batch

    src = os.path.join(os.path.dirname(__file__), "fnv.c")
    build_dir = os.path.join(os.path.dirname(__file__), "_build")
    lib_path = os.path.join(build_dir, "libfnv.so")
    try:
        if (not os.path.exists(lib_path)
                or os.path.getmtime(lib_path) < os.path.getmtime(src)):
            os.makedirs(build_dir, exist_ok=True)
            # build via a temp file + rename: concurrent importers race
            fd, tmp = tempfile.mkstemp(dir=build_dir, suffix=".so")
            os.close(fd)
            subprocess.run(
                ["cc", "-O2", "-shared", "-fPIC", "-o", tmp, src],
                check=True, capture_output=True, timeout=60)
            os.replace(tmp, lib_path)
        lib = ctypes.CDLL(lib_path)
        # symbol binding stays inside the guard: a stale .so missing a
        # symbol must degrade to pure Python, not crash the import
        lib.fnv1a64.restype = ctypes.c_uint64
        lib.fnv1a64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.fnv1a64_lanes_batch.restype = None
        lib.fnv1a64_lanes_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32)]
    except (OSError, subprocess.SubprocessError, AttributeError) as e:
        log.debug("native fnv unavailable (%s); using pure Python", e)
        return

    def _fnv1a64(data: bytes) -> int:
        return lib.fnv1a64(data, len(data))

    def _lanes_batch(items: list[bytes]):
        import numpy as np

        n = len(items)
        blob = b"".join(items)
        offsets = (ctypes.c_size_t * (n + 1))()
        pos = 0
        for i, item in enumerate(items):
            offsets[i] = pos
            pos += len(item)
        offsets[n] = pos
        lo = np.empty(n, np.uint32)
        hi = np.empty(n, np.uint32)
        lib.fnv1a64_lanes_batch(
            blob, offsets, n,
            lo.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            hi.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return lo, hi

    fnv1a64 = _fnv1a64
    lanes_batch = _lanes_batch


_build_and_bind()
