/* Host-ledger scatter-add — the commit half of the scheduler's bind path.
 *
 * After every solved batch the driver mirrors the device ledger into host
 * numpy: for each committed pod, add its packed encode-row columns onto its
 * node's ledger row (kubernetes_tpu/state/statedb.py commit_batch; the host
 * analog of the scheduler cache's AssumePod accounting,
 * reference plugin/pkg/scheduler/schedulercache/cache.go:109). numpy's
 * segmented-reduction formulation (argsort + add.reduceat) measured
 * ~17 us/pod at bench scale; this loop is the same arithmetic done once,
 * in row order, at memory bandwidth.
 *
 * Returns the number of pods whose source slice had any nonzero element —
 * the callers' cheap "did this group participate at all" signal (drives
 * coverage-based dirtiness in commit_batch).
 */

#include <stddef.h>
#include <stdint.h>

#ifdef KTPU_HAVE_PYTHON
#include <Python.h>
#endif

uint64_t scatter_add_cols(float *dst, size_t dst_stride,
                          const float *src, size_t src_stride, size_t off,
                          const int64_t *rows, size_t n, size_t width) {
    uint64_t touched = 0;
    for (size_t k = 0; k < n; k++) {
        float *d = dst + (size_t)rows[k] * dst_stride;
        const float *s = src + (size_t)k * src_stride + off;
        uint64_t any = 0;
        for (size_t w = 0; w < width; w++) {
            d[w] += s[w];
            any |= (s[w] != 0.0f);
        }
        touched += any;
    }
    return touched;
}

#ifdef KTPU_HAVE_PYTHON
/* Bulk native bind — the store half of the scheduler's bind path.
 *
 * One C pass over a solved batch's Binding list replacing ObjectStore.
 * bind_many's per-pod Python loop (apiserver/store.py): key lookup,
 * not-found / already-bound checks, shallow metadata+spec shells, the
 * rebound Pod, the bucket write, and the WatchEvent fan-out buffer are
 * all built here with direct C-API calls. Semantics are bit-identical to
 * the Python loop (tests/test_native_bind.py pins ledger/store/event
 * parity); the Python wrapper keeps the WAL flush + watcher fan-out.
 *
 * Loaded via ctypes.PyDLL (GIL held throughout); called ON the event
 * loop — at ~1 us/pod a 4,096-pod batch stays far inside the 100 ms
 * loop-stall budget that testing/races.py enforces.
 */

static PyObject *s_empty_tuple;
static PyObject *s_default, *s_metadata, *s_spec, *s_status, *s_type,
    *s_kind, *s_obj, *s_resource_version, *s_node_name, *s_pod_name,
    *s_namespace, *s_target_node, *s_modified, *s_pod;

static int ensure_interned(void) {
    if (s_empty_tuple != NULL)
        return 0;
#define KTPU_INTERN(var, text) \
    if ((var = PyUnicode_InternFromString(text)) == NULL) return -1
    KTPU_INTERN(s_default, "default");
    KTPU_INTERN(s_metadata, "metadata");
    KTPU_INTERN(s_spec, "spec");
    KTPU_INTERN(s_status, "status");
    KTPU_INTERN(s_type, "type");
    KTPU_INTERN(s_kind, "kind");
    KTPU_INTERN(s_obj, "obj");
    KTPU_INTERN(s_resource_version, "resource_version");
    KTPU_INTERN(s_node_name, "node_name");
    KTPU_INTERN(s_pod_name, "pod_name");
    KTPU_INTERN(s_namespace, "namespace");
    KTPU_INTERN(s_target_node, "target_node");
    KTPU_INTERN(s_modified, "MODIFIED");
    KTPU_INTERN(s_pod, "Pod");
#undef KTPU_INTERN
    return (s_empty_tuple = PyTuple_New(0)) == NULL ? -1 : 0;
}

/* Fresh instance of `tp` whose __dict__ is `dict` (reference stolen). */
static PyObject *fresh_with_dict(PyTypeObject *tp, PyObject *dict) {
    PyObject *fresh = tp->tp_new(tp, s_empty_tuple, NULL);
    PyObject **dp;
    if (fresh == NULL || (dp = _PyObject_GetDictPtr(fresh)) == NULL) {
        Py_XDECREF(fresh);
        Py_DECREF(dict);
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_TypeError, "bulk_bind: type without __dict__");
        return NULL;
    }
    Py_XSETREF(*dp, dict);
    return fresh;
}

/* Shallow shell: same type, __dict__ copied, one attribute replaced
 * (the C analog of bind_many's shell() + one assignment). `val` is
 * borrowed. */
static PyObject *shell_with(PyObject *obj, PyObject *attr, PyObject *val) {
    PyObject **sdp = _PyObject_GetDictPtr(obj);
    PyObject *d;
    if (sdp == NULL || *sdp == NULL) {
        PyErr_SetString(PyExc_TypeError, "bulk_bind: object without __dict__");
        return NULL;
    }
    if ((d = PyDict_Copy(*sdp)) == NULL)
        return NULL;
    if (PyDict_SetItem(d, attr, val) < 0) {
        Py_DECREF(d);
        return NULL;
    }
    return fresh_with_dict(Py_TYPE(obj), d);
}

/* ktpu_bulk_bind(bucket, bindings, rv_base, WatchEvent, NotFound,
 * Conflict) -> (bound, errors, events, rv_end)
 *
 * Mirrors ObjectStore.bind_many's loop exactly: per entry either an
 * error (NotFound / Conflict, same message text) with bound=None, or a
 * rebound Pod shell written into `bucket` plus one MODIFIED WatchEvent.
 * rv_base is the store's current _rv; rv_end is what _rv must become. */
PyObject *ktpu_bulk_bind(PyObject *bucket, PyObject *bindings,
                         Py_ssize_t rv_base, PyObject *watch_event_cls,
                         PyObject *notfound_cls, PyObject *conflict_cls) {
    PyObject *bound = NULL, *errors = NULL, *events = NULL, *out = NULL;
    Py_ssize_t rv = rv_base;
    Py_ssize_t n, i;

    if (ensure_interned() < 0)
        return NULL;
    if (!PyDict_Check(bucket) || !PyList_Check(bindings)
            || !PyType_Check(watch_event_cls)) {
        PyErr_SetString(PyExc_TypeError,
                        "bulk_bind: (dict, list, int, type, ...) expected");
        return NULL;
    }
    n = PyList_GET_SIZE(bindings);
    if ((bound = PyList_New(0)) == NULL || (errors = PyList_New(0)) == NULL
            || (events = PyList_New(0)) == NULL)
        goto done;

    for (i = 0; i < n; i++) {
        PyObject *b = PyList_GET_ITEM(bindings, i);   /* borrowed */
        PyObject *name = NULL, *ns = NULL, *key = NULL, *err = NULL;
        PyObject *current, *ns_eff;
        int failed = 1;

        if ((name = PyObject_GetAttr(b, s_pod_name)) == NULL
                || (ns = PyObject_GetAttr(b, s_namespace)) == NULL)
            goto entry_done;
        switch (PyObject_IsTrue(ns)) {
        case 1:  ns_eff = ns; break;
        case 0:  ns_eff = s_default; break;
        default: goto entry_done;
        }
        if ((key = PyTuple_Pack(2, ns_eff, name)) == NULL)
            goto entry_done;
        current = PyDict_GetItemWithError(bucket, key);  /* borrowed */
        if (current == NULL) {
            PyObject *msg;
            if (PyErr_Occurred())
                goto entry_done;
            msg = PyUnicode_FromFormat("Pod %S/%S not found", ns, name);
            if (msg == NULL)
                goto entry_done;
            err = PyObject_CallFunctionObjArgs(notfound_cls, msg, NULL);
            Py_DECREF(msg);
            if (err == NULL)
                goto entry_done;
            if (PyList_Append(bound, Py_None) < 0
                    || PyList_Append(errors, err) < 0)
                goto entry_done;
            failed = 0;
        } else {
            PyObject *spec = NULL, *node = NULL;
            if ((spec = PyObject_GetAttr(current, s_spec)) == NULL)
                goto entry_done;
            node = PyObject_GetAttr(spec, s_node_name);
            if (node == NULL) {
                Py_DECREF(spec);
                goto entry_done;
            }
            switch (PyObject_IsTrue(node)) {
            case 1: {
                PyObject *msg = PyUnicode_FromFormat(
                    "pod %S/%S already bound to %S", ns, name, node);
                Py_DECREF(spec);
                Py_DECREF(node);
                if (msg == NULL)
                    goto entry_done;
                err = PyObject_CallFunctionObjArgs(conflict_cls, msg, NULL);
                Py_DECREF(msg);
                if (err == NULL)
                    goto entry_done;
                if (PyList_Append(bound, Py_None) < 0
                        || PyList_Append(errors, err) < 0)
                    goto entry_done;
                failed = 0;
                break;
            }
            case 0: {
                PyObject *rvstr = NULL, *meta = NULL, *spec2 = NULL;
                PyObject *target = NULL, *status = NULL, *stored = NULL;
                PyObject *d = NULL, *ev = NULL, *rvlong = NULL;
                Py_DECREF(node);
                rv += 1;
                if ((rvstr = PyUnicode_FromFormat("%zd", rv)) == NULL
                        || (meta = PyObject_GetAttr(current, s_metadata))
                            == NULL) {
                    Py_XDECREF(rvstr);
                    Py_DECREF(spec);
                    goto entry_done;
                }
                Py_SETREF(meta, shell_with(meta, s_resource_version, rvstr));
                Py_DECREF(rvstr);
                target = meta ? PyObject_GetAttr(b, s_target_node) : NULL;
                spec2 = target ? shell_with(spec, s_node_name, target) : NULL;
                Py_DECREF(spec);
                Py_XDECREF(target);
                status = spec2 ? PyObject_GetAttr(current, s_status) : NULL;
                if (status == NULL || (d = PyDict_New()) == NULL
                        || PyDict_SetItem(d, s_metadata, meta) < 0
                        || PyDict_SetItem(d, s_spec, spec2) < 0
                        || PyDict_SetItem(d, s_status, status) < 0) {
                    Py_XDECREF(d);
                    Py_XDECREF(meta);
                    Py_XDECREF(spec2);
                    Py_XDECREF(status);
                    goto entry_done;
                }
                Py_DECREF(meta);
                Py_DECREF(spec2);
                Py_DECREF(status);
                stored = fresh_with_dict(Py_TYPE(current), d);
                if (stored == NULL)
                    goto entry_done;
                if (PyDict_SetItem(bucket, key, stored) < 0
                        || (rvlong = PyLong_FromSsize_t(rv)) == NULL
                        || (d = PyDict_New()) == NULL) {
                    Py_XDECREF(rvlong);
                    Py_DECREF(stored);
                    goto entry_done;
                }
                if (PyDict_SetItem(d, s_type, s_modified) < 0
                        || PyDict_SetItem(d, s_kind, s_pod) < 0
                        || PyDict_SetItem(d, s_obj, stored) < 0
                        || PyDict_SetItem(d, s_resource_version, rvlong) < 0) {
                    Py_DECREF(d);
                    Py_DECREF(rvlong);
                    Py_DECREF(stored);
                    goto entry_done;
                }
                Py_DECREF(rvlong);
                ev = fresh_with_dict((PyTypeObject *)watch_event_cls, d);
                if (ev == NULL) {
                    Py_DECREF(stored);
                    goto entry_done;
                }
                if (PyList_Append(events, ev) < 0
                        || PyList_Append(bound, stored) < 0
                        || PyList_Append(errors, Py_None) < 0) {
                    Py_DECREF(ev);
                    Py_DECREF(stored);
                    goto entry_done;
                }
                Py_DECREF(ev);
                Py_DECREF(stored);
                failed = 0;
                break;
            }
            default:
                Py_DECREF(spec);
                Py_DECREF(node);
                goto entry_done;
            }
        }
entry_done:
        Py_XDECREF(name);
        Py_XDECREF(ns);
        Py_XDECREF(key);
        Py_XDECREF(err);
        if (failed)
            goto done;
    }
    out = Py_BuildValue("(OOOn)", bound, errors, events, rv);
done:
    Py_XDECREF(bound);
    Py_XDECREF(errors);
    Py_XDECREF(events);
    return out;
}
#endif /* KTPU_HAVE_PYTHON */
