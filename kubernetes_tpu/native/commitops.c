/* Host-ledger scatter-add — the commit half of the scheduler's bind path.
 *
 * After every solved batch the driver mirrors the device ledger into host
 * numpy: for each committed pod, add its packed encode-row columns onto its
 * node's ledger row (kubernetes_tpu/state/statedb.py commit_batch; the host
 * analog of the scheduler cache's AssumePod accounting,
 * reference plugin/pkg/scheduler/schedulercache/cache.go:109). numpy's
 * segmented-reduction formulation (argsort + add.reduceat) measured
 * ~17 us/pod at bench scale; this loop is the same arithmetic done once,
 * in row order, at memory bandwidth.
 *
 * Returns the number of pods whose source slice had any nonzero element —
 * the callers' cheap "did this group participate at all" signal (drives
 * coverage-based dirtiness in commit_batch).
 */

#include <stddef.h>
#include <stdint.h>

uint64_t scatter_add_cols(float *dst, size_t dst_stride,
                          const float *src, size_t src_stride, size_t off,
                          const int64_t *rows, size_t n, size_t width) {
    uint64_t touched = 0;
    for (size_t k = 0; k < n; k++) {
        float *d = dst + (size_t)rows[k] * dst_stride;
        const float *s = src + (size_t)k * src_stride + off;
        uint64_t any = 0;
        for (size_t w = 0; w < width; w++) {
            d[w] += s[w];
            any |= (s[w] != 0.0f);
        }
        touched += any;
    }
    return touched;
}
