/* FNV-1a 64-bit — the host-plane hashing kernel behind universe interning.
 *
 * Every string the device ever compares (labels, selector terms, taints,
 * node names) is hashed exactly once on the host at encode time
 * (kubernetes_tpu/utils/hashing.py); this is that loop in C. The reference
 * runtime is compiled Go — its map hashing and string compares are native
 * code — so the framework's encode path gets the same treatment rather
 * than a Python byte loop.
 *
 * Exposed via ctypes (no pybind11 in the image); see native/__init__.py
 * for the build-on-first-import harness and the pure-Python fallback.
 */

#include <stddef.h>
#include <stdint.h>

#define FNV64_OFFSET 0xCBF29CE484222325ULL
#define FNV64_PRIME 0x100000001B3ULL

uint64_t fnv1a64(const unsigned char *data, size_t len) {
    uint64_t h = FNV64_OFFSET;
    for (size_t i = 0; i < len; i++) {
        h ^= (uint64_t)data[i];
        h *= FNV64_PRIME;
    }
    return h;
}

/* Batch API: hash n strings packed back-to-back in `data`, with
 * offsets[i]..offsets[i+1] delimiting string i (offsets has n+1 entries).
 * Writes the 0->1-remapped uint32 lanes the device layout wants. */
void fnv1a64_lanes_batch(const unsigned char *data, const size_t *offsets,
                         size_t n, uint32_t *lo_out, uint32_t *hi_out) {
    for (size_t i = 0; i < n; i++) {
        uint64_t h = fnv1a64(data + offsets[i], offsets[i + 1] - offsets[i]);
        uint32_t lo = (uint32_t)(h & 0xFFFFFFFFULL);
        uint32_t hi = (uint32_t)(h >> 32);
        lo_out[i] = lo ? lo : 1;
        hi_out[i] = hi ? hi : 1;
    }
}
