"""Scheduler policy: which predicates/priorities run, with what weights.

The analog of the reference's Policy file API
(plugin/pkg/scheduler/api/types.go:38-50: `Policy{Predicates, Priorities,
ExtenderConfigs}` loadable from JSON) and the default algorithm provider
(algorithmprovider/defaults/defaults.go:73-231). The policy is frozen and
hashable so it can be a static jit argument: changing policy recompiles the
device program, matching the reference's construct-scheduler-from-policy flow
(factory.go CreateFromConfig).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubernetes_tpu.state.layout import (
    DEFAULT_MAX_AZURE_DISK_VOLUMES,
    DEFAULT_MAX_EBS_VOLUMES,
    DEFAULT_MAX_GCE_PD_VOLUMES,
)

# Predicate names follow the reference registry (factory/plugins.go).
# "GeneralPredicates" expands to resources+host+ports+selector
# (predicates.go:900).
DEFAULT_PREDICATES: tuple[str, ...] = (
    "GeneralPredicates",
    "PodToleratesNodeTaints",
    "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure",
    "CheckNodeCondition",
)

DEFAULT_PRIORITIES: tuple[tuple[str, int], ...] = (
    ("LeastRequestedPriority", 1),
    ("BalancedResourceAllocation", 1),
    ("TaintTolerationPriority", 1),
)

KNOWN_PREDICATES = frozenset({
    "GeneralPredicates", "PodFitsResources", "PodFitsHost", "PodFitsHostPorts",
    "MatchNodeSelector", "PodToleratesNodeTaints", "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure", "CheckNodeCondition", "MatchInterPodAffinity",
    # registry aliases (defaults.go:73-87)
    "PodFitsPorts", "HostName",
    # volume predicates (defaults.go:120-155, 178-184)
    "NoDiskConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount", "NoVolumeZoneConflict", "NoVolumeNodeConflict",
})

KNOWN_PRIORITIES = frozenset({
    "LeastRequestedPriority", "MostRequestedPriority",
    "BalancedResourceAllocation", "TaintTolerationPriority", "EqualPriority",
    "NodeAffinityPriority", "InterPodAffinityPriority",
})


@dataclass(frozen=True)
class Policy:
    predicates: tuple[str, ...] = DEFAULT_PREDICATES
    priorities: tuple[tuple[str, int], ...] = DEFAULT_PRIORITIES
    # HardPodAffinitySymmetricWeight (api/types.go:50; default 1): the score
    # granted per existing pod whose *required* affinity term matches the
    # incoming pod, in InterPodAffinityPriority's symmetric pass.
    hard_pod_affinity_weight: int = 1
    # MaxPDVolumeCount limits (defaults.go:120-155; env KUBE_MAX_PD_VOLS
    # override applied by `with_env_overrides` at scheduler construction)
    max_ebs_volumes: int = DEFAULT_MAX_EBS_VOLUMES
    max_gce_pd_volumes: int = DEFAULT_MAX_GCE_PD_VOLUMES
    max_azure_disk_volumes: int = DEFAULT_MAX_AZURE_DISK_VOLUMES

    def __post_init__(self):
        unknown = set(self.predicates) - KNOWN_PREDICATES
        if unknown:
            raise ValueError(f"unknown predicates: {sorted(unknown)}")
        unknown = {n for n, _ in self.priorities} - KNOWN_PRIORITIES
        if unknown:
            raise ValueError(f"unknown priorities: {sorted(unknown)}")
        for n, w in self.priorities:
            # the reference registry requires positive weights
            # (factory/plugins.go validatePriorityOrDie)
            if w <= 0:
                raise ValueError(f"priority {n} must have a positive weight, got {w}")

    # --- convenience views used by the solver ---
    def has_predicate(self, *names: str) -> bool:
        return any(n in self.predicates for n in names)

    def attach_maxes(self) -> tuple[tuple[int, int], ...]:
        """Static ((VolType, limit), ...) for the configured MaxPDVolumeCount
        predicates."""
        from kubernetes_tpu.state.layout import VolType

        out = []
        if "MaxEBSVolumeCount" in self.predicates:
            out.append((VolType.EBS, self.max_ebs_volumes))
        if "MaxGCEPDVolumeCount" in self.predicates:
            out.append((VolType.GCE, self.max_gce_pd_volumes))
        if "MaxAzureDiskVolumeCount" in self.predicates:
            out.append((VolType.AZURE, self.max_azure_disk_volumes))
        return tuple(out)

    def with_env_overrides(self) -> "Policy":
        """Apply KUBE_MAX_PD_VOLS (defaults.go getMaxVols) to every attach
        limit, like the reference's predicate factories."""
        import os
        from dataclasses import replace

        raw = os.environ.get("KUBE_MAX_PD_VOLS")
        if not raw:
            return self
        try:
            limit = int(raw)
        except ValueError:
            return self
        if limit <= 0:
            return self
        return replace(self, max_ebs_volumes=limit, max_gce_pd_volumes=limit,
                       max_azure_disk_volumes=limit)

    def weight(self, name: str) -> int:
        for n, w in self.priorities:
            if n == name:
                return w
        return 0

    @classmethod
    def from_json(cls, text: str) -> "Policy":
        """Parse the reference's JSON policy schema
        (plugin/pkg/scheduler/api/v1/types.go): {"predicates": [{"name": ..}],
        "priorities": [{"name": .., "weight": ..}]}."""
        d = json.loads(text)
        preds = tuple(p["name"] for p in d.get("predicates") or []) or DEFAULT_PREDICATES
        prios = tuple(
            (p["name"], int(p.get("weight", 1))) for p in d.get("priorities") or []
        ) or DEFAULT_PRIORITIES
        return cls(predicates=preds, priorities=prios,
                   hard_pod_affinity_weight=int(
                       d.get("hardPodAffinitySymmetricWeight", 1)))

    def to_json(self) -> str:
        return json.dumps({
            "kind": "Policy",
            "apiVersion": "v1",
            "predicates": [{"name": n} for n in self.predicates],
            "priorities": [{"name": n, "weight": w} for n, w in self.priorities],
            "hardPodAffinitySymmetricWeight": self.hard_pod_affinity_weight,
        })


DEFAULT_POLICY = Policy()
