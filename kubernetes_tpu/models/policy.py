"""Scheduler policy: which predicates/priorities run, with what weights.

The analog of the reference's Policy file API
(plugin/pkg/scheduler/api/types.go:38-50: `Policy{Predicates, Priorities,
ExtenderConfigs}` loadable from JSON) and the default algorithm provider
(algorithmprovider/defaults/defaults.go:73-231). The policy is frozen and
hashable so it can be a static jit argument: changing policy recompiles the
device program, matching the reference's construct-scheduler-from-policy flow
(factory.go CreateFromConfig).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubernetes_tpu.state.layout import (
    DEFAULT_MAX_AZURE_DISK_VOLUMES,
    DEFAULT_MAX_EBS_VOLUMES,
    DEFAULT_MAX_GCE_PD_VOLUMES,
)

# Predicate names follow the reference registry (factory/plugins.go).
# "GeneralPredicates" expands to resources+host+ports+selector
# (predicates.go:900). The defaults are the reference's default algorithm
# provider sets (defaultPredicates/defaultPriorities, defaults.go:118-235).
DEFAULT_PREDICATES: tuple[str, ...] = (
    "NoVolumeZoneConflict",
    "MaxEBSVolumeCount",
    "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount",
    "MatchInterPodAffinity",
    "NoDiskConflict",
    "GeneralPredicates",
    "PodToleratesNodeTaints",
    "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure",
    "CheckNodeCondition",
    "NoVolumeNodeConflict",
)

DEFAULT_PRIORITIES: tuple[tuple[str, int], ...] = (
    ("SelectorSpreadPriority", 1),
    ("InterPodAffinityPriority", 1),
    ("LeastRequestedPriority", 1),
    ("BalancedResourceAllocation", 1),
    ("NodePreferAvoidPodsPriority", 10000),
    ("NodeAffinityPriority", 1),
    ("TaintTolerationPriority", 1),
)

KNOWN_PREDICATES = frozenset({
    "GeneralPredicates", "PodFitsResources", "PodFitsHost", "PodFitsHostPorts",
    "MatchNodeSelector", "PodToleratesNodeTaints", "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure", "CheckNodeCondition", "MatchInterPodAffinity",
    # registry aliases (defaults.go:73-87)
    "PodFitsPorts", "HostName",
    # volume predicates (defaults.go:120-155, 178-184)
    "NoDiskConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount", "NoVolumeZoneConflict", "NoVolumeNodeConflict",
})

KNOWN_PRIORITIES = frozenset({
    "LeastRequestedPriority", "MostRequestedPriority",
    "BalancedResourceAllocation", "TaintTolerationPriority", "EqualPriority",
    "NodeAffinityPriority", "InterPodAffinityPriority",
    "SelectorSpreadPriority", "ServiceSpreadingPriority",
    "NodePreferAvoidPodsPriority", "ImageLocalityPriority",
})


@dataclass(frozen=True)
class ExtenderConfig:
    """One configured external extender (api/types.go:129 ExtenderConfig):
    the scheduler POSTs ExtenderArgs to urlPrefix/verb after its own
    evaluation (core/extender.go:100 Filter, :143 Prioritize)."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    http_timeout: float = 5.0  # extender.go:36 DefaultExtenderTimeout


@dataclass(frozen=True)
class Policy:
    predicates: tuple[str, ...] = DEFAULT_PREDICATES
    priorities: tuple[tuple[str, int], ...] = DEFAULT_PRIORITIES
    # HardPodAffinitySymmetricWeight (api/types.go:50; default 1): the score
    # granted per existing pod whose *required* affinity term matches the
    # incoming pod, in InterPodAffinityPriority's symmetric pass.
    hard_pod_affinity_weight: int = 1
    # MaxPDVolumeCount limits (defaults.go:120-155; env KUBE_MAX_PD_VOLS
    # override applied by `with_env_overrides` at scheduler construction)
    max_ebs_volumes: int = DEFAULT_MAX_EBS_VOLUMES
    max_gce_pd_volumes: int = DEFAULT_MAX_GCE_PD_VOLUMES
    max_azure_disk_volumes: int = DEFAULT_MAX_AZURE_DISK_VOLUMES
    # argument-carrying registrations (api/v1/types.go PredicateArgument /
    # PriorityArgument): custom-named entries whose behavior comes from args.
    # (name, (labels...), presence) — CheckNodeLabelPresence instances
    label_presence_predicates: tuple = ()
    # (name, (labels...)) — ServiceAffinity instances
    service_affinity_predicates: tuple = ()
    # (name, label, presence) — NodeLabelPriority instances (weight in
    # `priorities` under the same name)
    label_priorities: tuple = ()
    # (name, label) — ServiceAntiAffinityPriority instances
    service_anti_priorities: tuple = ()
    # ExtenderConfigs (api/types.go:129): external extenders the driver
    # calls after device evaluation (core/extender.go:211-228,381-401)
    extenders: tuple = ()

    def __post_init__(self):
        arg_preds = ({n for n, _, _ in self.label_presence_predicates}
                     | {n for n, _ in self.service_affinity_predicates})
        unknown = set(self.predicates) - KNOWN_PREDICATES - arg_preds
        if unknown:
            raise ValueError(f"unknown predicates: {sorted(unknown)}")
        arg_prios = ({n for n, _, _ in self.label_priorities}
                     | {n for n, _ in self.service_anti_priorities})
        unknown = {n for n, _ in self.priorities} - KNOWN_PRIORITIES - arg_prios
        if unknown:
            raise ValueError(f"unknown priorities: {sorted(unknown)}")
        for n, w in self.priorities:
            # the reference registry requires positive weights
            # (factory/plugins.go validatePriorityOrDie)
            if w <= 0:
                raise ValueError(f"priority {n} must have a positive weight, got {w}")

    # --- convenience views used by the solver ---
    def has_predicate(self, *names: str) -> bool:
        return any(n in self.predicates for n in names)

    def attach_maxes(self) -> tuple[tuple[int, int], ...]:
        """Static ((VolType, limit), ...) for the configured MaxPDVolumeCount
        predicates."""
        from kubernetes_tpu.state.layout import VolType

        out = []
        if "MaxEBSVolumeCount" in self.predicates:
            out.append((VolType.EBS, self.max_ebs_volumes))
        if "MaxGCEPDVolumeCount" in self.predicates:
            out.append((VolType.GCE, self.max_gce_pd_volumes))
        if "MaxAzureDiskVolumeCount" in self.predicates:
            out.append((VolType.AZURE, self.max_azure_disk_volumes))
        return tuple(out)

    def with_env_overrides(self) -> "Policy":
        """Apply KUBE_MAX_PD_VOLS (defaults.go getMaxVols) to every attach
        limit, like the reference's predicate factories."""
        import os
        from dataclasses import replace

        raw = os.environ.get("KUBE_MAX_PD_VOLS")
        if not raw:
            return self
        try:
            limit = int(raw)
        except ValueError:
            return self
        if limit <= 0:
            return self
        return replace(self, max_ebs_volumes=limit, max_gce_pd_volumes=limit,
                       max_azure_disk_volumes=limit)

    def weight(self, name: str) -> int:
        for n, w in self.priorities:
            if n == name:
                return w
        return 0

    @classmethod
    def from_json(cls, text: str) -> "Policy":
        """Parse the reference's JSON policy schema
        (plugin/pkg/scheduler/api/v1/types.go): {"predicates": [{"name": ..,
        "argument": ..}], "priorities": [{"name": .., "weight": ..,
        "argument": ..}]} with labelsPresence / serviceAffinity /
        labelPreference / serviceAntiAffinity arguments."""
        d = json.loads(text)
        preds, label_presence, svc_aff = [], [], []
        for p in d.get("predicates") or []:
            name = p["name"]
            preds.append(name)
            arg = p.get("argument") or {}
            if "labelsPresence" in arg:
                lp = arg["labelsPresence"] or {}
                label_presence.append((name, tuple(lp.get("labels") or ()),
                                       bool(lp.get("presence"))))
            elif "serviceAffinity" in arg:
                sa = arg["serviceAffinity"] or {}
                svc_aff.append((name, tuple(sa.get("labels") or ())))
        prios, label_prios, svc_anti = [], [], []
        for p in d.get("priorities") or []:
            name = p["name"]
            prios.append((name, int(p.get("weight", 1))))
            arg = p.get("argument") or {}
            if "labelPreference" in arg:
                lp = arg["labelPreference"] or {}
                label_prios.append((name, lp.get("label", ""),
                                    bool(lp.get("presence"))))
            elif "serviceAntiAffinity" in arg:
                sa = arg["serviceAntiAffinity"] or {}
                svc_anti.append((name, sa.get("label", "")))
        extenders = tuple(
            ExtenderConfig(
                url_prefix=e.get("urlPrefix", ""),
                filter_verb=e.get("filterVerb", "") or "",
                prioritize_verb=e.get("prioritizeVerb", "") or "",
                weight=int(e.get("weight", 1) or 1),
                node_cache_capable=bool(e.get("nodeCacheCapable", False)),
                http_timeout=float(e.get("httpTimeout", 5.0) or 5.0))
            for e in d.get("extenders") or [])
        return cls(predicates=tuple(preds) or DEFAULT_PREDICATES,
                   priorities=tuple(prios) or DEFAULT_PRIORITIES,
                   hard_pod_affinity_weight=int(
                       d.get("hardPodAffinitySymmetricWeight", 1)),
                   label_presence_predicates=tuple(label_presence),
                   service_affinity_predicates=tuple(svc_aff),
                   label_priorities=tuple(label_prios),
                   service_anti_priorities=tuple(svc_anti),
                   extenders=extenders)

    def to_json(self) -> str:
        pred_args = {n: {"labelsPresence": {"labels": list(labels),
                                            "presence": presence}}
                     for n, labels, presence in self.label_presence_predicates}
        pred_args.update({n: {"serviceAffinity": {"labels": list(labels)}}
                          for n, labels in self.service_affinity_predicates})
        prio_args = {n: {"labelPreference": {"label": label,
                                             "presence": presence}}
                     for n, label, presence in self.label_priorities}
        prio_args.update({n: {"serviceAntiAffinity": {"label": label}}
                          for n, label in self.service_anti_priorities})
        out = {
            "kind": "Policy",
            "apiVersion": "v1",
            "predicates": [
                {"name": n, **({"argument": pred_args[n]} if n in pred_args else {})}
                for n in self.predicates],
            "priorities": [
                {"name": n, "weight": w,
                 **({"argument": prio_args[n]} if n in prio_args else {})}
                for n, w in self.priorities],
            "hardPodAffinitySymmetricWeight": self.hard_pod_affinity_weight,
        }
        if self.extenders:
            out["extenders"] = [{
                "urlPrefix": e.url_prefix,
                **({"filterVerb": e.filter_verb} if e.filter_verb else {}),
                **({"prioritizeVerb": e.prioritize_verb}
                   if e.prioritize_verb else {}),
                "weight": e.weight,
                "nodeCacheCapable": e.node_cache_capable,
                "httpTimeout": e.http_timeout,
            } for e in self.extenders]
        return json.dumps(out)

    def service_affinity_labels(self) -> tuple:
        """Union of all configured ServiceAffinity labels (for the encode
        context)."""
        out: list = []
        for name, labels in self.service_affinity_predicates:
            if name in self.predicates:
                out.extend(labels)
        return tuple(dict.fromkeys(out))


DEFAULT_POLICY = Policy()


def active_label_priorities(policy: Policy) -> tuple:
    """((label, presence, weight), ...) for configured NodeLabel priorities."""
    weights = dict(policy.priorities)
    return tuple((label, presence, weights[name])
                 for name, label, presence in policy.label_priorities
                 if weights.get(name))


def active_service_anti(policy: Policy) -> tuple:
    """((label, weight), ...) for configured ServiceAntiAffinity priorities."""
    weights = dict(policy.priorities)
    return tuple((label, weights[name])
                 for name, label in policy.service_anti_priorities
                 if weights.get(name))


def active_label_presence(policy: Policy) -> tuple:
    """(((labels...), presence), ...) for configured CheckNodeLabelPresence
    instances."""
    return tuple((labels, presence)
                 for name, labels, presence in policy.label_presence_predicates
                 if name in policy.predicates)


def build_policy_rows(policy: Policy, table, caps):
    """Device rows for the argument-carrying registrations: each configured
    label becomes an interned Exists requirement (membership via the shared
    requirement universe) and each ServiceAntiAffinity label a topology slot.
    Returns None when the policy carries no arguments (the common case, and
    a stable jit signature)."""
    import numpy as np

    from kubernetes_tpu.state.layout import ReqOp

    lp = active_label_presence(policy)
    nl = active_label_priorities(policy)
    sa = active_service_anti(policy)
    if not (lp or nl or sa):
        return None
    ur = caps.req_universe
    pres = np.zeros((ur,), np.float32)
    absent = np.zeros((ur,), np.float32)
    npres = 0
    for labels, presence in lp:
        for label in labels:
            rid = table.intern_requirement(label, ReqOp.EXISTS, ())
            if presence:
                if pres[rid] == 0:
                    npres += 1
                pres[rid] = 1.0
            else:
                absent[rid] = 1.0
    nlp = np.zeros((len(nl), ur), np.float32)
    for i, (label, _presence, _w) in enumerate(nl):
        nlp[i, table.intern_requirement(label, ReqOp.EXISTS, ())] = 1.0
    slots = np.asarray([table.intern_topo_key(label) for label, _w in sa],
                       np.int32)
    return PolicyRows(pres_onehot=pres, pres_count=np.float32(npres),
                      abs_onehot=absent, nlp_onehot=nlp, svcanti_slot=slots)


from flax import struct as _struct  # noqa: E402


@_struct.dataclass
class PolicyRows:
    """Interned device rows for argument-carrying policy registrations
    (passed to schedule_batch alongside the static Policy)."""

    pres_onehot: object   # f32[UR] labels that must exist
    pres_count: object    # f32 scalar
    abs_onehot: object    # f32[UR] labels that must not exist
    nlp_onehot: object    # f32[KN, UR] one Exists row per NodeLabel prio
    svcanti_slot: object  # i32[KS] topo slot per ServiceAntiAffinity prio
