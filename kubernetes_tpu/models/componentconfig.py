"""componentconfig: versioned per-binary configuration objects.

Analog of pkg/apis/componentconfig (reference types.go:562-600 for
KubeSchedulerConfiguration): each binary's knobs are an API-shaped object
— kind/apiVersion + defaulted fields — loadable from a JSON file via
`--config`, with explicit command-line flags taking precedence (the
reference's flag/config layering, SURVEY.md §5.6a-b). Unknown fields are
an error: a typo'd knob must not silently run with defaults.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, fields


class ConfigError(ValueError):
    pass


# field annotations are strings under `from __future__ import annotations`
_TYPE_OK = {"int": (int,), "float": (int, float), "bool": (bool,),
            "str": (str,)}


def _load(cls, kind: str, path: str):
    with open(path, encoding="utf-8") as f:
        try:
            data = json.load(f)
        except ValueError as e:
            raise ConfigError(f"{path}: not JSON: {e}") from None
    if not isinstance(data, dict):
        raise ConfigError(f"{path}: top level must be an object, "
                          f"got {type(data).__name__}")
    got_kind = data.pop("kind", kind)
    if got_kind != kind:
        raise ConfigError(f"{path}: kind {got_kind!r}, want {kind!r}")
    data.pop("apiVersion", None)
    by_name = {f.name: f for f in fields(cls)}
    unknown = sorted(set(data) - set(by_name))
    if unknown:
        raise ConfigError(f"{path}: unknown field(s) {unknown}; "
                          f"known: {sorted(by_name)}")
    for name, value in data.items():
        declared = str(by_name[name].type)
        want = _TYPE_OK.get(declared)
        if want is not None:
            # bool is an int subclass: reject bools for numeric knobs
            if not isinstance(value, want) or (
                    declared != "bool" and isinstance(value, bool)):
                raise ConfigError(
                    f"{path}: field {name!r} wants {declared}, got "
                    f"{type(value).__name__} ({value!r})")
        elif declared.startswith("dict") and not isinstance(value, dict):
            raise ConfigError(f"{path}: field {name!r} wants an object, "
                              f"got {type(value).__name__}")
    return cls(**data)


@dataclass
class KubeSchedulerConfiguration:
    """componentconfig/v1alpha1 KubeSchedulerConfiguration subset
    (reference types.go:562-600: SchedulerName, AlgorithmSource->policy
    file, LeaderElection, HealthzBindAddress ports)."""

    schedulerName: str = "default-scheduler"
    policyConfigFile: str = ""
    leaderElect: bool = False
    lockObjectName: str = "kube-scheduler"
    lockObjectNamespace: str = "kube-system"
    port: int = 10251
    numNodes: int = 1024
    batchPods: int = 256
    featureGates: dict[str, bool] = field(default_factory=dict)

    kind = "KubeSchedulerConfiguration"
    api_version = "componentconfig/v1alpha1"

    @classmethod
    def from_file(cls, path: str) -> "KubeSchedulerConfiguration":
        return _load(cls, cls.kind, path)


@dataclass
class KubeControllerManagerConfiguration:
    """componentconfig KubeControllerManagerConfiguration subset
    (reference types.go KubeControllerManagerConfiguration: controllers
    toggle list, leader election, node-monitor knobs)."""

    leaderElect: bool = False
    lockObjectName: str = "kube-controller-manager"
    lockObjectNamespace: str = "kube-system"
    nodeMonitorPeriod: float = 5.0
    nodeMonitorGracePeriod: float = 40.0
    podEvictionTimeout: float = 300.0
    terminatedPodGCThreshold: int = 12500
    featureGates: dict[str, bool] = field(default_factory=dict)

    kind = "KubeControllerManagerConfiguration"
    api_version = "componentconfig/v1alpha1"

    @classmethod
    def from_file(cls, path: str) -> "KubeControllerManagerConfiguration":
        return _load(cls, cls.kind, path)


def explicit_dests(parser, argv) -> set[str]:
    """The dests the user actually typed on the command line. Parsing a
    second time with every default suppressed leaves only provided flags
    in the namespace — value-equality against defaults would wrongly let
    the config override an explicit flag that happens to equal the
    default (`--port 10251 --config …` must keep 10251)."""
    import argparse

    saved = [(a, a.default) for a in parser._actions]
    try:
        for a in parser._actions:
            a.default = argparse.SUPPRESS
        ns, _ = parser.parse_known_args(argv)
        return set(vars(ns))
    finally:
        for a, d in saved:
            a.default = d


def apply_config_to_args(config, args, explicit: set[str],
                         mapping: dict[str, str]) -> None:
    """Layering: a config-file value applies only where the flag was NOT
    explicitly provided — explicit flags win (the reference applies flags
    after config deserialization)."""
    for cfg_field, arg_name in mapping.items():
        if arg_name not in explicit:
            setattr(args, arg_name, getattr(config, cfg_field))
