"""Spreading priorities: SelectorSpread and ServiceAntiAffinity.

Re-expresses CalculateSpreadPriority (selector_spreading.go:100-188) and
CalculateAntiAffinityPriority (:210-270) over the interned pod-selector
universe: the pod carries ONE union entry id (match-any over its controller
selectors, built in state/spreading.py), per-node matching-pod counts live in
the scan-carried AffinityLedger (so earlier in-batch assignments are visible,
matching the serial assume semantics), and zone aggregation rides the virtual
GetZoneKey topology slot (layout.TOPO_SPREAD_ZONE).

Both reduces run over the *filtered* node list (PrioritizeNodes receives
filteredNodes, generic_scheduler.go:121) — hence the `feasible` mask inputs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.interpod import AffinityLedger
from kubernetes_tpu.ops.priorities import FLOOR_EPS
from kubernetes_tpu.state.cluster_state import ClusterState
from kubernetes_tpu.state.layout import MAX_PRIORITY, TOPO_SPREAD_ZONE

# zoneWeighting (selector_spreading.go:36)
ZONE_WEIGHT = 2.0 / 3.0


def selector_spread(state: ClusterState, spread_q, ledger: AffinityLedger,
                    feasible, domain_universe: int,
                    topo_onehot=None) -> jnp.ndarray:
    """f32[N] SelectorSpread scores for one pod (spread_q: traced i32 scalar,
    -1 = no matching controllers -> uniform MaxPriority,
    selector_spreading.go:157 initializes every fScore to MaxPriority and
    the selector-less path never lowers it)."""
    qc = jnp.clip(spread_q, 0)
    counts = ledger.podsel_count[:, qc]                   # f32[N]
    masked = jnp.where(feasible, counts, 0.0)
    max_node = jnp.max(masked)

    dom = state.topology[:, TOPO_SPREAD_ZONE]             # i32[N]
    has_zone = dom >= 0
    onehot = (jax.nn.one_hot(dom, domain_universe)        # [N, D], -1 -> 0row
              if topo_onehot is None else topo_onehot[TOPO_SPREAD_ZONE])
    zc = onehot.T @ masked                                # [D] per-zone counts
    zc_node = onehot @ zc                                 # [N]
    have_zones = jnp.any(feasible & has_zone)
    max_zone = jnp.max(zc)

    node_score = jnp.where(
        max_node > 0,
        MAX_PRIORITY * (max_node - counts) / jnp.maximum(max_node, 1.0),
        float(MAX_PRIORITY))
    # maxCountByZone == 0 with haveZones is 0/0 in the reference (undefined
    # int(NaN)); deterministically: all zones equally empty -> MaxPriority
    zone_score = jnp.where(
        max_zone > 0,
        MAX_PRIORITY * (max_zone - zc_node) / jnp.maximum(max_zone, 1.0),
        float(MAX_PRIORITY))
    blended = jnp.where(
        have_zones & has_zone,
        node_score * (1.0 - ZONE_WEIGHT) + ZONE_WEIGHT * zone_score,
        node_score)
    score = jnp.trunc(blended + FLOOR_EPS)
    return jnp.where(spread_q < 0, float(MAX_PRIORITY), score)


def service_anti_affinity(state: ClusterState, svcanti_q, total,
                          ledger: AffinityLedger, feasible, slot,
                          domain_universe: int, topo_onehot=None) -> jnp.ndarray:
    """f32[N] ServiceAntiAffinity scores for one pod and one configured
    label (slot: traced i32 from PolicyRows). Labeled nodes score by how few
    same-service pods share their label value — counted over feasible
    labeled nodes only (getNodeClassificationByLabels runs on the filtered
    list, selector_spreading.go:232); unlabeled nodes score 0."""
    qc = jnp.clip(svcanti_q, 0)
    counts = jnp.where(svcanti_q >= 0, ledger.podsel_count[:, qc], 0.0)
    dom = state.topology[:, slot]                         # i32[N]
    labeled = dom >= 0
    contrib = jnp.where(feasible & labeled, counts, 0.0)
    onehot = (jax.nn.one_hot(dom, domain_universe)
              if topo_onehot is None else topo_onehot[slot])
    per_dom = onehot.T @ contrib
    dom_count = onehot @ per_dom                          # [N]
    score = jnp.where(
        total > 0,
        jnp.trunc(MAX_PRIORITY * (total - dom_count)
                  / jnp.maximum(total, 1.0) + FLOOR_EPS),
        float(MAX_PRIORITY))
    # in-batch assume increments can push dom_count past the encode-time
    # total; the reference recomputes both from the same snapshot and can
    # never go negative — clamp to preserve that invariant
    score = jnp.maximum(score, 0.0)
    return jnp.where(labeled, score, 0.0)
