"""Pallas TPU kernels for the solver's hot tensor ops.

`fused_static_mask` computes the default-policy static feasibility
conjunction — selector membership, hard-taint toleration, node conditions,
host-name pinning, validity — for a whole (P × N) batch in ONE pass over
node tiles: both matmuls hit the MXU from VMEM and every comparison/AND
fuses behind them, so the (P, N) intermediates that the composed XLA
kernels materialize in HBM (selector counts, taint violations, per-check
masks) never leave the chip. Mirrors ops/predicates.py semantics exactly
(predicates.go:686, :1241, :1306 and the lister's unschedulable filter);
parity is pinned against the XLA path in tests (interpret mode off-TPU).

Opt-in: the solver uses it when KTPU_PALLAS=1 and the policy's static set
matches what the kernel fuses (solver._use_fused_static). Node-affinity
terms stay in XLA and AND in afterwards — they ride a (T × UR × N) contraction
the fused two-matmul shape doesn't cover.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_tpu.state.layout import Condition

# tile sizes trade VMEM footprint against grid-step count; at (128, 256)
# a 16k-node / 4k-pod mask is (4096/128)*(16384/256) = 2048 grid steps
# with ~0.5 MB of VMEM-resident operands per step (512-wide node tiles
# tripped the scoped-vmem limit under Mosaic's double buffering)
NODE_TILE = 256
POD_TILE = 128

_HARD_BITS = (Condition.NOT_READY | Condition.NETWORK_UNAVAILABLE
              | Condition.OUT_OF_DISK | Condition.DISK_PRESSURE
              | Condition.UNSCHEDULABLE)


def _kernel(sel_onehot, sel_count, untol, best_effort, pod_lo, pod_hi,
            sel_member, hard_member, node_bits, name_lo, name_hi, out):
    # selector: satisfied-term counts via MXU, then the >= count compare
    sat = jax.lax.dot_general(
        sel_onehot[:], sel_member[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (TP, TN)
    ok = sat >= sel_count[:]                          # (TP,1) broadcasts

    # taints: untolerated hard-taint hits must be zero
    viol = jax.lax.dot_general(
        untol[:], hard_member[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ok &= viol == 0.0

    # conditions: hard bits reject everyone; MemoryPressure rejects only
    # BestEffort pods; bit 0x80000000 marks an invalid (padding) row
    bits = node_bits[:].reshape(1, -1)                # (TN,1) -> (1, TN)
    ok &= (bits & _HARD_BITS) == 0
    mem = (bits & Condition.MEMORY_PRESSURE) != 0
    ok &= ~(mem & (best_effort[:] != 0))
    ok &= (bits & jnp.int32(-2147483648)) == 0        # invalid-row bit

    # spec.nodeName pinning: unset (0) matches everywhere
    lo = pod_lo[:]                                    # (TP, 1) i32
    hi = pod_hi[:]
    pinned = lo != 0
    match = ((lo == name_lo[:].reshape(1, -1))
             & (hi == name_hi[:].reshape(1, -1)))
    ok &= match | ~pinned

    out[:] = ok.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_static_mask(state, sel_onehot, sel_count, untol, best_effort,
                      node_name_lo, node_name_hi, *,
                      interpret: bool = False) -> jnp.ndarray:
    """bool[P, N]: valid & schedulable & conditions & selector & taints &
    host-name for every (pod, node) pair.

    `untol` is the per-pod untolerated-taint-universe row
    (predicates._tolerated_universe complement), f32[P, UT]. P must be a
    multiple of 8 and N of 128 (the padded capacities guarantee this).
    """
    p = sel_onehot.shape[0]
    n = state.valid.shape[0]
    # adapt tiles to small padded capacities (tests run at N=128, P=16);
    # callers guarantee n % 128 == 0 and p % 8 == 0
    tile_n = NODE_TILE if n % NODE_TILE == 0 else n
    tile_p = POD_TILE if p % POD_TILE == 0 else p
    # node-level bits: condition mask + the invalid-row marker in the sign
    # bit (one i32 per node keeps SMEM/VMEM traffic minimal)
    node_bits = (state.conditions.astype(jnp.int32)
                 | jnp.where(state.valid, 0, jnp.int32(-2147483648)))
    grid = (p // tile_p, n // tile_n)
    spec = pl.BlockSpec
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((p, n), jnp.float32),
        grid=grid,
        in_specs=[
            spec((tile_p, sel_onehot.shape[1]), lambda i, j: (i, 0)),
            spec((tile_p, 1), lambda i, j: (i, 0)),
            spec((tile_p, untol.shape[1]), lambda i, j: (i, 0)),
            spec((tile_p, 1), lambda i, j: (i, 0)),
            spec((tile_p, 1), lambda i, j: (i, 0)),
            spec((tile_p, 1), lambda i, j: (i, 0)),
            spec((tile_n, sel_onehot.shape[1]), lambda i, j: (j, 0)),
            spec((tile_n, untol.shape[1]), lambda i, j: (j, 0)),
            spec((tile_n, 1), lambda i, j: (j, 0)),
            spec((tile_n, 1), lambda i, j: (j, 0)),
            spec((tile_n, 1), lambda i, j: (j, 0)),
        ],
        out_specs=spec((tile_p, tile_n), lambda i, j: (i, j)),
        interpret=interpret,
    )(
        sel_onehot,
        sel_count.reshape(p, 1),
        untol,
        best_effort.astype(jnp.int32).reshape(p, 1),
        node_name_lo.astype(jnp.int32).reshape(p, 1),
        node_name_hi.astype(jnp.int32).reshape(p, 1),
        state.sel_member,
        state.taint_hard_member,
        node_bits.reshape(n, 1),
        state.name_lo.astype(jnp.int32).reshape(n, 1),
        state.name_hi.astype(jnp.int32).reshape(n, 1),
    )
    return out != 0.0
