"""Vectorized priority (scoring) kernels: f32[N] scores in [0, MaxPriority].

Each kernel re-expresses one reference PriorityMap/PriorityReduce pair
(signature plugin/pkg/scheduler/algorithm/types.go:36-42) as a vector op over
all nodes. The reference computes integer scores with int64 division; we
reproduce the truncation with explicit floor so scores match exactly on
integer-valued inputs.

Covered (reference plugin/pkg/scheduler/algorithm/priorities/):
- LeastRequestedPriority     (least_requested.go)        -> least_requested
- BalancedResourceAllocation (balanced_resource_allocation.go) -> balanced_allocation
- TaintTolerationPriority    (taint_toleration.go)       -> taint_toleration
- EqualPriority              (core/generic_scheduler.go:416) -> equal

SelectorSpread / InterPodAffinity / NodeAffinity arrive with the spreading and
affinity op sets (they need service/owner state and affinity-term encodings).

The per-pod function is vmapped over the batch; the per-priority goroutine
fan-out + reduce of the reference (generic_scheduler.go:352-364) becomes plain
vector arithmetic.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.ops.predicates import count_untolerated_prefer_taints
from kubernetes_tpu.state.cluster_state import ClusterState
from kubernetes_tpu.state.layout import MAX_PRIORITY, Resource
from kubernetes_tpu.state.pod_batch import PodBatch

# The reference computes scores with exact int64 division; we use f32. When
# the true quotient is an exact integer, f32 rounding can land epsilon *below*
# it and floor() would lose a whole point. Nudging by FLOOR_EPS (far below the
# quotient granularity 10/capacity for any realistic node size) restores exact
# parity on representable inputs.
FLOOR_EPS = 1e-6


def _unused_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """calculateUnusedScore (least_requested.go:40): ((cap-req)*10)/cap with
    int64 truncation; 0 when cap == 0 or req > cap."""
    safe_cap = jnp.where(capacity == 0, 1.0, capacity)
    score = jnp.floor((capacity - requested) * MAX_PRIORITY / safe_cap + FLOOR_EPS)
    return jnp.where((capacity == 0) | (requested > capacity), 0.0, score)


def least_requested(state: ClusterState, pod: PodBatch, nonzero_requested=None) -> jnp.ndarray:
    """LeastRequestedPriorityMap: favor nodes with more free cpu+mem after
    placing the pod, using non-zero scoring requests."""
    nz = state.nonzero_requested if nonzero_requested is None else nonzero_requested
    total_cpu = nz[:, 0] + pod.nonzero_requests[0]
    total_mem = nz[:, 1] + pod.nonzero_requests[1]
    cpu_score = _unused_score(total_cpu, state.allocatable[:, Resource.CPU])
    mem_score = _unused_score(total_mem, state.allocatable[:, Resource.MEMORY])
    return jnp.floor((cpu_score + mem_score) / 2.0 + FLOOR_EPS)


def balanced_allocation(state: ClusterState, pod: PodBatch, nonzero_requested=None) -> jnp.ndarray:
    """BalancedResourceAllocation: favor nodes where cpu and mem utilization
    fractions are closest; 0 if either fraction exceeds 1."""
    nz = state.nonzero_requested if nonzero_requested is None else nonzero_requested
    cap_cpu = state.allocatable[:, Resource.CPU]
    cap_mem = state.allocatable[:, Resource.MEMORY]
    safe_cpu = jnp.where(cap_cpu == 0, 1.0, cap_cpu)
    safe_mem = jnp.where(cap_mem == 0, 1.0, cap_mem)
    cpu_frac = (nz[:, 0] + pod.nonzero_requests[0]) / safe_cpu
    mem_frac = (nz[:, 1] + pod.nonzero_requests[1]) / safe_mem
    diff = jnp.abs(cpu_frac - mem_frac)
    score = jnp.trunc((1.0 - diff) * MAX_PRIORITY + FLOOR_EPS)
    bad = (cpu_frac >= 1.0) | (mem_frac >= 1.0) | (cap_cpu == 0) | (cap_mem == 0)
    return jnp.where(bad, 0.0, score)


def taint_toleration_from_counts(counts: jnp.ndarray, feasible: jnp.ndarray) -> jnp.ndarray:
    """The reduce half of TaintToleration (taint_toleration.go:73-96) from
    precomputed per-node intolerable counts: (1 - count/max)*MaxPriority
    truncated, all-MaxPriority when max == 0.

    The reference reduce runs over the *filtered* node list
    (generic_scheduler.go:121 passes filteredNodes to PrioritizeNodes), so the
    max is taken over `feasible` nodes.
    """
    counts = jnp.where(feasible, counts.astype(jnp.float32), 0.0)
    max_count = jnp.max(counts)
    return jnp.where(
        max_count > 0,
        jnp.trunc((1.0 - counts / jnp.maximum(max_count, 1.0)) * MAX_PRIORITY + FLOOR_EPS),
        float(MAX_PRIORITY),
    )


def taint_toleration(state: ClusterState, pod: PodBatch, feasible=None) -> jnp.ndarray:
    """TaintToleration map+reduce: fewer untolerated PreferNoSchedule taints
    is better; normalized against the per-pod max count."""
    counts = count_untolerated_prefer_taints(state, pod)
    return taint_toleration_from_counts(
        counts, state.valid if feasible is None else feasible)


def node_affinity_counts(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """The map half of NodeAffinityPriority (node_affinity.go
    CalculateNodeAffinityPriorityMap): per node, the total weight of preferred
    scheduling terms whose selector matches the node's labels. One matmul per
    pod: `pref_onehot[TP, UR] @ req_member[N, UR].T`, a term matches when all
    its requirements do."""
    term_sat = pod.pref_onehot @ state.req_member.T            # f32[TP, N]
    matches = (term_sat >= pod.pref_count[:, None]) & (pod.pref_weight[:, None] > 0)
    return jnp.sum(jnp.where(matches, pod.pref_weight[:, None], 0.0), axis=0)


def normalized_from_counts(counts: jnp.ndarray, feasible: jnp.ndarray) -> jnp.ndarray:
    """NormalizeReduce-style reduce (node_affinity.go
    CalculateNodeAffinityPriorityReduce): score = int(MaxPriority * count /
    maxCount) over the filtered node list; all zero when maxCount == 0."""
    counts = jnp.where(feasible, counts.astype(jnp.float32), 0.0)
    max_count = jnp.max(counts)
    return jnp.where(
        max_count > 0,
        jnp.trunc(counts * MAX_PRIORITY / jnp.maximum(max_count, 1.0) + FLOOR_EPS),
        0.0,
    )


def node_affinity(state: ClusterState, pod: PodBatch, feasible=None) -> jnp.ndarray:
    """NodeAffinityPriority map+reduce."""
    counts = node_affinity_counts(state, pod)
    return normalized_from_counts(
        counts, state.valid if feasible is None else feasible)


def equal(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """EqualPriority (generic_scheduler.go:416): weight-1 constant score."""
    return jnp.ones(state.valid.shape[0], dtype=jnp.float32)


def _used_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """calculateUsedScore (most_requested.go:51): (req*10)/cap truncated;
    0 when cap == 0 or req > cap."""
    safe_cap = jnp.where(capacity == 0, 1.0, capacity)
    score = jnp.floor(requested * MAX_PRIORITY / safe_cap + FLOOR_EPS)
    return jnp.where((capacity == 0) | (requested > capacity), 0.0, score)


def most_requested(state: ClusterState, pod: PodBatch,
                   nonzero_requested=None) -> jnp.ndarray:
    """MostRequestedPriorityMap (most_requested.go:32): the bin-packing
    mirror of LeastRequested — favor nodes with higher cpu+mem utilization
    after placing the pod."""
    nz = state.nonzero_requested if nonzero_requested is None else nonzero_requested
    total_cpu = nz[:, 0] + pod.nonzero_requests[0]
    total_mem = nz[:, 1] + pod.nonzero_requests[1]
    cpu_score = _used_score(total_cpu, state.allocatable[:, Resource.CPU])
    mem_score = _used_score(total_mem, state.allocatable[:, Resource.MEMORY])
    return jnp.floor((cpu_score + mem_score) / 2.0 + FLOOR_EPS)


# ImageLocality size bounds (balanced_resource_allocation.go:33-35)
MIN_IMG_SIZE = 23.0 * 1024 * 1024
MAX_IMG_SIZE = 1000.0 * 1024 * 1024


def image_locality(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """ImageLocalityPriorityMap (image_locality.go:32): bucket the summed
    bytes of the pod's images already present on the node into [0, 10]. One
    matvec: sums = img_size[N, UI] @ img_onehot[UI]."""
    sums = state.img_size @ pod.img_onehot
    mid = jnp.floor(MAX_PRIORITY * (sums - MIN_IMG_SIZE)
                    / (MAX_IMG_SIZE - MIN_IMG_SIZE) + FLOOR_EPS) + 1.0
    return jnp.where(sums < MIN_IMG_SIZE, 0.0,
                     jnp.where(sums >= MAX_IMG_SIZE, float(MAX_PRIORITY), mid))


def node_prefer_avoid(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """CalculateNodePreferAvoidPodsPriorityMap (node_prefer_avoid_pods.go:29):
    0 on nodes whose preferAvoidPods annotation names the pod's RC/RS
    controller, MaxPriority elsewhere (registered at weight 10000 so it
    dominates, defaults.go:225)."""
    hit = state.avoid_member @ pod.avoid_onehot
    return jnp.where(hit > 0, 0.0, float(MAX_PRIORITY))


def node_label_score(state: ClusterState, onehot_row: jnp.ndarray,
                     presence: bool) -> jnp.ndarray:
    """CalculateNodeLabelPriorityMap (node_label.go:44): MaxPriority when the
    label's presence matches the preference. Pod-independent — computed once
    per batch from the PolicyRows Exists-requirement row."""
    exists = (state.req_member @ onehot_row) > 0
    match = exists if presence else ~exists
    return jnp.where(match, float(MAX_PRIORITY), 0.0)
