"""Batched assignment solver: the device-side replacement for `scheduleOne`.

The reference schedules strictly one pod at a time — `scheduleOne`
(plugin/pkg/scheduler/scheduler.go:253) pops a pod, runs findNodesThatFit +
PrioritizeNodes + selectHost over all nodes, assumes the result into the cache
(scheduler.go:188), and repeats — so pod K sees the resource claims of pods
0..K-1. This solver reproduces those semantics exactly while moving all the
work to the device:

- **Phase A (parallel over P x N)**: every assignment-independent predicate
  and score term evaluates for the whole batch at once via vmap — the
  expensive irregular matching (selectors, taints, conditions, host names).
- **Phase B (lax.scan over P, vector over N)**: a scan carries the running
  (requested, nonzero_requested, ports) ledger; each step evaluates only the
  assignment-*dependent* terms (resource fit, in-batch port conflicts,
  utilization scores), picks argmax with the reference's round-robin
  tie-break (selectHost, generic_scheduler.go:144-157), and scatters the
  pod's claims into the ledger — the batched analog of cache.AssumePod.

Scores are computed exactly as the reference's int64 math (floor-division
semantics in the priority kernels), so argmax decisions match the serial
scheduler decision-for-decision; parity is enforced against a pure-Python
serial reference in tests/serial_reference.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_tpu.models.policy import (
    DEFAULT_POLICY,
    Policy,
    active_label_presence,
    active_label_priorities,
    active_service_anti,
)
from kubernetes_tpu.ops import interpod
from kubernetes_tpu.ops import predicates as preds
from kubernetes_tpu.ops import priorities as prios
from kubernetes_tpu.ops import spread as spreadops
from kubernetes_tpu.state.cluster_state import ClusterState
from kubernetes_tpu.state.layout import MAX_PRIORITY, Resource
from kubernetes_tpu.state.pod_batch import PodBatch

# Domain-axis size for inter-pod affinity aggregates; must equal the encoding
# Capacities.domain_universe (pass caps to schedule_batch to override).
DEFAULT_DOMAIN_UNIVERSE = 64


@dataclass(frozen=True)
class BatchFlags:
    """Batch-content gates: what this batch can actually exercise.

    The policy decides which kernels are *configured*; these flags record
    which of them the current batch (plus accounted state) can possibly
    affect, so the compiled program skips provably-neutral work. Each flag
    set False asserts a fact about the inputs under which the skipped
    kernel's contribution is exactly neutral (constant score shifts are
    re-added as scalars), keeping decisions bit-identical to ALL_ACTIVE.

    This is the batched analog of the reference's per-predicate
    short-circuits (e.g. the len(newVolumes)==0 quick return,
    predicates.go:296): the reference skips per pod at run time, a compiled
    tensor program must skip per batch at trace time. Hashable — part of
    the jit key; the driver computes it per batch (few distinct values in
    practice, so a handful of program variants).
    """

    ipa: bool = True      # own interpod terms in batch, or carried terms
    spread: bool = True   # any spread_q / spread_svc_q entry
    svcanti: bool = True  # any svcanti_q entry
    vol: bool = True      # any disk-conflict atom wanted
    attach: bool = True   # any attachable-volume atom (or resolve failure)
    tt: bool = True       # any PreferNoSchedule taint interned (TaintToleration
                          # counts can be nonzero) — else uniform MaxPriority
    na: bool = True       # any preferred node-affinity term in batch
    ports: bool = True    # any host port wanted: with none, PodFitsHostPorts
                          # is constant-true (conflicts = count @ 0) whatever
                          # the ledger — skip the kernel and the ledger update
    gpu: bool = True      # any GPU request in batch: with none, the GPU fit
                          # column never changes through the scan — fold into
                          # the assignment-independent Phase A fit
    storage: bool = True  # any scratch/overlay request in batch: same —
                          # the storage fallthrough logic (predicates.go:
                          # 590-605) becomes assignment-independent
    gang: bool = True     # any gang member (gang_id > 0) in batch: with none
                          # the group-revert carry extension is dead weight —
                          # whole-ledger selects per scan step — so the gate
                          # keeps the non-gang program untaxed
    preempt: bool = True  # any nonzero pod priority in batch: all-zero
                          # priorities can never out-rank a victim, so the
                          # victim-selection pass is provably neutral and the
                          # pre-preemption program compiles unchanged (the
                          # pass also needs a VictimTable — absent one,
                          # schedule_batch skips it at trace time regardless)
    explain: bool = False  # explainability probe: additionally emit the
                          # per-predicate cumulative survivor counts from
                          # _pod_eval's feasible-mask chain (i32[P, 6] over
                          # EXPLAIN_STAGES) so the driver can render
                          # reference-parity FailedScheduling reasons
                          # (findNodesThatFit's failedPredicateMap,
                          # core/generic_scheduler.go:163). Like scale_sim
                          # this defaults OFF and is never derived from
                          # batch content (packed_batch_flags leaves it
                          # False) — explain-off batches compile the
                          # bit-identical pre-explain program, and the
                          # extra per-step sums are traced only into
                          # programs the operator requests (KTPU_EXPLAIN).
    scale_sim: bool = False  # autoscaler probe solve: additionally emit the
                          # per-node placed count (how many batch pods landed
                          # on each node row) so a what-if simulation can
                          # score hypothetical rows. Unlike every flag above
                          # this one defaults OFF and is never derived from
                          # batch content (packed_batch_flags leaves it
                          # False) — real scheduling batches compile the
                          # bit-identical pre-autoscaler program, and the
                          # extra segment-sum is traced only into programs
                          # the autoscaler itself requests.


ALL_ACTIVE = BatchFlags()

# Stage labels for the BatchFlags.explain breakdown — the order of
# _pod_eval's feasible-mask chain. Column i holds the survivor count
# AFTER stage i; a gated-off stage repeats the previous count (it
# rejected nobody). "static" folds Phase A (selectors, taints,
# conditions, host name, ports-free fit, and — under the gpu/storage
# hoist — the static resource columns).
EXPLAIN_STAGES = ("static", "resources", "ports", "disk", "attach",
                  "interpod")


@dataclass(frozen=True)
class PolicyGates:
    """The compile-time kernel gates for one (policy, flags) pair — the
    single derivation consumed both by `schedule_batch` (what the compiled
    program tracks) and by `ledger_coverage` (what the driver may chain
    device-side at commit time). Weights are post-gating: a flag-neutralized
    kernel has weight 0 here and its constant contribution in const_score."""

    use_resources: bool
    use_ports: bool
    dyn_gpu: bool      # GPU fit must track the in-batch ledger
    dyn_storage: bool  # scratch/overlay fit must track the in-batch ledger
    w_lr: float
    w_mr: float
    w_ba: float
    w_tt: float
    w_na: float
    w_ip: float
    w_ss: float
    w_ssp: float
    svcanti: tuple
    use_ipa: bool
    use_svcanti: bool
    use_terms: bool
    use_ip_ledger: bool
    use_nodisk: bool
    attach_maxes: tuple
    const_score: float


def policy_gates(policy: Policy, flags: BatchFlags) -> PolicyGates:
    use_ipa = policy.has_predicate("MatchInterPodAffinity") and flags.ipa
    w_ss = policy.weight("SelectorSpreadPriority")
    w_ssp = policy.weight("ServiceSpreadingPriority")
    w_tt = policy.weight("TaintTolerationPriority")
    w_na = policy.weight("NodeAffinityPriority")
    svcanti = active_service_anti(policy)
    # flag-gated neutral terms: with every spread_q == -1, SelectorSpread
    # scores a uniform MaxPriority (selector_spreading.go:157) — a constant
    # shift that cannot change argmax but must stay in the reported score
    const_score = 0.0
    if w_ss and not flags.spread:
        const_score += w_ss * float(MAX_PRIORITY)
        w_ss = 0
    if w_ssp and not flags.spread:
        const_score += w_ssp * float(MAX_PRIORITY)
        w_ssp = 0
    # no PreferNoSchedule taint interned: every count is 0, the reduce
    # yields uniform MaxPriority (taint_toleration.go:90 maxCount==0 path)
    if w_tt and not flags.tt:
        const_score += w_tt * float(MAX_PRIORITY)
        w_tt = 0
    # no preferred node-affinity term in the batch: counts are all 0 and the
    # NormalizeReduce maxCount==0 path scores every node 0 — drop the kernel
    if w_na and not flags.na:
        w_na = 0
    w_ip = policy.weight("InterPodAffinityPriority") if flags.ipa else 0
    use_svcanti = bool(svcanti) and flags.svcanti
    use_terms = use_ipa or bool(w_ip)   # carried-term ledger structures
    return PolicyGates(
        use_resources=policy.has_predicate("GeneralPredicates",
                                           "PodFitsResources"),
        # no host port wanted anywhere in the batch: conflicts = count @ 0
        # == 0 on every node whatever the ledger — the predicate is
        # constant-true and the port ledger passes through untouched
        use_ports=policy.has_predicate("GeneralPredicates",
                                       "PodFitsHostPorts",
                                       "PodFitsPorts") and flags.ports,
        dyn_gpu=flags.gpu,
        dyn_storage=flags.storage,
        w_lr=policy.weight("LeastRequestedPriority"),
        w_mr=policy.weight("MostRequestedPriority"),
        w_ba=policy.weight("BalancedResourceAllocation"),
        w_tt=w_tt, w_na=w_na, w_ip=w_ip, w_ss=w_ss, w_ssp=w_ssp,
        svcanti=svcanti,
        use_ipa=use_ipa,
        use_svcanti=use_svcanti,
        use_terms=use_terms,
        use_ip_ledger=(use_terms or bool(w_ss) or bool(w_ssp) or use_svcanti),
        use_nodisk=policy.has_predicate("NoDiskConflict") and flags.vol,
        attach_maxes=policy.attach_maxes() if flags.attach else (),
        const_score=const_score,
    )


def ledger_coverage(policy: Policy, flags: BatchFlags) -> tuple[bool, bool, bool]:
    """(ipa, vol, attach): which state-ledger groups a program compiled with
    this (policy, flags) pair actually tracks through its scan carry —
    derived from the same PolicyGates the program itself compiles with. The
    driver uses this at commit time: a pod whose accounting rows touch an
    *untracked* group must dirty the host mirror so the next flush re-uploads
    truth the device pass-through ledger does not contain."""
    g = policy_gates(policy, flags)
    return g.use_ip_ledger, bool(g.use_nodisk), bool(g.attach_maxes)


def batch_flags(batch: PodBatch, n_pods: int, table) -> BatchFlags:
    """Compute the gates for `n_pods` encoded rows of a host-side batch
    against the current NodeTable (carried terms live in the state)."""
    import numpy as np

    def any_(arr):
        return bool(np.asarray(arr[:n_pods]).any())

    return BatchFlags(
        ipa=bool(table.terms) or any_(batch.paff_q >= 0)
        or any_(batch.panti_q >= 0) or any_(batch.ppref_q >= 0)
        or any_(batch.ipaff_fail),
        spread=any_(batch.spread_q >= 0) or any_(batch.spread_svc_q >= 0),
        svcanti=any_(batch.svcanti_q >= 0),
        vol=any_(batch.vol_want_rw) or any_(batch.vol_want_ro),
        attach=any_(batch.att_onehot) or any_(batch.att_fail),
        tt=table_has_prefer_taints(table),
        na=any_(batch.pref_weight > 0),
        ports=any_(batch.port_onehot),
        gpu=any_(batch.requests[:, Resource.GPU]),
        storage=any_(batch.requests[:, Resource.SCRATCH])
        or any_(batch.requests[:, Resource.OVERLAY]),
        gang=any_(batch.gang_id > 0),
        preempt=any_(batch.priority != 0),
    )


def table_has_prefer_taints(table) -> bool:
    """True when any interned taint can produce a nonzero TaintToleration
    count (the map input is taint_prefer_member, populated only by
    PreferNoSchedule taints)."""
    return any(effect == "PreferNoSchedule" for _k, _v, effect in table.taints)


@struct.dataclass
class VictimTable:
    """Per-node preemption candidates — the bound-pods tensor the victim-
    selection pass scans (the batched analog of selectNodesForPreemption's
    per-node pod lists, generic_scheduler.go). Built host-side by
    kubernetes_tpu/preemption/victims.py from the StateDB accounting:

    - slots within a node are sorted ASCENDING by (priority, pod key), so
      "evict lowest-priority victims first" is a prefix of the slot axis
      and (node, k) identifies the victim set reproducibly on the host;
    - `ok` is False for empty slots and for pods any covering
      PodDisruptionBudget refuses to disrupt (disruptionsAllowed <= 0) —
      the pass never selects a PDB-protected victim;
    - `prio` is INT32_MAX on empty slots so they sort last.
    """

    prio: jnp.ndarray   # i32[N, S] victim priority (INT32_MAX = empty slot)
    req: jnp.ndarray    # f32[N, S, R] victim resource requests (device units)
    ok: jnp.ndarray     # bool[N, S] evictable (PDB allows; slot occupied)


@struct.dataclass
class SolverResult:
    assignments: jnp.ndarray   # i32[P] node row, -1 = unschedulable (or padding)
    scores: jnp.ndarray        # f32[P] winning node's score (0 when unassigned)
    feasible_counts: jnp.ndarray  # i32[P] nodes that passed all predicates
    new_requested: jnp.ndarray     # f32[N, R] ledger after the batch
    new_nonzero: jnp.ndarray       # f32[N, 2]
    new_port_count: jnp.ndarray    # f32[N, UP]
    rr_end: jnp.ndarray        # u32 round-robin counter after the batch
    # full post-batch state ledger: kernels the batch could not touch pass
    # the input arrays through unchanged (an alias, no device copy), so the
    # driver can chain EVERY batch device-to-device with no host re-upload
    new_podsel: jnp.ndarray    # f32[N, UQ]
    new_term: jnp.ndarray      # f32[N, UE]
    new_vol_any: jnp.ndarray   # f32[N, UV]
    new_vol_rw: jnp.ndarray    # f32[N, UV]
    new_attach: jnp.ndarray    # f32[N, UA]
    # preemption verdicts for pods the scan left unassigned: the node whose
    # minimal victim set the pass chose (-1 = none found / pass off) and the
    # victim count k — the first k ok-slots of that node's VictimTable row.
    # Constant (-1, 0) when the pass is compiled out, so gated and
    # ALL_ACTIVE programs stay field-for-field comparable.
    preempt_node: jnp.ndarray = None   # i32[P]
    victim_count: jnp.ndarray = None   # i32[P]
    # autoscaler probe output (BatchFlags.scale_sim): batch pods placed per
    # node row. None — an empty pytree leaf, zero HLO — on every real
    # scheduling program; the simulator reads its hypothetical rows from it.
    placed_per_node: jnp.ndarray = None  # i32[N]
    # explainability output (BatchFlags.explain): cumulative survivor
    # counts down _pod_eval's feasible chain, one column per
    # EXPLAIN_STAGES entry. None — zero HLO — on every explain-off
    # program; the driver diffs adjacent columns into per-predicate
    # reject counts for FailedScheduling messages.
    explain_counts: jnp.ndarray = None  # i32[P, len(EXPLAIN_STAGES)]


@struct.dataclass
class Carry:
    """Scan-carried assume ledger: every assignment-dependent count. Fields
    gated off by the policy stay None (None is an empty pytree, so the scan
    carry structure remains static per policy).

    requested and nonzero stay SEPARATE arrays on purpose: fusing them into
    one [N, R+2] ledger (one scatter per claim instead of two) measured 4x
    SLOWER (365 ms vs 91 ms per 4,096-pod solve) — the static column slices
    feeding the predicates break XLA's in-place while-loop buffer aliasing,
    so every step copies the whole ledger instead of scattering in place."""

    requested: jnp.ndarray
    nonzero: jnp.ndarray
    port_count: jnp.ndarray
    rr: jnp.ndarray
    ipa: object = None          # AffinityLedger | None
    vol_any: object = None      # f32[N, UV] | None
    vol_rw: object = None
    attach_count: object = None  # f32[N, UA] | None
    # gang group-revert extension (BatchFlags.gang; None when gated off).
    # gang_snap holds the whole live ledger (incl. rr) as of the current
    # group's entry; a group that exits with fewer than gang_min_cur placed
    # members restores it wholesale — the batched analog of forgetting every
    # AssumePod of a gang that cannot complete. Whole-ledger selects per
    # step are the known cost (see the fused-ledger note above); they are
    # only ever compiled into gang-gated programs.
    gang_snap: object = None     # ledger tuple | None
    gang_cur: object = None      # i32 current group id, 0 = not in a group
    gang_placed: object = None   # i32 members assigned in the current group
    gang_min_cur: object = None  # i32 current group's quorum


def _live_ledger(c: Carry):
    """The revertible ledger as one pytree — every assignment-dependent
    count a gang revert must restore, the round-robin counter included (a
    reverted member's rr bump must not survive, or tie-breaks downstream of
    a failed gang would diverge from the serial oracle)."""
    return (c.requested, c.nonzero, c.port_count, c.rr,
            c.ipa, c.vol_any, c.vol_rw, c.attach_count)


def _static_mask(state: ClusterState, pod, policy: Policy,
                 base_mask=None) -> jnp.ndarray:
    """Assignment-independent predicate conjunction for one pod: bool[N].

    The unschedulable filter is NOT policy-gated: the reference applies it in
    the scheduler's node lister regardless of configured predicates
    (factory.go getNodeConditionPredicate). `base_mask` carries the
    pod-independent policy-argument predicates (CheckNodeLabelPresence).
    """
    ok = state.valid & preds.node_schedulable(state, pod)
    if base_mask is not None:
        ok = ok & base_mask
    if policy.service_affinity_predicates and policy.has_predicate(
            *[n for n, _ in policy.service_affinity_predicates]):
        ok = ok & preds.service_affinity(state, pod)
    if policy.has_predicate("GeneralPredicates", "PodFitsHost", "HostName"):
        ok = ok & preds.fits_host(state, pod)
    if policy.has_predicate("GeneralPredicates", "MatchNodeSelector"):
        ok = ok & preds.match_node_selector(state, pod)
    if policy.has_predicate("PodToleratesNodeTaints"):
        ok = ok & preds.tolerates_node_taints(state, pod)
    if policy.has_predicate("CheckNodeCondition"):
        ok = ok & preds.check_node_condition(state, pod)
    if policy.has_predicate("CheckNodeMemoryPressure"):
        ok = ok & preds.check_memory_pressure(state, pod)
    if policy.has_predicate("CheckNodeDiskPressure"):
        ok = ok & preds.check_disk_pressure(state, pod)
    if policy.has_predicate("NoVolumeZoneConflict"):
        ok = ok & preds.volume_zone(state, pod)
    if policy.has_predicate("NoVolumeNodeConflict"):
        ok = ok & preds.volume_node(state, pod)
    return ok


def _use_fused_static(policy: Policy, state, batch) -> bool:
    """The Pallas fused static kernel applies selector/taint/condition/
    host checks unconditionally — sound only when the policy registers all
    of them and adds no base-mask predicates; tile shapes must divide the
    padded capacities. Opt-in via KTPU_PALLAS=1 (see PERF.md). The sharded
    path passes allow_fused=False — Mosaic custom calls have no GSPMD
    partitioning rule, so the kernel must never trace under a mesh."""
    import os

    from kubernetes_tpu.utils.features import enabled

    if os.environ.get("KTPU_PALLAS") != "1" \
            and not enabled("PallasFusedScoring"):
        return False
    return (
        state.valid.shape[0] % 128 == 0    # lane width (tiles adapt above)
        and batch.valid.shape[0] % 8 == 0  # f32 sublane width
        and policy.has_predicate("GeneralPredicates", "PodFitsHost",
                                 "HostName")
        and policy.has_predicate("GeneralPredicates", "MatchNodeSelector")
        and policy.has_predicate("PodToleratesNodeTaints")
        and policy.has_predicate("CheckNodeCondition")
        and policy.has_predicate("CheckNodeMemoryPressure")
        and policy.has_predicate("CheckNodeDiskPressure")
        and not policy.service_affinity_predicates
        and not active_label_presence(policy))


def _static_rest(state: ClusterState, pod, policy: Policy,
                 base_mask=None) -> jnp.ndarray:
    """The static terms the fused kernel does NOT cover: required
    node-affinity (a (T × UR × N) contraction) and the volume zone/node
    predicates. AND-combined with the kernel output."""
    ok = preds.node_affinity_ok(state, pod)
    if base_mask is not None:
        ok = ok & base_mask
    if policy.has_predicate("NoVolumeZoneConflict"):
        ok = ok & preds.volume_zone(state, pod)
    if policy.has_predicate("NoVolumeNodeConflict"):
        ok = ok & preds.volume_node(state, pod)
    return ok


def _static_score(state: ClusterState, pod, policy: Policy,
                  base_score=None) -> jnp.ndarray:
    """Assignment-independent score terms for one pod: f32[N]. `base_score`
    carries the pod-independent terms (NodeLabel priorities)."""
    score = jnp.zeros(state.valid.shape[0], jnp.float32)
    if base_score is not None:
        score = score + base_score
    w = policy.weight("EqualPriority")
    if w:
        score = score + w * prios.equal(state, pod)
    w = policy.weight("ImageLocalityPriority")
    if w:
        score = score + w * prios.image_locality(state, pod)
    w = policy.weight("NodePreferAvoidPodsPriority")
    if w:
        score = score + w * prios.node_prefer_avoid(state, pod)
    return score


def _base_rows(state: ClusterState, policy: Policy, prows,
               g: PolicyGates):
    """Pod-independent policy-argument rows (CheckNodeLabelPresence mask,
    NodeLabel priority scores, gated-neutral constant shifts) — computed once
    per batch/evaluation, broadcast over pods."""
    base_mask = None
    base_score = None
    if g.const_score:
        base_score = jnp.full(state.valid.shape[0], g.const_score, jnp.float32)
    if prows is not None:
        if active_label_presence(policy):
            base_mask = preds.label_presence_ok(
                state, prows.pres_onehot, prows.pres_count, prows.abs_onehot)
        nl = active_label_priorities(policy)
        if nl:
            if base_score is None:
                base_score = jnp.zeros(state.valid.shape[0], jnp.float32)
            for i, (_label, presence, weight) in enumerate(nl):
                base_score = base_score + weight * prios.node_label_score(
                    state, prows.nlp_onehot[i], presence)
        if g.svcanti and not g.use_svcanti:
            # every svcanti_q == -1 and svcanti_total == 0: counts are zero,
            # so labeled nodes score MaxPriority and unlabeled 0 — a
            # pod-independent surface, hoisted out of the scan
            if base_score is None:
                base_score = jnp.zeros(state.valid.shape[0], jnp.float32)
            for i, (_label, sa_weight) in enumerate(g.svcanti):
                labeled = state.topology[:, prows.svcanti_slot[i]] >= 0
                base_score = base_score + sa_weight * jnp.where(
                    labeled, float(MAX_PRIORITY), 0.0)
    return base_mask, base_score


def _init_carry(state: ClusterState, g: PolicyGates, rr_start,
                domain_universe: int, use_gang: bool = False) -> Carry:
    """The assume ledger as of batch start — the accounted cluster state."""
    carry = Carry(
        requested=state.requested,
        nonzero=state.nonzero_requested,
        port_count=state.port_count,
        rr=jnp.asarray(rr_start, jnp.uint32),
        ipa=(interpod.make_ledger(state, domain_universe,
                                  with_terms=g.use_terms)
             if g.use_ip_ledger else None),
        vol_any=state.vol_any if g.use_nodisk else None,
        vol_rw=state.vol_rw if g.use_nodisk else None,
        attach_count=state.attach_count if g.attach_maxes else None,
    )
    if use_gang:
        carry = carry.replace(
            gang_snap=_live_ledger(carry),
            gang_cur=jnp.int32(0),
            gang_placed=jnp.int32(0),
            gang_min_cur=jnp.int32(0),
        )
    return carry


def _pod_eval(state: ClusterState, g: PolicyGates, carry: Carry, pod,
              s_mask, s_score, p_counts, na_count, topo_onehot, prows,
              hard_w: float, domain_universe: int, explain: bool = False):
    """One pod's full-policy (feasible[N], score[N], breakdown) against an
    assume ledger — THE evaluation semantics, shared verbatim by the
    solver's scan step and the extender's Filter/Prioritize verbs (extender
    parity with in-batch scheduling is by construction, not by
    re-implementation). `breakdown` is the i32[len(EXPLAIN_STAGES)]
    cumulative survivor count down the mask chain when `explain`, else
    None — the trail list below holds plain aliases of `feasible`, so an
    explain-off trace sees zero extra ops."""
    feasible = s_mask
    trail = [feasible]
    if g.use_resources:
        feasible = feasible & preds.fits_resources_dyn(
            state, pod, carry.requested, g.dyn_gpu, g.dyn_storage)
    trail.append(feasible)
    if g.use_ports:
        feasible = feasible & preds.fits_host_ports(
            state, pod, port_count=carry.port_count)
    trail.append(feasible)
    if g.use_nodisk:
        feasible = feasible & preds.no_disk_conflict(
            state, pod, vol_any=carry.vol_any, vol_rw=carry.vol_rw)
    trail.append(feasible)
    if g.attach_maxes:
        feasible = feasible & preds.max_attach_ok(
            state, pod, g.attach_maxes, attach_count=carry.attach_count)
    trail.append(feasible)
    if g.use_ipa:
        feasible = feasible & interpod.interpod_feasible(
            state, pod, carry.ipa, topo_onehot)
    trail.append(feasible)
    breakdown = None
    if explain:
        breakdown = jnp.stack(
            [jnp.sum(m.astype(jnp.int32)) for m in trail])

    score = s_score
    if g.w_lr:
        score = score + g.w_lr * prios.least_requested(
            state, pod, nonzero_requested=carry.nonzero)
    if g.w_mr:
        score = score + g.w_mr * prios.most_requested(
            state, pod, nonzero_requested=carry.nonzero)
    if g.w_ba:
        score = score + g.w_ba * prios.balanced_allocation(
            state, pod, nonzero_requested=carry.nonzero)
    if g.w_tt:
        score = score + g.w_tt * prios.taint_toleration_from_counts(
            p_counts, feasible)
    if g.w_na:
        score = score + g.w_na * prios.normalized_from_counts(
            na_count, feasible)
    if g.w_ip:
        ip_counts = interpod.interpod_counts(state, pod, carry.ipa, hard_w,
                                             topo_onehot)
        score = score + g.w_ip * interpod.interpod_score(ip_counts, feasible)
    if g.w_ss:
        score = score + g.w_ss * spreadops.selector_spread(
            state, pod.spread_q, carry.ipa, feasible, domain_universe,
            topo_onehot)
    if g.w_ssp:
        score = score + g.w_ssp * spreadops.selector_spread(
            state, pod.spread_svc_q, carry.ipa, feasible, domain_universe,
            topo_onehot)
    if g.use_svcanti:
        for i, (_label, sa_weight) in enumerate(g.svcanti):
            score = score + sa_weight * spreadops.service_anti_affinity(
                state, pod.svcanti_q, pod.svcanti_total, carry.ipa,
                feasible, prows.svcanti_slot[i], domain_universe,
                topo_onehot)
    return feasible, score, breakdown


def _select_host(masked_score: jnp.ndarray, feasible: jnp.ndarray, rr: jnp.ndarray):
    """selectHost parity (generic_scheduler.go:144): among max-score feasible
    nodes, pick the (rr % ties)-th in node order.

    The tie count is read off the cumsum's last element rather than a
    separate sum (one less serial reduction in the scan step), and the
    cumsum runs in f32 — the VPU's native dtype, exact for counts < 2^24.
    A two-level reshape select ([N/128, 128] row-reduce + 128-wide rank
    find) measured SLOWER (99 ms vs 88 ms per 4,096-pod solve at N=16k):
    the 1-D->2-D retile of the tie vector costs more than the flat
    reduce-window cumsum it saves."""
    best = jnp.max(masked_score)
    ties = feasible & (masked_score == best)
    cum = jnp.cumsum(ties.astype(jnp.float32))
    ntie = cum[-1].astype(jnp.int32)
    k = (rr % jnp.maximum(ntie, 1).astype(jnp.uint32)).astype(jnp.int32)
    # cum is nondecreasing and steps exactly at tie positions: the first
    # index reaching k+1 IS the (k+1)-th tie
    node = jnp.argmax(cum >= (k + 1).astype(jnp.float32)).astype(jnp.int32)
    return node, best, ntie


def schedule_batch(
    state: ClusterState,
    batch: PodBatch,
    rr_start,
    policy: Policy = DEFAULT_POLICY,
    caps=None,
    prows=None,
    flags: BatchFlags = ALL_ACTIVE,
    allow_fused: bool = True,
    victims: VictimTable | None = None,
) -> SolverResult:
    """Schedule a whole pending batch in one device program.

    Pure function; jit with `policy`, `flags` (and `caps`, if given) static.
    `prows` carries the PolicyRows for argument-carrying registrations (None
    when the policy has none — models/policy.py build_policy_rows). Returns
    per-pod assignments plus the post-batch resource ledger for the host to
    commit (assume semantics).

    `victims` (a VictimTable) enables the preemption pass: pods the scan
    leaves unassigned get a per-node minimal-victim-set search and a
    pickOneNodeForPreemption node choice reported via
    (preempt_node, victim_count). The pass is traced only when BOTH
    flags.preempt is set AND a table is given — a batch with no priorities,
    or a driver with nothing evictable, compiles the exact pre-preemption
    program.
    """
    # normalize to jnp arrays: un-jitted callers pass host numpy, and numpy
    # arrays cannot be indexed by traced scalars inside the scan
    state = jax.tree.map(jnp.asarray, state)
    batch = jax.tree.map(jnp.asarray, batch)
    use_preempt = flags.preempt and victims is not None
    if use_preempt:
        victims = jax.tree.map(jnp.asarray, victims)

    g = policy_gates(policy, flags)
    # only the gates the remaining inline code reads; _base_rows/_init_carry/
    # _pod_eval consume the rest straight from g
    w_tt, w_na, use_ports, svcanti = g.w_tt, g.w_na, g.use_ports, g.svcanti
    use_terms, use_ip_ledger = g.use_terms, g.use_ip_ledger
    use_nodisk, attach_maxes = g.use_nodisk, g.attach_maxes
    use_gang = flags.gang
    if prows is None and (svcanti or active_label_presence(policy)
                          or active_label_priorities(policy)):
        raise ValueError(
            "policy carries argument registrations (labelsPresence / "
            "labelPreference / serviceAntiAffinity) but no PolicyRows were "
            "given — build them with models.policy.build_policy_rows")
    hard_w = float(policy.hard_pod_affinity_weight)
    domain_universe = caps.domain_universe if caps else DEFAULT_DOMAIN_UNIVERSE

    # pod-independent policy-argument rows (CheckNodeLabelPresence mask,
    # NodeLabel priority scores) — computed once, broadcast over the batch
    base_mask, base_score = _base_rows(state, policy, prows, g)

    # ---- Phase A: batched over (P, N) ----
    if allow_fused and _use_fused_static(policy, state, batch):
        from kubernetes_tpu.ops.pallas_kernels import fused_static_mask

        untol = jax.vmap(
            lambda p: 1.0 - preds._tolerated_universe(state, p)
            .astype(jnp.float32))(batch)
        fused = fused_static_mask(
            state, batch.sel_onehot, batch.sel_count, untol,
            batch.best_effort, batch.node_name_lo, batch.node_name_hi,
            interpret=jax.default_backend() != "tpu")
        static_mask = fused & jax.vmap(
            lambda p: _static_rest(state, p, policy, base_mask))(batch)
    else:
        static_mask = jax.vmap(
            lambda p: _static_mask(state, p, policy, base_mask))(batch)
    static_score = jax.vmap(
        lambda p: _static_score(state, p, policy, base_score))(batch)

    # resource columns the batch cannot touch (gpu/storage under the
    # BatchFlags gates) hold against the batch-start ledger for the whole
    # batch: hoist their compares out of the scan into the static mask
    if g.use_resources and not (g.dyn_gpu and g.dyn_storage):
        static_mask = static_mask & jax.vmap(
            lambda p: preds.fits_resources_static(
                state, p, g.dyn_gpu, g.dyn_storage))(batch)

    if w_tt:
        prefer_counts = jax.vmap(
            lambda p: preds.count_untolerated_prefer_taints(state, p))(batch)
    if w_na:
        na_counts = jax.vmap(
            lambda p: prios.node_affinity_counts(state, p))(batch)

    # domain->node broadcast matrix, shared by every interpod/spread kernel
    # (pod-independent; hoisted so scan steps do matmuls, not gathers)
    topo_onehot = (interpod.topology_onehot(state.topology, domain_universe)
                   if use_ip_ledger else None)

    # ---- Phase B: scan over the pod axis, vector over nodes ----
    # Every scan-xs leaf costs one dynamic-slice per step inside the compiled
    # while loop (~1 us each on TPU — the dominant per-pod cost when the xs
    # is the ~45-leaf PodBatch pytree; PERF.md round 5). So the step consumes
    # the batch as TWO packed blob rows (pod fields become static slices that
    # fuse into the step body) plus one combined (mask, score) row: the
    # static mask rides the score row as -inf.
    # the static mask AND the pod-valid bit ride the static-score row as
    # -inf: one fused (P, N) xs leaf instead of three per-step reads. A
    # padding row is all--inf, so its tie count is 0 and it can never be
    # assigned — the step needs no separate `valid` test (its feasible
    # count reads 0, which is also the honest verdict for a non-pod).
    masked_static = jnp.where(batch.valid[:, None] & static_mask,
                              static_score, -jnp.inf)
    xs_list = [batch, masked_static]
    if w_tt:
        xs_list.append(prefer_counts)
    if w_na:
        xs_list.append(na_counts)
    zero_i = jnp.zeros((1,), jnp.int32)
    zero_f = jnp.zeros((1,), jnp.float32)

    def step(carry: Carry, xs):
        pod, ms_row = xs[0], xs[1]
        rest = list(xs[2:])
        p_counts = rest.pop(0) if w_tt else zero_i
        na_count = rest.pop(0) if w_na else zero_f
        if use_gang:
            # group boundary crossing: first settle the group being left —
            # below quorum, restore its entry snapshot (forget every member
            # charge, rr included) — then, if this pod opens a new group,
            # snapshot the settled ledger as its revert point
            gid = pod.gang_id
            boundary = gid != carry.gang_cur
            revert = boundary & (carry.gang_cur > 0) \
                & (carry.gang_placed < carry.gang_min_cur)
            ledger = jax.tree.map(
                lambda cur, snap: jnp.where(revert, snap, cur),
                _live_ledger(carry), carry.gang_snap)
            entering = boundary & (gid > 0)
            snap = jax.tree.map(
                lambda led, sn: jnp.where(entering, led, sn),
                ledger, carry.gang_snap)
            requested, nonzero, port_count, rr, ipa, vol_any, vol_rw, \
                attach_count = ledger
            carry = Carry(
                requested=requested, nonzero=nonzero,
                port_count=port_count, rr=rr, ipa=ipa, vol_any=vol_any,
                vol_rw=vol_rw, attach_count=attach_count,
                gang_snap=snap, gang_cur=gid,
                gang_placed=jnp.where(entering, jnp.int32(0),
                                      carry.gang_placed),
                gang_min_cur=jnp.where(entering, pod.gang_min,
                                       carry.gang_min_cur))
        s_mask = ms_row > -jnp.inf
        feasible, score, breakdown = _pod_eval(
            state, g, carry, pod, s_mask, ms_row, p_counts, na_count,
            topo_onehot, prows, hard_w, domain_universe,
            explain=flags.explain)

        masked = jnp.where(feasible, score, -jnp.inf)
        node, best, ntie = _select_host(masked, feasible, carry.rr)
        assigned = ntie > 0   # a padding row is all--inf: ntie == 0
        node_idx = jnp.where(assigned, node, -1)

        add = jnp.where(assigned, 1.0, 0.0)
        new_carry = Carry(
            requested=carry.requested.at[node].add(add * pod.requests),
            nonzero=carry.nonzero.at[node].add(add * pod.nonzero_requests),
            port_count=(carry.port_count.at[node].add(add * pod.port_onehot)
                        if use_ports else carry.port_count),
            rr=carry.rr + jnp.where(assigned, jnp.uint32(1), jnp.uint32(0)),
            ipa=(interpod.ledger_add(carry.ipa, state, pod, node, add,
                                     with_terms=use_terms)
                 if use_ip_ledger else None),
            vol_any=(carry.vol_any.at[node].add(
                add * (pod.vol_want_rw + pod.vol_want_ro))
                if use_nodisk else None),
            vol_rw=(carry.vol_rw.at[node].add(add * pod.vol_want_rw)
                    if use_nodisk else None),
            attach_count=(carry.attach_count.at[node].add(add * pod.att_onehot)
                          if attach_maxes else None),
            gang_snap=carry.gang_snap,
            gang_cur=carry.gang_cur,
            gang_placed=(carry.gang_placed
                         + jnp.where(assigned & (carry.gang_cur > 0),
                                     jnp.int32(1), jnp.int32(0))
                         if use_gang else None),
            gang_min_cur=carry.gang_min_cur,
        )
        # the feasible row is emitted whole and summed AFTER the scan (an
        # in-step scalar sum measured SLOWER: the reduction does not fuse
        # into the select chain, while the row's dynamic-update-slice is one
        # 16 KB write), and the two scalar outputs ride one [2] f32 vector —
        # each ys leaf costs its own dynamic-update-slice per step (node
        # index is exact in f32: < 2^24)
        packed = jnp.stack([node_idx.astype(jnp.float32),
                            jnp.where(assigned, best, 0.0)])
        if flags.explain:
            return new_carry, (packed, feasible, breakdown)
        return new_carry, (packed, feasible)

    init = _init_carry(state, g, rr_start, domain_universe, use_gang=use_gang)
    if flags.explain:
        final, (packed_out, feas_rows, explain_rows) = jax.lax.scan(
            step, init, tuple(xs_list))
    else:
        final, (packed_out, feas_rows) = jax.lax.scan(
            step, init, tuple(xs_list))
        explain_rows = None
    nodes = packed_out[:, 0].astype(jnp.int32)
    scores = packed_out[:, 1]
    counts = jnp.sum(feas_rows.astype(jnp.int32), axis=1)

    if use_gang:
        # close out the group still open at scan end (the step only settles
        # groups on a boundary crossing; the last group has none)
        revert_last = (final.gang_cur > 0) \
            & (final.gang_placed < final.gang_min_cur)
        requested, nonzero, port_count, rr, ipa, vol_any, vol_rw, \
            attach_count = jax.tree.map(
                lambda cur, snap: jnp.where(revert_last, snap, cur),
                _live_ledger(final), final.gang_snap)
        final = final.replace(
            requested=requested, nonzero=nonzero, port_count=port_count,
            rr=rr, ipa=ipa, vol_any=vol_any, vol_rw=vol_rw,
            attach_count=attach_count)
        # mask every member of a below-quorum group out of the result: the
        # scan already forgot their ledger charges, and no partial gang may
        # reach bind. Groups are contiguous runs of equal gang_id, so
        # boundary-cumsum segment ids + one segment_sum of the per-row
        # assigned bits give each group's placed count without an O(P^2)
        # member-by-member comparison.
        gid_col = batch.gang_id
        seg = jnp.cumsum(jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             (gid_col[1:] != gid_col[:-1]).astype(jnp.int32)]))
        placed_per_seg = jax.ops.segment_sum(
            (nodes >= 0).astype(jnp.int32), seg,
            num_segments=gid_col.shape[0])
        group_failed = (gid_col > 0) & (placed_per_seg[seg] < batch.gang_min)
        nodes = jnp.where(group_failed, -1, nodes)
        scores = jnp.where(group_failed, 0.0, scores)

    if use_preempt:
        preempt_node, victim_count = _preemption_pass(
            state, batch, masked_static, nodes, final.requested, victims,
            use_gang)
    else:
        preempt_node = jnp.full(nodes.shape, -1, jnp.int32)
        victim_count = jnp.zeros(nodes.shape, jnp.int32)

    # autoscaler probe: per-node placed counts (unassigned rows scatter to
    # row 0 but contribute 0). Off — the default — leaves the field None,
    # so the program is the byte-identical pre-autoscaler HLO.
    placed_per_node = None
    if flags.scale_sim:
        placed_per_node = jax.ops.segment_sum(
            (nodes >= 0).astype(jnp.int32), jnp.maximum(nodes, 0),
            num_segments=state.valid.shape[0])

    # explainability probe: per-pod cumulative survivor counts down the
    # predicate chain. Off — the default — leaves the field None, so the
    # program is the byte-identical pre-explain HLO.
    explain_counts = explain_rows if flags.explain else None

    return SolverResult(
        assignments=nodes,
        scores=scores,
        feasible_counts=counts,
        new_requested=final.requested,
        new_nonzero=final.nonzero,
        new_port_count=final.port_count,
        rr_end=final.rr,
        new_podsel=(final.ipa.podsel_count if use_ip_ledger
                    else state.podsel_count),
        new_term=(final.ipa.term_count if use_ip_ledger and use_terms
                  else state.term_count),
        new_vol_any=final.vol_any if use_nodisk else state.vol_any,
        new_vol_rw=final.vol_rw if use_nodisk else state.vol_rw,
        new_attach=final.attach_count if attach_maxes else state.attach_count,
        preempt_node=preempt_node,
        victim_count=victim_count,
        placed_per_node=placed_per_node,
        explain_counts=explain_counts,
    )


class _PodRequests:
    """Minimal pod shim for preds.fits_resources_dyn, which reads only
    `.requests` — lets the preemption pass reuse the exact
    predicates.go:556 fit composition without scanning the full batch
    pytree a second time."""

    __slots__ = ("requests",)

    def __init__(self, requests):
        self.requests = requests


def _preemption_pass(state: ClusterState, batch: PodBatch, masked_static,
                     nodes, base_requested, victims: VictimTable,
                     use_gang: bool):
    """Batched victim selection for pods the scan left unassigned.

    Mirrors the reference preemption flow (generic_scheduler.go
    selectNodesForPreemption / pickOneNodeForPreemption) over the
    VictimTable: for each participating pod, on every statically-feasible
    node, find the minimal k such that evicting the k lowest-priority
    evictable candidates (priority strictly below the preemptor's, PDBs
    respected via `ok`) makes PodFitsResources pass against the post-batch
    ledger; then pick the node lexicographically minimizing
    (highest victim priority, victim count, node index).

    A second scan over the pod axis carries in-batch preemption bookings —
    chosen victims are marked taken and the preemptor's requests are
    charged against the freed node, so two preemptors in one batch never
    double-book the same freed capacity. Gang groups are all-or-nothing:
    if ANY participating member of a group finds no victim set, the whole
    group's bookings revert at the group boundary and its verdicts are
    masked out — no evictions happen for a gang that cannot fully land.

    Returns (preempt_node i32[P] (-1 = none), victim_count i32[P]).
    Resource-only semantics: the freed capacity re-check covers the
    resource fit; the preemptor still reschedules through the full solver
    after the evictions land, so the other dynamic predicates (ports,
    disk conflicts) are enforced at placement time, not here.
    """
    n_nodes = base_requested.shape[0]
    n_slots = victims.prio.shape[1]
    imin = jnp.iinfo(jnp.int32).min
    imax = jnp.iinfo(jnp.int32).max
    participate = batch.valid & (nodes < 0)
    static_ok = masked_static > -jnp.inf
    node_iota = jnp.arange(n_nodes, dtype=jnp.int32)
    ks = jnp.arange(n_slots + 1, dtype=jnp.float32)

    def pstep(carry, xs):
        extra, taken, snap_e, snap_t, cur, bad = carry
        req_p, prio_p, part, s_ok, gid = xs
        # gang boundary: settle the group being left (revert its bookings
        # if any member failed), then snapshot for a newly entered group
        boundary = gid != cur
        revert = boundary & (cur > 0) & bad
        extra = jnp.where(revert, snap_e, extra)
        taken = jnp.where(revert, snap_t, taken)
        entering = boundary & (gid > 0)
        snap_e = jnp.where(entering, extra, snap_e)
        snap_t = jnp.where(entering, taken, snap_t)
        bad = bad & ~boundary

        # candidates: evictable, not already booked by an earlier
        # preemptor, strictly lower priority than this pod
        cand = victims.ok & ~taken & (victims.prio < prio_p)
        cand_f = cand.astype(jnp.float32)
        rank = jnp.cumsum(cand_f, axis=1)              # f32[N, S], 1-based
        count = rank[:, -1]                            # f32[N]
        freed_cum = jnp.cumsum(cand_f[:, :, None] * victims.req, axis=1)
        ledger = base_requested + extra
        # ledgers after evicting the first 0..S candidates: [S+1, N, R]
        adj = jnp.concatenate(
            [ledger[None], ledger[None] - jnp.moveaxis(freed_cum, 1, 0)],
            axis=0)
        shim = _PodRequests(req_p)
        fit_k = jax.vmap(
            lambda led: preds.fits_resources_dyn(state, shim, led))(adj)
        # k beyond the candidate count frees nothing more — exclude it so
        # "minimal k" is well-defined and (node, k) names real victims
        ok_k = fit_k & (ks[:, None] <= count[None, :]) & s_ok[None, :]
        feas = jnp.any(ok_k, axis=0)                   # bool[N]
        k_n = jnp.argmax(ok_k, axis=0).astype(jnp.int32)  # first feasible k
        chosen = cand & (rank <= k_n[:, None].astype(jnp.float32))
        # highest victim priority of the minimal set (imin when k == 0:
        # a no-eviction node dominates every evicting one)
        top_prio = jnp.max(jnp.where(chosen, victims.prio, imin), axis=1)
        # pickOneNodeForPreemption: lexicographic min over
        # (top victim priority, victim count, node index)
        tp = jnp.where(feas, top_prio, imax)
        m1 = feas & (tp == jnp.min(tp))
        kk = jnp.where(m1, k_n, imax)
        m2 = m1 & (kk == jnp.min(kk))
        node = jnp.argmax(m2).astype(jnp.int32)
        found = jnp.any(feas)
        act = part & found

        k_sel = k_n[node]
        freed_sel = jnp.where(
            k_sel > 0, freed_cum[node, jnp.maximum(k_sel - 1, 0)], 0.0)
        add = jnp.where(act, 1.0, 0.0)
        extra = extra.at[node].add(add * (req_p - freed_sel))
        taken = taken | (chosen & (node_iota == node)[:, None] & act)
        bad = bad | (part & ~found & (gid > 0))
        out = jnp.stack([jnp.where(act, node, jnp.int32(-1)),
                         jnp.where(act, k_sel, jnp.int32(0))])
        return (extra, taken, snap_e, snap_t, gid, bad), out

    zero_extra = jnp.zeros_like(base_requested)
    zero_taken = jnp.zeros((n_nodes, n_slots), bool)
    init = (zero_extra, zero_taken, zero_extra, zero_taken,
            jnp.int32(0), jnp.bool_(False))
    _, packed = jax.lax.scan(
        pstep, init,
        (batch.requests, batch.priority, participate, static_ok,
         batch.gang_id))
    preempt_node = packed[:, 0]
    victim_count = packed[:, 1]

    if use_gang:
        # all-or-nothing over each group's PARTICIPANTS: if any failed to
        # find a victim set, the scan already reverted the group's
        # bookings — mask its verdicts so the driver evicts nothing
        gid_col = batch.gang_id
        seg = jnp.cumsum(jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             (gid_col[1:] != gid_col[:-1]).astype(jnp.int32)]))
        n_part = jax.ops.segment_sum(
            participate.astype(jnp.int32), seg,
            num_segments=gid_col.shape[0])
        n_found = jax.ops.segment_sum(
            (participate & (preempt_node >= 0)).astype(jnp.int32), seg,
            num_segments=gid_col.shape[0])
        group_bad = (gid_col > 0) & (n_found[seg] < n_part[seg])
        preempt_node = jnp.where(group_bad, -1, preempt_node)
        victim_count = jnp.where(group_bad, 0, victim_count)
    return preempt_node, victim_count


def evaluate_pod(
    state: ClusterState,
    pod,
    policy: Policy = DEFAULT_POLICY,
    caps=None,
    prows=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full-policy (feasible bool[N], score f32[N]) for ONE encoded pod row
    against the accounted cluster state — the extender's Filter/Prioritize
    surface (core/extender.go:100,143).

    Runs the exact `_pod_eval` the solver's scan step runs, with the assume
    ledger initialized from `state` and no in-batch predecessors — i.e. the
    verdict the solver would reach scheduling this pod next. Pure; jit with
    `policy` (and `caps`) static. Always compiled ALL_ACTIVE: the extender
    serves one pod per request, so batch-content gating buys nothing and
    full faithfulness costs nothing.
    """
    state = jax.tree.map(jnp.asarray, state)
    pod = jax.tree.map(jnp.asarray, pod)
    g = policy_gates(policy, ALL_ACTIVE)
    if prows is None and (g.svcanti or active_label_presence(policy)
                          or active_label_priorities(policy)):
        raise ValueError(
            "policy carries argument registrations (labelsPresence / "
            "labelPreference / serviceAntiAffinity) but no PolicyRows were "
            "given — build them with models.policy.build_policy_rows")
    hard_w = float(policy.hard_pod_affinity_weight)
    domain_universe = caps.domain_universe if caps else DEFAULT_DOMAIN_UNIVERSE

    base_mask, base_score = _base_rows(state, policy, prows, g)
    s_mask = _static_mask(state, pod, policy, base_mask)
    s_score = _static_score(state, pod, policy, base_score)
    p_counts = (preds.count_untolerated_prefer_taints(state, pod)
                if g.w_tt else jnp.zeros((1,), jnp.int32))
    na_count = (prios.node_affinity_counts(state, pod)
                if g.w_na else jnp.zeros((1,), jnp.float32))
    topo_onehot = (interpod.topology_onehot(state.topology, domain_universe)
                   if g.use_ip_ledger else None)
    carry = _init_carry(state, g, 0, domain_universe)
    feasible, score, _ = _pod_eval(
        state, g, carry, pod, s_mask, s_score, p_counts, na_count,
        topo_onehot, prows, hard_w, domain_universe)
    return feasible, score
