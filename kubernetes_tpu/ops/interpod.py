"""Inter-pod (anti-)affinity kernels: the O(pods x nodes x terms) case.

Re-expresses the reference's PodAffinityChecker (predicates.go:982
InterPodAffinityMatches, :1139 satisfiesExistingPodsAntiAffinity, :1181
satisfiesPodsAffinityAntiAffinity) and CalculateInterPodAffinityPriority
(interpod_affinity.go) as tensor ops over interned universes:

- selectors -> pod-selector universe UQ; `podsel_count[N, UQ]` counts matching
  pods per node; `total_q[UQ]` counts matching pods anywhere.
- existing-pod terms -> carried-term universe UE with per-entry attributes
  (selector id, topology slot, signed weight, kind); `term_count[N, UE]`
  counts carriers per node.
- topology domains -> per-slot domain ids in `topology[N, K]`; domain-level
  aggregates `dom_*[K, D, U]` turn "matching pod exists in my topology
  domain" into a gather instead of an O(N^2) comparison.

Hostname short-circuit: slot 0 domains are per-node (hostname label values
are assumed unique per node, which the encoder guarantees when the label is
absent), so hostname-scoped counts read the node-level arrays directly and
the domain axis D only needs to cover zone/region/custom-key cardinalities.

The empty-topologyKey preferred-term case ("same in any default failure
domain", priorityutil.Topologies) is computed exactly by inclusion-exclusion:
union = hostC*(1-has_zone)*(1-has_region) + zoneC + regionC - zoneRegionC,
using the virtual composite (zone, region) slot (layout.TOPO_ZONE_REGION).

All counts flow through the solver scan so earlier in-batch assignments are
visible to later pods, matching the serial scheduleOne semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_tpu.ops.priorities import FLOOR_EPS
from kubernetes_tpu.state.cluster_state import ClusterState
from kubernetes_tpu.state.layout import (
    MAX_PRIORITY,
    TKEY_DEFAULT_UNION,
    TKEY_INVALID,
    TOPO_HOSTNAME,
    TOPO_REGION,
    TOPO_ZONE,
    TOPO_ZONE_REGION,
    TermKind,
)
from kubernetes_tpu.state.pod_batch import PodBatch


@struct.dataclass
class AffinityLedger:
    """The scan-carried inter-pod affinity state. Term-universe fields are
    None (empty pytree) when only the podsel consumers (SelectorSpread /
    ServiceAntiAffinity) are active — they read node-level podsel counts
    only."""

    podsel_count: jnp.ndarray   # f32[N, UQ]
    total_q: jnp.ndarray        # f32[UQ]
    term_count: object = None   # f32[N, UE] | None
    dom_podsel: object = None   # f32[K, D, UQ] | None
    dom_term: object = None     # f32[K, D, UE] | None
    total_e: object = None      # f32[UE] | None


def domain_aggregates(topology: jnp.ndarray, counts: jnp.ndarray,
                      domain_universe: int) -> jnp.ndarray:
    """f32[K, D, U]: per-domain sums of per-node counts. one_hot maps the
    -1 (no label) sentinel to an all-zero row, excluding those nodes."""
    onehot = jax.nn.one_hot(topology, domain_universe, axis=-1)  # [N, K, D]
    return jnp.einsum("nkd,nu->kdu", onehot, counts)


def topology_onehot(topology: jnp.ndarray, domain_universe: int) -> jnp.ndarray:
    """f32[K, N, D]: one-hot of each node's domain id per topology slot
    (-1 sentinel -> zero row). Pod-independent — compute once per batch and
    thread through the per-pod kernels so domain->node broadcasts become MXU
    matmuls instead of device gathers (dynamic gathers serialize on the TPU
    scalar core and dominated the round-1 solve)."""
    return jnp.transpose(jax.nn.one_hot(topology, domain_universe, axis=-1),
                         (1, 0, 2))


def make_ledger(state: ClusterState, domain_universe: int,
                with_terms: bool = True) -> AffinityLedger:
    if not with_terms:
        return AffinityLedger(
            podsel_count=state.podsel_count,
            total_q=jnp.sum(state.podsel_count, axis=0),
        )
    return AffinityLedger(
        podsel_count=state.podsel_count,
        term_count=state.term_count,
        dom_podsel=domain_aggregates(state.topology, state.podsel_count,
                                     domain_universe),
        dom_term=domain_aggregates(state.topology, state.term_count,
                                   domain_universe),
        total_q=jnp.sum(state.podsel_count, axis=0),
        total_e=jnp.sum(state.term_count, axis=0),
    )


def _slot_counts(topo_onehot: jnp.ndarray, node_counts: jnp.ndarray,
                 dom_counts: jnp.ndarray) -> jnp.ndarray:
    """f32[K, N, U]: for every topology slot k, the count of matches in node
    n's k-domain. Slot 0 (hostname) reads node-level counts directly; the
    rest broadcast domain aggregates back to nodes in ONE batched
    [K,N,D]x[K,D,U] contraction (the -1 sentinel's zero one-hot row masks
    automatically). K separate [N,D]@[D,U] matmuls at U≈32 ran at ~25%
    lane efficiency each and were the measured device wall of the interpod
    config (PERF.md r4); the batched einsum tiles the K axis together."""
    out = jnp.einsum("knd,kdu->knu", topo_onehot, dom_counts)
    return out.at[0].set(node_counts)


def _union_counts(topology: jnp.ndarray, slot_counts: jnp.ndarray,
                  node_counts: jnp.ndarray) -> jnp.ndarray:
    """f32[N, U]: matches in the union of the default failure domains
    (inclusion-exclusion; see module docstring)."""
    has_zone = (topology[:, TOPO_ZONE] >= 0)[:, None]
    has_region = (topology[:, TOPO_REGION] >= 0)[:, None]
    host_part = node_counts * (~has_zone) * (~has_region)
    return (host_part + slot_counts[TOPO_ZONE] + slot_counts[TOPO_REGION]
            - slot_counts[TOPO_ZONE_REGION])


def _counts_by_tkey(tkey: jnp.ndarray, slot_counts: jnp.ndarray,
                    union: jnp.ndarray) -> jnp.ndarray:
    """f32[N, U]: per-entry counts selected by each entry's topology code
    (tkey: i32[U]). TKEY_INVALID selects 0; TKEY_DEFAULT_UNION the union."""
    k_slots = slot_counts.shape[0]
    out = jnp.where(tkey[None, :] == TKEY_DEFAULT_UNION, union, 0.0)
    for k in range(k_slots):
        out = out + jnp.where(tkey[None, :] == k, slot_counts[k], 0.0)
    return out


def _scalar_count(q, tkey, slots, union_all) -> jnp.ndarray:
    """f32[N]: count for one (q, tkey) own-term slot (q, tkey traced
    scalars; q >= 0). slots: the f32[K, N, U] stack from _slot_counts —
    indexing it replaces the old per-term [N,D]@[D] matvecs (the stack is
    already computed for the carried-term selections, so XLA CSE shares
    it)."""
    k_slots = slots.shape[0]
    out = jnp.where(tkey == TKEY_DEFAULT_UNION, union_all[:, q], 0.0)
    for k in range(k_slots):
        out = out + jnp.where(tkey == k, slots[k, :, q], 0.0)
    return out


def interpod_feasible(state: ClusterState, pod, ledger: AffinityLedger,
                      topo_onehot=None) -> jnp.ndarray:
    """bool[N]: InterPodAffinityMatches for one pod against every node."""
    topology = state.topology
    if topo_onehot is None:
        topo_onehot = topology_onehot(topology, ledger.dom_podsel.shape[1])
    n = topology.shape[0]

    # -- existing pods' required anti-affinity (predicates.go:1139) --
    term_q = state.term_q
    match_e = jnp.where(term_q >= 0,
                        pod.pod_matches_q[jnp.clip(term_q, 0)], 0.0)  # f32[UE]
    anti = state.term_kind == TermKind.ANTI_REQ
    active = anti & (match_e > 0)
    # a carried required-anti term with an unparseable selector poisons all
    # scheduling while any carrier exists (error path, predicates.go:1156)
    poisoned = jnp.any(anti & state.term_poison & (ledger.total_e > 0))

    slot_e = _slot_counts(topo_onehot, ledger.term_count, ledger.dom_term)
    union_e = _union_counts(topology, slot_e, ledger.term_count)
    cnt_e = _counts_by_tkey(state.term_tkey, slot_e, union_e)      # [N, UE]
    # empty topologyKey on a required anti term rejects every node while a
    # carrier exists (predicates.go:1162-1165)
    invalid_term = (state.term_tkey == TKEY_INVALID) & (ledger.total_e > 0)
    violations = jnp.sum(jnp.where(active[None, :],
                                   cnt_e + invalid_term[None, :], 0.0), axis=1)
    ok = (violations == 0) & ~poisoned

    slot_q = _slot_counts(topo_onehot, ledger.podsel_count,
                          ledger.dom_podsel)
    union_q = _union_counts(topology, slot_q, ledger.podsel_count)

    # -- the pod's own required affinity terms (predicates.go:1189) --
    for t in range(pod.paff_q.shape[0]):
        q = pod.paff_q[t]
        used = q >= 0
        qc = jnp.clip(q, 0)
        cnt = _scalar_count(qc, pod.paff_tkey[t], slot_q, union_q)
        exists = ledger.total_q[qc] > 0
        self_match = pod.pod_matches_q[qc] > 0
        # term holds if a matching pod is in this node's domain; else only
        # the first-pod-of-collection escape applies (predicates.go:1193)
        term_ok = (cnt > 0) | (~exists & self_match)
        ok = ok & (~used | term_ok)

    # -- the pod's own required anti-affinity terms (predicates.go:1221) --
    for t in range(pod.panti_q.shape[0]):
        q = pod.panti_q[t]
        used = q >= 0
        qc = jnp.clip(q, 0)
        cnt = _scalar_count(qc, pod.panti_tkey[t], slot_q, union_q)
        ok = ok & (~used | (cnt == 0))

    return ok & ~pod.ipaff_fail & jnp.ones((n,), bool)


def interpod_counts(state: ClusterState, pod, ledger: AffinityLedger,
                    hard_weight: float, topo_onehot=None) -> jnp.ndarray:
    """f32[N]: the weighted-count map of CalculateInterPodAffinityPriority —
    the pod's own preferred terms plus the symmetric contributions of
    existing pods' terms (hard affinity weighted by hard_weight)."""
    topology = state.topology
    if topo_onehot is None:
        topo_onehot = topology_onehot(topology, ledger.dom_podsel.shape[1])

    slot_q = _slot_counts(topo_onehot, ledger.podsel_count, ledger.dom_podsel)
    union_q = _union_counts(topology, slot_q, ledger.podsel_count)
    counts = jnp.zeros((topology.shape[0],), jnp.float32)

    for t in range(pod.ppref_q.shape[0]):
        q = pod.ppref_q[t]
        used = q >= 0
        qc = jnp.clip(q, 0)
        cnt = _scalar_count(qc, pod.ppref_tkey[t], slot_q, union_q)
        counts = counts + jnp.where(used, pod.ppref_w[t] * cnt, 0.0)

    # symmetric: existing pods' terms matching this pod
    term_q = state.term_q
    match_e = jnp.where(term_q >= 0,
                        pod.pod_matches_q[jnp.clip(term_q, 0)], 0.0)
    eff_w = state.term_weight + hard_weight * (
        state.term_kind == TermKind.AFF_REQ).astype(jnp.float32)
    slot_e = _slot_counts(topo_onehot, ledger.term_count, ledger.dom_term)
    union_e = _union_counts(topology, slot_e, ledger.term_count)
    cnt_e = _counts_by_tkey(state.term_tkey, slot_e, union_e)
    counts = counts + jnp.sum(cnt_e * (match_e * eff_w)[None, :], axis=1)
    return counts


def interpod_score(counts: jnp.ndarray, feasible: jnp.ndarray) -> jnp.ndarray:
    """The reduce: fScore = MaxPriority * (c - min) / (max - min) with min and
    max initialized to 0 (interpod_affinity.go:214-233), truncated to int."""
    masked = jnp.where(feasible, counts, 0.0)
    max_c = jnp.maximum(jnp.max(masked), 0.0)
    min_c = jnp.minimum(jnp.min(masked), 0.0)
    spread = max_c - min_c
    score = jnp.trunc(MAX_PRIORITY * (counts - min_c)
                      / jnp.maximum(spread, 1.0) + FLOOR_EPS)
    return jnp.where(spread > 0, score, 0.0)


def ledger_add(ledger: AffinityLedger, state: ClusterState, pod, node,
               add: jnp.ndarray, with_terms: bool = True) -> AffinityLedger:
    """Account an assignment into the affinity ledger (add is 1.0 or 0.0)."""
    q_row = add * pod.pod_matches_q
    if not with_terms:
        return AffinityLedger(
            podsel_count=ledger.podsel_count.at[node].add(q_row),
            total_q=ledger.total_q + q_row,
        )
    e_row = add * pod.pod_carries_e
    doms = state.topology[node]                       # i32[K]
    k_idx = jnp.arange(doms.shape[0])
    mask = (doms >= 0) & (k_idx != TOPO_HOSTNAME)
    dmask = mask.astype(jnp.float32)[:, None]
    return AffinityLedger(
        podsel_count=ledger.podsel_count.at[node].add(q_row),
        term_count=ledger.term_count.at[node].add(e_row),
        dom_podsel=ledger.dom_podsel.at[k_idx, jnp.clip(doms, 0)].add(
            dmask * q_row[None, :]),
        dom_term=ledger.dom_term.at[k_idx, jnp.clip(doms, 0)].add(
            dmask * e_row[None, :]),
        total_q=ledger.total_q + q_row,
        total_e=ledger.total_e + e_row,
    )
