"""Vectorized scheduling predicates: masked ops over the node axis.

Each kernel re-expresses one reference `FitPredicate(pod, meta, nodeInfo) ->
bool` (signature at plugin/pkg/scheduler/algorithm/types.go:31) as a function
of one encoded pod against *all* nodes at once, returning `bool[N]`. Batch
evaluation over P pods is `jax.vmap` — the TPU-native replacement for the
`workqueue.Parallelize(16, len(nodes), checkNode)` goroutine fan-out
(reference plugin/pkg/scheduler/core/generic_scheduler.go:204).

The irregular string-matching predicates ride the MXU: selector terms, taints
and host ports are interned into small universes (state/cluster_state.py), so
matching is `one_hot_row @ membership_matrix.T` — under vmap, one (P x U) x
(U x N) matmul per predicate. This replaces the reference's per-node Go map
lookups (predicates.go:686,859,1241) and is what makes 15k-node clusters a
single small device program.

Covered predicates (reference algorithm/predicates/predicates.go):
- PodFitsResources      (:556)  -> fits_resources
- PodFitsHost           (:698)  -> fits_host
- PodFitsHostPorts      (:859)  -> fits_host_ports
- PodMatchNodeSelector  (:686)  -> match_node_selector  (map-form nodeSelector;
                                   required node-affinity terms arrive with
                                   the affinity op set)
- PodToleratesNodeTaints(:1241) -> tolerates_node_taints
- CheckNodeCondition    (:1306), CheckNodeMemoryPressure (:1274),
  CheckNodeDiskPressure (:1296) -> check_node_condition / check_*_pressure
- unschedulable lister filter   -> node_schedulable (not policy-gated)

Volume predicates (atom grammars in state/volumes.py):
- NoDiskConflict        (:183)  -> no_disk_conflict
- MaxPDVolumeCount      (:215)  -> max_attach_ok (EBS/GCE PD/Azure Disk)
- NoVolumeZoneConflict  (:395)  -> volume_zone
- NoVolumeNodeConflict  (:1345) -> volume_node

All kernels are pure, jit-safe, and shard over the node axis: elementwise ops,
reductions over static universe axes, and node-sharded matmuls.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.state.cluster_state import ClusterState
from kubernetes_tpu.state.layout import Condition, Effect, Resource, TolOp, VolType
from kubernetes_tpu.state.pod_batch import PodBatch


def _requests_all_zero(r) -> jnp.ndarray:
    """The all-zero shortcut: a pod requesting nothing only pays the
    pod-count check (predicates.go:576-578)."""
    return (
        (r[Resource.CPU] == 0) & (r[Resource.MEMORY] == 0) & (r[Resource.GPU] == 0)
        & (r[Resource.SCRATCH] == 0) & (r[Resource.OVERLAY] == 0)
    )


def _storage_fit(req, alloc, r) -> jnp.ndarray:
    """Storage half of PodFitsResources: when the node exposes no overlay
    allocatable, overlay requests fall through to scratch space
    (predicates.go:590-605)."""
    no_overlay = alloc[:, Resource.OVERLAY] == 0
    scratch_req_no_overlay = r[Resource.SCRATCH] + r[Resource.OVERLAY]
    node_scratch_no_overlay = req[:, Resource.OVERLAY] + req[:, Resource.SCRATCH]
    scratch_ok_no_overlay = (
        alloc[:, Resource.SCRATCH] >= scratch_req_no_overlay + node_scratch_no_overlay
    )
    scratch_ok_overlay = (
        alloc[:, Resource.SCRATCH] >= r[Resource.SCRATCH] + req[:, Resource.SCRATCH]
    ) & (alloc[:, Resource.OVERLAY] >= r[Resource.OVERLAY] + req[:, Resource.OVERLAY])
    return jnp.where(no_overlay, scratch_ok_no_overlay, scratch_ok_overlay)


def fits_resources_static(state: ClusterState, pod: PodBatch,
                          dyn_gpu: bool, dyn_storage: bool) -> jnp.ndarray:
    """The assignment-independent remainder of PodFitsResources under batch
    gates: resource columns no pod in the batch requests never change through
    the scan, so their compares hold against the batch-start ledger for the
    whole batch and hoist out of the per-pod step (solver BatchFlags.gpu/
    storage). The all-zero OR is distributed across the split —
    `(z | a) & (z | b) == z | (a & b)` keeps the conjunction with
    `fits_resources_dyn` exactly equal to predicates.go:556's composition."""
    req = state.requested
    alloc = state.allocatable
    ok = jnp.ones(alloc.shape[0], dtype=bool)
    r = pod.requests
    if not dyn_gpu:
        ok = ok & (alloc[:, Resource.GPU] >= r[Resource.GPU] + req[:, Resource.GPU])
    if not dyn_storage:
        ok = ok & _storage_fit(req, alloc, r)
    return _requests_all_zero(r) | ok


def fits_resources_dyn(state: ClusterState, pod: PodBatch, requested,
                       dyn_gpu: bool = True,
                       dyn_storage: bool = True) -> jnp.ndarray:
    """The in-scan half of PodFitsResources: the pod count always moves with
    in-batch claims; cpu/mem always (every claim charges at least the
    non-zero scoring defaults is irrelevant here — requests themselves may be
    zero, but the compare is cheap and claims can change it); gpu/storage
    only when the batch requests them (`dyn_*`)."""
    req = requested
    alloc = state.allocatable
    pods_ok = req[:, Resource.PODS] + 1.0 <= alloc[:, Resource.PODS]
    r = pod.requests

    def fits(row):
        return alloc[:, row] >= r[row] + req[:, row]

    basic = fits(Resource.CPU) & fits(Resource.MEMORY)
    if dyn_gpu:
        basic = basic & fits(Resource.GPU)
    if dyn_storage:
        basic = basic & _storage_fit(req, alloc, r)
    return pods_ok & (_requests_all_zero(r) | basic)


def fits_resources(state: ClusterState, pod: PodBatch, requested=None) -> jnp.ndarray:
    """PodFitsResources (predicates.go:556) against all nodes.

    `requested` overrides state.requested — the solver passes the running
    ledger that includes in-batch assumptions (the analog of scheduling
    against assumed pods, scheduler.go:188).
    """
    req = state.requested if requested is None else requested
    return fits_resources_dyn(state, pod, req)


def fits_host(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """PodFitsHost (predicates.go:698): spec.nodeName pins the node."""
    unset = pod.node_name_lo == 0
    match = (state.name_lo == pod.node_name_lo) & (state.name_hi == pod.node_name_hi)
    return unset | match


def fits_host_ports(state: ClusterState, pod: PodBatch, port_count=None) -> jnp.ndarray:
    """PodFitsHostPorts (predicates.go:859): no requested host port may be in
    use. One matvec: conflicts = port_count[N, UP] @ pod_onehot[UP]."""
    counts = state.port_count if port_count is None else port_count
    conflicts = counts @ pod.port_onehot
    return conflicts == 0.0


def node_affinity_ok(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """The required-node-affinity half of PodMatchNodeSelector: OR over
    terms, each term an AND over interned requirements —
    `naff_onehot[T, UR] @ req_member[N, UR].T` gives per-term
    satisfied-requirement counts, a term holds when every requirement
    matched (count equality), and dead terms (empty/unparseable,
    predicates.go:628-645) never hold. Shared by match_node_selector and
    the Pallas fused path's XLA remainder (solver._static_rest)."""
    term_sat = pod.naff_onehot @ state.req_member.T          # f32[T, N]
    term_ok = (term_sat >= pod.naff_count[:, None]) & pod.naff_ok[:, None]
    return (~pod.naff_has) | jnp.any(term_ok, axis=0)


def match_node_selector(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """PodMatchNodeSelector (predicates.go:686 podMatchesNodeLabels): the
    map-form nodeSelector AND any required node affinity must both hold.
    nodeSelector: satisfied-term count from one matvec against the selector
    membership matrix."""
    satisfied = state.sel_member @ pod.sel_onehot
    sel_ok = satisfied >= pod.sel_count
    return sel_ok & node_affinity_ok(state, pod)


def _tolerated_universe(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """bool[UT]: universe taint u is tolerated by some toleration of the pod
    (v1 ToleratesTaint semantics, see api.objects.Toleration.tolerates):
    empty toleration key matches every taint key; Equal compares values only;
    Exists ignores values; empty toleration effect matches every effect."""
    out = jnp.zeros(state.taint_u_key.shape[0], dtype=bool)
    for j in range(pod.tol_op.shape[0]):
        op = pod.tol_op[j]
        used = op != TolOp.NONE
        eff_ok = (pod.tol_effect[j] == Effect.NONE) | (
            pod.tol_effect[j] == state.taint_u_effect)
        key_ok = (pod.tol_key[j] == 0) | (pod.tol_key[j] == state.taint_u_key)
        value_ok = jnp.where(
            op == TolOp.EXISTS,
            True,
            (pod.tol_val_lo[j] == state.taint_u_val_lo)
            & (pod.tol_val_hi[j] == state.taint_u_val_hi),
        )
        out |= used & eff_ok & key_ok & value_ok
    return out


def tolerates_node_taints(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """PodToleratesNodeTaints (predicates.go:1241): every NoSchedule/NoExecute
    taint must be tolerated (PreferNoSchedule is scoring-only). One matvec:
    violations = hard_member[N, UT] @ untolerated[UT]."""
    untolerated = 1.0 - _tolerated_universe(state, pod).astype(jnp.float32)
    violations = state.taint_hard_member @ untolerated
    return violations == 0.0


def count_untolerated_prefer_taints(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """f32[N]: untolerated PreferNoSchedule taints per node — the map half of
    the TaintToleration priority (priorities/taint_toleration.go:29)."""
    untolerated = 1.0 - _tolerated_universe(state, pod).astype(jnp.float32)
    return state.taint_prefer_member @ untolerated


def node_schedulable(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """spec.unschedulable exclusion — NOT a policy predicate: the reference
    applies it unconditionally in the scheduler's node lister
    (factory.go getNodeConditionPredicate), so the solver always ANDs this in."""
    return (state.conditions & jnp.uint32(Condition.UNSCHEDULABLE)) == 0


def check_node_condition(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """CheckNodeCondition (predicates.go:1306): NotReady, NetworkUnavailable
    and OutOfDisk reject all pods."""
    hard = Condition.NOT_READY | Condition.NETWORK_UNAVAILABLE | Condition.OUT_OF_DISK
    return (state.conditions & jnp.uint32(hard)) == 0


def check_memory_pressure(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """CheckNodeMemoryPressure (predicates.go:1274): rejects only BestEffort
    pods."""
    pressure = (state.conditions & jnp.uint32(Condition.MEMORY_PRESSURE)) != 0
    return ~(pressure & pod.best_effort)


def check_disk_pressure(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """CheckNodeDiskPressure (predicates.go:1296): rejects all pods."""
    return (state.conditions & jnp.uint32(Condition.DISK_PRESSURE)) == 0


def no_disk_conflict(state: ClusterState, pod: PodBatch,
                     vol_any=None, vol_rw=None) -> jnp.ndarray:
    """NoDiskConflict (predicates.go:183): a wanted read-write atom conflicts
    with any existing user; a wanted read-only atom conflicts with a
    read-write user. Two matvecs over the conflict-atom universe."""
    v_any = state.vol_any if vol_any is None else vol_any
    v_rw = state.vol_rw if vol_rw is None else vol_rw
    conflicts = v_any @ pod.vol_want_rw + v_rw @ pod.vol_want_ro
    return conflicts == 0.0


def max_attach_ok(state: ClusterState, pod: PodBatch, maxes: tuple,
                  attach_count=None) -> jnp.ndarray:
    """MaxPDVolumeCount for the configured filters (predicates.go:281-320).

    `maxes` is a static tuple of (VolType code, limit). For each filter:
    distinct existing atoms of that type on the node, plus the pod's wanted
    atoms not already there, must not exceed the limit. VolType.ANY atoms
    (unresolvable claims) count toward every filter."""
    counts = state.attach_count if attach_count is None else attach_count
    present = (counts > 0).astype(jnp.float32)          # [N, UA]
    absent = 1.0 - present
    ok = jnp.ones(present.shape[0], dtype=bool)
    for vtype, limit in maxes:
        mask = ((state.attach_type == vtype)
                | (state.attach_type == VolType.ANY)).astype(jnp.float32)
        # a pod wanting no atoms of this type passes before any counting
        # (the len(newVolumes)==0 quick return, predicates.go:296)
        wants = pod.att_onehot @ mask > 0
        existing = present @ mask                        # distinct, [N]
        new = (absent * pod.att_onehot[None, :]) @ mask  # not-yet-attached
        ok = ok & (~wants | (existing + new <= float(limit)))
    return ok & ~pod.att_fail


def volume_zone(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """NoVolumeZoneConflict (predicates.go:395): nodes carrying zone/region
    labels must match every bound PV's zone/region label exactly; nodes with
    no zone constraints pass unconditionally (predicates.go:421-427).

    A pod whose claim chain fails to resolve errors the whole scheduling
    attempt whenever a zoned node would have evaluated it (the predicate
    error path aggregated by findNodesThatFit, generic_scheduler.go:182-199;
    the reference's exact scope depends on unspecified predicate map order —
    here it is deterministically "any valid zoned node exists")."""
    from kubernetes_tpu.state.layout import TOPO_REGION, TOPO_ZONE

    unconstrained = (state.topology[:, TOPO_ZONE] < 0) & (
        state.topology[:, TOPO_REGION] < 0)
    satisfied = state.sel_member @ pod.vz_onehot
    fail_kill = pod.vz_fail & jnp.any(state.valid & ~unconstrained)
    return (unconstrained | ((satisfied >= pod.vz_count) & ~pod.vz_fail)) \
        & ~fail_kill


def volume_node(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """NoVolumeNodeConflict (predicates.go:1345): every bound PV's
    node-affinity selector must match the node."""
    satisfied = state.volsel_member @ pod.vs_onehot
    return (satisfied >= pod.vs_count) & ~pod.vs_fail


def label_presence_ok(state: ClusterState, pres_onehot, pres_count,
                      abs_onehot) -> jnp.ndarray:
    """CheckNodeLabelPresence (predicates.go:737): configured labels must all
    be present (pres) / all absent (abs), value-independent. Pod-independent —
    one mask per batch from the PolicyRows Exists-requirement rows."""
    have = state.req_member @ pres_onehot
    stray = state.req_member @ abs_onehot
    return (have >= pres_count) & (stray == 0)


def service_affinity(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """checkServiceAffinity (predicates.go:821): the node must carry the
    pod's resolved affinity labels (pinned by nodeSelector or backfilled
    from an existing service pod's node — state/spreading.py)."""
    satisfied = state.req_member @ pod.svcaff_onehot
    return (satisfied >= pod.svcaff_count) & ~pod.svcaff_fail


def node_conditions_ok(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """All condition checks plus the unschedulable filter (convenience
    conjunction for full-default evaluation)."""
    return (
        node_schedulable(state, pod)
        & check_node_condition(state, pod)
        & check_memory_pressure(state, pod)
        & check_disk_pressure(state, pod)
    )


def static_feasibility(state: ClusterState, pod: PodBatch) -> jnp.ndarray:
    """All assignment-independent predicates for one pod: bool[N].

    Resource and port checks against the *running* ledger happen in the
    solver; this mask covers everything that in-batch assignments cannot
    change. Invalid (padding) node rows are always infeasible.
    """
    return (
        state.valid
        & pod.valid
        & fits_host(state, pod)
        & match_node_selector(state, pod)
        & tolerates_node_taints(state, pod)
        & node_conditions_ok(state, pod)
    )
