"""Cloud provider SPI + the fake provider.

The pkg/cloudprovider analog (Interface at pkg/cloudprovider/cloud.go:
LoadBalancer/Instances/Zones sub-interfaces; nine real providers + the fake
at pkg/cloudprovider/providers/fake used by every controller test). The
service controller consumes LoadBalancer; the node lifecycle consumes
Instances (does a cloud instance still exist?); Zones labels nodes; the
cluster autoscaler consumes NodeGroups (the autoscaler/cloudprovider
CloudProvider/NodeGroup contract: TargetSize/IncreaseSize/DeleteNodes plus
a template node per group for what-if simulation)."""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

# the autoscaler's group-membership label on template/created nodes (the
# upstream analog is the per-cloud group tag, e.g. the MIG/ASG name label)
NODE_GROUP_LABEL = "ktpu.io/nodegroup"
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
REGION_LABEL = "failure-domain.beta.kubernetes.io/region"


@dataclass
class LoadBalancerStatus:
    ingress_ip: str = ""


class CloudProvider:
    """The Interface subset controllers consume (cloud.go:43-118)."""

    # -- LoadBalancer --
    def get_load_balancer(self, service) -> LoadBalancerStatus | None:
        raise NotImplementedError

    def ensure_load_balancer(self, service, node_names) -> LoadBalancerStatus:
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, service) -> None:
        raise NotImplementedError

    # -- Instances --
    def instance_exists(self, node_name: str) -> bool:
        raise NotImplementedError

    # -- Zones --
    def get_zone(self, node_name: str) -> tuple[str, str]:
        """(failure domain, region)."""
        raise NotImplementedError

    # -- Disks (the Attacher/Detacher seam the attachable volume plugin
    # family consumes — gce_pd/attacher.go, aws_ebs/attacher.go) --
    def attach_disk(self, disk_name: str, node_name: str,
                    read_only: bool = False) -> None:
        raise NotImplementedError

    def detach_disk(self, disk_name: str, node_name: str) -> None:
        raise NotImplementedError

    def disk_attached_to(self, disk_name: str) -> str | None:
        raise NotImplementedError

    # -- Routes (cloud.go Routes interface; route controller consumer) --
    def list_routes(self) -> dict[str, str]:
        """node name -> destination CIDR."""
        raise NotImplementedError

    def create_route(self, node_name: str, cidr: str) -> None:
        raise NotImplementedError

    def delete_route(self, node_name: str) -> None:
        raise NotImplementedError

    # -- NodeGroups (the cluster-autoscaler SPI; default: no groups, so a
    # provider that predates autoscaling keeps working unchanged) --
    def node_groups(self) -> list[str]:
        """Names of the autoscalable node groups, stable order."""
        return []

    def node_group_of(self, node_name: str) -> str | None:
        """The group an instance belongs to (None: unmanaged node — the
        autoscaler never scales it down)."""
        return None

    def group_size_range(self, group: str) -> tuple[int, int]:
        """(min_size, max_size) bounds for the group."""
        raise NotImplementedError

    def target_size(self, group: str) -> int:
        """Current desired instance count (cloud-side source of truth)."""
        raise NotImplementedError

    def increase_size(self, group: str, delta: int) -> list[str]:
        """Grow the group by `delta` instances; returns the new instance
        names. Must reject growth past max_size."""
        raise NotImplementedError

    def delete_nodes(self, group: str, node_names: list[str]) -> None:
        """Remove specific instances from the group (scale-down). Must
        reject shrinking below min_size or deleting a non-member."""
        raise NotImplementedError

    def template_node(self, group: str):
        """A Node object shaped like a fresh instance of this group
        (allocatable, labels incl. zone, Ready condition) — what the
        autoscaler encodes as hypothetical rows in probe solves."""
        raise NotImplementedError


@dataclass
class FakeNodeGroup:
    """One autoscalable pool of identical fake instances."""

    name: str
    min_size: int = 0
    max_size: int = 10
    cpu: str = "4"
    memory: str = "8Gi"
    pods: str = "110"
    zone: str = ""                 # "" = provider default zone
    labels: dict = field(default_factory=dict)
    members: set = field(default_factory=set)
    _seq: itertools.count = field(default_factory=lambda: itertools.count())


@dataclass
class FakeCloud(CloudProvider):
    """Deterministic in-memory provider (providers/fake/fake.go): records
    every call so tests can assert the controller's cloud traffic."""

    balancers: dict[str, LoadBalancerStatus] = field(default_factory=dict)
    backends: dict[str, tuple[str, ...]] = field(default_factory=dict)
    instances: set = field(default_factory=set)
    zone: tuple[str, str] = ("fake-zone-a", "fake-region")
    routes: dict[str, str] = field(default_factory=dict)
    disk_attachments: dict[str, str] = field(default_factory=dict)
    groups: dict[str, FakeNodeGroup] = field(default_factory=dict)
    calls: list[str] = field(default_factory=list)
    _ip_counter: itertools.count = field(
        default_factory=lambda: itertools.count(1))

    def get_load_balancer(self, service):
        self.calls.append(f"get:{service.key}")
        return self.balancers.get(service.key)

    def ensure_load_balancer(self, service, node_names):
        self.calls.append(f"ensure:{service.key}")
        status = self.balancers.get(service.key)
        if status is None:
            status = LoadBalancerStatus(
                ingress_ip=f"198.51.100.{next(self._ip_counter)}")
            self.balancers[service.key] = status
        self.backends[service.key] = tuple(sorted(node_names))
        return status

    def ensure_load_balancer_deleted(self, service):
        self.calls.append(f"delete:{service.key}")
        self.balancers.pop(service.key, None)
        self.backends.pop(service.key, None)

    def instance_exists(self, node_name: str) -> bool:
        return node_name in self.instances

    def get_zone(self, node_name: str) -> tuple[str, str]:
        group = self.node_group_of(node_name)
        if group is not None and self.groups[group].zone:
            return (self.groups[group].zone, self.zone[1])
        return self.zone

    def attach_disk(self, disk_name: str, node_name: str,
                    read_only: bool = False) -> None:
        """Single-writer semantics (a PD/EBS disk attaches to one instance
        unless read-only): attaching elsewhere raises, exactly the cloud
        error the reference's attacher surfaces and retries."""
        self.calls.append(f"attach:{disk_name}@{node_name}")
        current = self.disk_attachments.get(disk_name)
        if current and current != node_name and not read_only:
            raise RuntimeError(
                f"disk {disk_name!r} is attached to {current!r}")
        self.disk_attachments[disk_name] = node_name

    def detach_disk(self, disk_name: str, node_name: str) -> None:
        self.calls.append(f"detach:{disk_name}@{node_name}")
        if self.disk_attachments.get(disk_name) == node_name:
            del self.disk_attachments[disk_name]

    def disk_attached_to(self, disk_name: str) -> str | None:
        return self.disk_attachments.get(disk_name)

    def list_routes(self) -> dict[str, str]:
        return dict(self.routes)

    def create_route(self, node_name: str, cidr: str) -> None:
        self.calls.append(f"route+:{node_name}={cidr}")
        self.routes[node_name] = cidr

    def delete_route(self, node_name: str) -> None:
        self.calls.append(f"route-:{node_name}")
        self.routes.pop(node_name, None)

    # -- NodeGroups --

    def add_node_group(self, name: str, min_size: int = 0,
                       max_size: int = 10, *, cpu: str = "4",
                       memory: str = "8Gi", pods: str = "110",
                       zone: str = "", labels: dict | None = None,
                       initial: int = 0) -> FakeNodeGroup:
        """Register a pool; `initial` pre-provisions that many instances
        (without Node objects — registration is the autoscaler's job)."""
        if not (0 <= min_size <= max_size):
            raise ValueError(f"bad size range [{min_size}, {max_size}]")
        group = FakeNodeGroup(name=name, min_size=min_size,
                              max_size=max_size, cpu=cpu, memory=memory,
                              pods=pods, zone=zone, labels=dict(labels or {}))
        self.groups[name] = group
        if initial:
            self.increase_size(name, initial)
        return group

    def node_groups(self) -> list[str]:
        return sorted(self.groups)

    def node_group_of(self, node_name: str) -> str | None:
        for name, group in self.groups.items():
            if node_name in group.members:
                return name
        return None

    def group_size_range(self, group: str) -> tuple[int, int]:
        g = self.groups[group]
        return (g.min_size, g.max_size)

    def target_size(self, group: str) -> int:
        return len(self.groups[group].members)

    def increase_size(self, group: str, delta: int) -> list[str]:
        g = self.groups[group]
        if delta <= 0:
            raise ValueError(f"increase_size delta must be > 0, got {delta}")
        if len(g.members) + delta > g.max_size:
            raise ValueError(
                f"group {group!r}: {len(g.members)}+{delta} exceeds "
                f"max_size {g.max_size}")
        self.calls.append(f"scaleup:{group}+{delta}")
        created = []
        for _ in range(delta):
            name = f"{g.name}-{next(g._seq):04d}"
            g.members.add(name)
            self.instances.add(name)
            created.append(name)
        return created

    def delete_nodes(self, group: str, node_names: list[str]) -> None:
        g = self.groups[group]
        missing = [n for n in node_names if n not in g.members]
        if missing:
            raise ValueError(f"group {group!r}: not members: {missing}")
        if len(g.members) - len(node_names) < g.min_size:
            raise ValueError(
                f"group {group!r}: deleting {len(node_names)} would go "
                f"below min_size {g.min_size}")
        self.calls.append(
            f"scaledown:{group}-{','.join(sorted(node_names))}")
        for name in node_names:
            g.members.discard(name)
            self.instances.discard(name)

    def template_node(self, group: str):
        """Fresh-instance Node shape: allocatable + zone/group labels +
        Ready condition — exactly what a new member registers with, so
        probe rows and real rows encode identically."""
        from kubernetes_tpu.api.objects import Node

        g = self.groups[group]
        zone, region = (g.zone or self.zone[0]), self.zone[1]
        labels = {
            "kubernetes.io/hostname": f"{g.name}-template",
            ZONE_LABEL: zone,
            REGION_LABEL: region,
            NODE_GROUP_LABEL: g.name,
        }
        labels.update(g.labels)
        return Node.from_dict({
            "metadata": {"name": f"{g.name}-template", "labels": labels},
            "status": {
                "allocatable": {"cpu": g.cpu, "memory": g.memory,
                                "pods": g.pods},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        })
