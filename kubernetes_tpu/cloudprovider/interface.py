"""Cloud provider SPI + the fake provider.

The pkg/cloudprovider analog (Interface at pkg/cloudprovider/cloud.go:
LoadBalancer/Instances/Zones sub-interfaces; nine real providers + the fake
at pkg/cloudprovider/providers/fake used by every controller test). The
service controller consumes LoadBalancer; the node lifecycle consumes
Instances (does a cloud instance still exist?); Zones labels nodes."""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field


@dataclass
class LoadBalancerStatus:
    ingress_ip: str = ""


class CloudProvider:
    """The Interface subset controllers consume (cloud.go:43-118)."""

    # -- LoadBalancer --
    def get_load_balancer(self, service) -> LoadBalancerStatus | None:
        raise NotImplementedError

    def ensure_load_balancer(self, service, node_names) -> LoadBalancerStatus:
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, service) -> None:
        raise NotImplementedError

    # -- Instances --
    def instance_exists(self, node_name: str) -> bool:
        raise NotImplementedError

    # -- Zones --
    def get_zone(self, node_name: str) -> tuple[str, str]:
        """(failure domain, region)."""
        raise NotImplementedError

    # -- Disks (the Attacher/Detacher seam the attachable volume plugin
    # family consumes — gce_pd/attacher.go, aws_ebs/attacher.go) --
    def attach_disk(self, disk_name: str, node_name: str,
                    read_only: bool = False) -> None:
        raise NotImplementedError

    def detach_disk(self, disk_name: str, node_name: str) -> None:
        raise NotImplementedError

    def disk_attached_to(self, disk_name: str) -> str | None:
        raise NotImplementedError

    # -- Routes (cloud.go Routes interface; route controller consumer) --
    def list_routes(self) -> dict[str, str]:
        """node name -> destination CIDR."""
        raise NotImplementedError

    def create_route(self, node_name: str, cidr: str) -> None:
        raise NotImplementedError

    def delete_route(self, node_name: str) -> None:
        raise NotImplementedError


@dataclass
class FakeCloud(CloudProvider):
    """Deterministic in-memory provider (providers/fake/fake.go): records
    every call so tests can assert the controller's cloud traffic."""

    balancers: dict[str, LoadBalancerStatus] = field(default_factory=dict)
    backends: dict[str, tuple[str, ...]] = field(default_factory=dict)
    instances: set = field(default_factory=set)
    zone: tuple[str, str] = ("fake-zone-a", "fake-region")
    routes: dict[str, str] = field(default_factory=dict)
    disk_attachments: dict[str, str] = field(default_factory=dict)
    calls: list[str] = field(default_factory=list)
    _ip_counter: itertools.count = field(
        default_factory=lambda: itertools.count(1))

    def get_load_balancer(self, service):
        self.calls.append(f"get:{service.key}")
        return self.balancers.get(service.key)

    def ensure_load_balancer(self, service, node_names):
        self.calls.append(f"ensure:{service.key}")
        status = self.balancers.get(service.key)
        if status is None:
            status = LoadBalancerStatus(
                ingress_ip=f"198.51.100.{next(self._ip_counter)}")
            self.balancers[service.key] = status
        self.backends[service.key] = tuple(sorted(node_names))
        return status

    def ensure_load_balancer_deleted(self, service):
        self.calls.append(f"delete:{service.key}")
        self.balancers.pop(service.key, None)
        self.backends.pop(service.key, None)

    def instance_exists(self, node_name: str) -> bool:
        return node_name in self.instances

    def get_zone(self, node_name: str) -> tuple[str, str]:
        return self.zone

    def attach_disk(self, disk_name: str, node_name: str,
                    read_only: bool = False) -> None:
        """Single-writer semantics (a PD/EBS disk attaches to one instance
        unless read-only): attaching elsewhere raises, exactly the cloud
        error the reference's attacher surfaces and retries."""
        self.calls.append(f"attach:{disk_name}@{node_name}")
        current = self.disk_attachments.get(disk_name)
        if current and current != node_name and not read_only:
            raise RuntimeError(
                f"disk {disk_name!r} is attached to {current!r}")
        self.disk_attachments[disk_name] = node_name

    def detach_disk(self, disk_name: str, node_name: str) -> None:
        self.calls.append(f"detach:{disk_name}@{node_name}")
        if self.disk_attachments.get(disk_name) == node_name:
            del self.disk_attachments[disk_name]

    def disk_attached_to(self, disk_name: str) -> str | None:
        return self.disk_attachments.get(disk_name)

    def list_routes(self) -> dict[str, str]:
        return dict(self.routes)

    def create_route(self, node_name: str, cidr: str) -> None:
        self.calls.append(f"route+:{node_name}={cidr}")
        self.routes[node_name] = cidr

    def delete_route(self, node_name: str) -> None:
        self.calls.append(f"route-:{node_name}")
        self.routes.pop(node_name, None)
