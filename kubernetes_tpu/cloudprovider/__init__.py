from kubernetes_tpu.cloudprovider.interface import (  # noqa: F401
    CloudProvider,
    FakeCloud,
)
