from kubernetes_tpu.cloudprovider.interface import (  # noqa: F401
    NODE_GROUP_LABEL,
    REGION_LABEL,
    ZONE_LABEL,
    CloudProvider,
    FakeCloud,
    FakeNodeGroup,
)
