"""Typed API objects (v1 subset) with Kubernetes-JSON round-tripping.

The analog of the reference's versioned API types
(staging/src/k8s.io/api/core/v1/types.go) plus their codec: each type parses
from / serializes to the same JSON wire shape the reference speaks, so the
extender endpoint (reference plugin/pkg/scheduler/core/extender.go:100) can
accept `ExtenderArgs` from an unmodified Go control plane, and fixtures can be
written as plain dicts.

Only the fields the scheduling/controller planes consume are modeled; unknown
fields are preserved in `extra` so round-trips are lossless enough for tests.
"""

from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass, field
from typing import Any

_uid_counter = itertools.count(1)


def _new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    resource_version: str = ""
    owner_references: list[dict[str, Any]] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    # deletion blocks until every finalizer is removed (registry
    # finalization, registry/generic/registry/store.go deletion flow)
    finalizers: list[str] = field(default_factory=list)

    def clone(self) -> "ObjectMeta":
        return ObjectMeta(
            name=self.name, namespace=self.namespace, uid=self.uid,
            labels=dict(self.labels), annotations=dict(self.annotations),
            resource_version=self.resource_version,
            owner_references=[dict(r) for r in self.owner_references],
            creation_timestamp=self.creation_timestamp,
            deletion_timestamp=self.deletion_timestamp,
            finalizers=list(self.finalizers),
        )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ObjectMeta":
        dts = d.get("deletionTimestamp")
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid") or _new_uid(),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            resource_version=str(d.get("resourceVersion", "")),
            owner_references=list(d.get("ownerReferences") or []),
            creation_timestamp=_cond_time(d.get("creationTimestamp")),
            deletion_timestamp=None if dts is None else _cond_time(dts),
            finalizers=list(d.get("finalizers") or []),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"name": self.name, "namespace": self.namespace, "uid": self.uid}
        if self.labels:
            out["labels"] = dict(self.labels)
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        if self.resource_version:
            out["resourceVersion"] = self.resource_version
        if self.owner_references:
            out["ownerReferences"] = list(self.owner_references)
        # timestamps must round-trip or WAL replay/restart loses creation
        # order (victim ranking) and node startup grace
        if self.creation_timestamp:
            out["creationTimestamp"] = _rfc3339(self.creation_timestamp)
        if self.deletion_timestamp is not None:
            out["deletionTimestamp"] = _rfc3339(self.deletion_timestamp)
        if self.finalizers:
            out["finalizers"] = list(self.finalizers)
        return out


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ContainerPort":
        return cls(
            container_port=int(d.get("containerPort", 0)),
            host_port=int(d.get("hostPort", 0)),
            protocol=d.get("protocol", "TCP") or "TCP",
            host_ip=d.get("hostIP", "") or "",
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"containerPort": self.container_port}
        if self.host_port:
            out["hostPort"] = self.host_port
        if self.protocol != "TCP":
            out["protocol"] = self.protocol
        if self.host_ip:
            out["hostIP"] = self.host_ip
        return out


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: dict[str, str] = field(default_factory=dict)
    limits: dict[str, str] = field(default_factory=dict)
    ports: list[ContainerPort] = field(default_factory=list)
    # raw v1 Probe dicts (exec/httpGet/tcpSocket + thresholds) — consumed
    # by the agent's prober manager (pkg/kubelet/prober)
    liveness_probe: dict[str, Any] | None = None
    readiness_probe: dict[str, Any] | None = None

    def clone(self) -> "Container":
        return Container(
            name=self.name, image=self.image, requests=dict(self.requests),
            limits=dict(self.limits),
            ports=[ContainerPort(p.container_port, p.host_port, p.protocol,
                                 p.host_ip) for p in self.ports],
            liveness_probe=copy.deepcopy(self.liveness_probe),
            readiness_probe=copy.deepcopy(self.readiness_probe),
        )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Container":
        res = d.get("resources") or {}
        return cls(
            name=d.get("name", ""),
            image=d.get("image", ""),
            requests={k: str(v) for k, v in (res.get("requests") or {}).items()},
            limits={k: str(v) for k, v in (res.get("limits") or {}).items()},
            ports=[ContainerPort.from_dict(p) for p in d.get("ports") or []],
            liveness_probe=copy.deepcopy(d.get("livenessProbe")),
            readiness_probe=copy.deepcopy(d.get("readinessProbe")),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"name": self.name}
        if self.image:
            out["image"] = self.image
        res: dict[str, Any] = {}
        if self.requests:
            res["requests"] = dict(self.requests)
        if self.limits:
            res["limits"] = dict(self.limits)
        if res:
            out["resources"] = res
        if self.ports:
            out["ports"] = [p.to_dict() for p in self.ports]
        if self.liveness_probe is not None:
            out["livenessProbe"] = copy.deepcopy(self.liveness_probe)
        if self.readiness_probe is not None:
            out["readinessProbe"] = copy.deepcopy(self.readiness_probe)
        return out


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects
    toleration_seconds: int | None = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Toleration":
        return cls(
            key=d.get("key", "") or "",
            operator=d.get("operator", "Equal") or "Equal",
            value=d.get("value", "") or "",
            effect=d.get("effect", "") or "",
            toleration_seconds=d.get("tolerationSeconds"),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.key:
            out["key"] = self.key
        if self.operator != "Equal":
            out["operator"] = self.operator
        if self.value:
            out["value"] = self.value
        if self.effect:
            out["effect"] = self.effect
        if self.toleration_seconds is not None:
            out["tolerationSeconds"] = self.toleration_seconds
        return out

    def tolerates(self, taint: "Taint") -> bool:
        """v1 helper semantics (reference
        staging/src/k8s.io/api/core/v1 ToleratesTaint): empty effect matches
        all effects; empty key with Exists matches all taints; Exists ignores
        value."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Taint":
        return cls(key=d.get("key", ""), value=d.get("value", "") or "",
                   effect=d.get("effect", "NoSchedule"))

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"key": self.key, "effect": self.effect}
        if self.value:
            out["value"] = self.value
        return out


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: dict[str, str] = field(default_factory=dict)
    containers: list[Container] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    affinity: dict[str, Any] = field(default_factory=dict)  # raw v1 Affinity
    volumes: list[dict[str, Any]] = field(default_factory=list)  # raw v1 Volume
    scheduler_name: str = "default-scheduler"
    restart_policy: str = "Always"
    priority: int = 0
    priority_class_name: str = ""
    service_account_name: str = ""

    def clone(self) -> "PodSpec":
        return PodSpec(
            node_name=self.node_name, node_selector=dict(self.node_selector),
            containers=[c.clone() for c in self.containers],
            tolerations=[Toleration(t.key, t.operator, t.value, t.effect,
                                    t.toleration_seconds)
                         for t in self.tolerations],
            affinity=copy.deepcopy(self.affinity) if self.affinity else {},
            volumes=copy.deepcopy(self.volumes) if self.volumes else [],
            scheduler_name=self.scheduler_name,
            restart_policy=self.restart_policy, priority=self.priority,
            priority_class_name=self.priority_class_name,
            service_account_name=self.service_account_name,
        )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodSpec":
        return cls(
            node_name=d.get("nodeName", "") or "",
            node_selector=dict(d.get("nodeSelector") or {}),
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            tolerations=[Toleration.from_dict(t) for t in d.get("tolerations") or []],
            affinity=copy.deepcopy(d.get("affinity") or {}),
            volumes=copy.deepcopy(d.get("volumes") or []),
            scheduler_name=d.get("schedulerName", "default-scheduler") or "default-scheduler",
            restart_policy=d.get("restartPolicy", "Always") or "Always",
            priority=int(d.get("priority", 0) or 0),
            priority_class_name=d.get("priorityClassName", "") or "",
            service_account_name=d.get("serviceAccountName", "") or "",
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.node_name:
            out["nodeName"] = self.node_name
        if self.node_selector:
            out["nodeSelector"] = dict(self.node_selector)
        if self.containers:
            out["containers"] = [c.to_dict() for c in self.containers]
        if self.tolerations:
            out["tolerations"] = [t.to_dict() for t in self.tolerations]
        if self.affinity:
            out["affinity"] = copy.deepcopy(self.affinity)
        if self.volumes:
            out["volumes"] = copy.deepcopy(self.volumes)
        if self.scheduler_name != "default-scheduler":
            out["schedulerName"] = self.scheduler_name
        if self.priority:
            out["priority"] = self.priority
        if self.priority_class_name:
            out["priorityClassName"] = self.priority_class_name
        if self.service_account_name:
            out["serviceAccountName"] = self.service_account_name
        if self.restart_policy != "Always":
            out["restartPolicy"] = self.restart_policy
        return out


@dataclass
class PodStatus:
    phase: str = "Pending"
    conditions: list[dict[str, Any]] = field(default_factory=list)
    host_ip: str = ""
    # terminal-state attribution (v1 PodStatus.Reason/Message — the
    # eviction manager writes Reason="Evicted", eviction_manager.go:560)
    reason: str = ""
    message: str = ""
    # raw v1 ContainerStatus dicts (restartCount/ready/state) written by
    # the agent's status manager, read by kubectl get (RESTARTS column)
    container_statuses: list[dict[str, Any]] = field(default_factory=list)
    # node the scheduler preempted victims on for this pod (v1
    # PodStatus.NominatedNodeName; the preemptor retries there first and
    # the freed capacity is held against lower-priority pods)
    nominated_node_name: str = ""

    def clone(self) -> "PodStatus":
        # containerStatuses entries nest state dicts — deep-copy so a
        # caller mutating a clone can't reach the store's canonical object
        return PodStatus(phase=self.phase,
                         conditions=[dict(c) for c in self.conditions],
                         host_ip=self.host_ip,
                         reason=self.reason, message=self.message,
                         container_statuses=copy.deepcopy(
                             self.container_statuses),
                         nominated_node_name=self.nominated_node_name)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodStatus":
        return cls(
            phase=d.get("phase", "Pending") or "Pending",
            conditions=list(d.get("conditions") or []),
            host_ip=d.get("hostIP", "") or "",
            reason=d.get("reason", "") or "",
            message=d.get("message", "") or "",
            container_statuses=list(d.get("containerStatuses") or []),
            nominated_node_name=d.get("nominatedNodeName", "") or "",
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"phase": self.phase}
        if self.conditions:
            out["conditions"] = list(self.conditions)
        if self.host_ip:
            out["hostIP"] = self.host_ip
        if self.reason:
            out["reason"] = self.reason
        if self.message:
            out["message"] = self.message
        if self.container_statuses:
            out["containerStatuses"] = list(self.container_statuses)
        if self.nominated_node_name:
            out["nominatedNodeName"] = self.nominated_node_name
        return out


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "Pod":
        return Pod(metadata=self.metadata.clone(), spec=self.spec.clone(),
                   status=self.status.clone())

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Pod":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
            status=PodStatus.from_dict(d.get("status") or {}),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    def is_best_effort(self) -> bool:
        """BestEffort QoS: no container has any request or limit (reference
        pkg/api/v1/helper/qos/qos.go GetPodQOS)."""
        for c in self.spec.containers:
            if c.requests or c.limits:
                return False
        return True

    def host_ports(self) -> list[int]:
        """Requested host ports (reference scheduler util GetUsedPorts,
        plugin/pkg/scheduler/util/utils.go:25 — port 0 excluded)."""
        return [p.host_port for c in self.spec.containers
                for p in c.ports if p.host_port]


def parse_node_affinity(affinity: dict) -> tuple[list | None, list]:
    """Split a raw v1 Affinity dict into node-affinity parts.

    Returns `(required_terms, preferred)`: `required_terms` is None when no
    requiredDuringSchedulingIgnoredDuringExecution NodeSelector is present
    (matches all nodes, reference predicates.go:662), else the list of
    nodeSelectorTerms (each a list of matchExpressions dicts — an empty list
    matches no nodes, predicates.go:645 via NodeSelectorRequirementsAsSelector
    returning labels.Nothing for len==0). `preferred` is a list of
    `(weight, matchExpressions)` tuples."""
    na = (affinity or {}).get("nodeAffinity") or {}
    required = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    req_terms = None
    if required is not None:
        req_terms = [t.get("matchExpressions") or []
                     for t in required.get("nodeSelectorTerms") or []]
    preferred = [(int(p.get("weight", 0)), (p.get("preference") or {}).get("matchExpressions") or [])
                 for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []]
    return req_terms, preferred


def _rfc3339(epoch: float) -> str:
    from datetime import datetime, timezone

    # microseconds: whole-second truncation would collapse a creation burst
    # into ties and scramble youngest-first victim ranking after WAL replay
    return datetime.fromtimestamp(epoch, timezone.utc).isoformat(
        timespec="microseconds").replace("+00:00", "Z")


def _cond_time(value) -> float:
    """Condition timestamps: internal producers write epoch floats; external
    Kubernetes JSON carries RFC3339 strings. Parse both, degrade unparseable
    values to 0.0 instead of rejecting the whole Node."""
    if value is None:
        return 0.0
    try:
        return float(value)  # epoch numbers, possibly as strings
    except (TypeError, ValueError):
        pass
    try:
        from datetime import datetime

        return datetime.fromisoformat(str(value).replace("Z", "+00:00")
                                      ).timestamp()
    except (TypeError, ValueError):
        return 0.0


@dataclass
class NodeCondition:
    type: str = ""
    status: str = "Unknown"  # True | False | Unknown
    # epoch seconds (the reference's metav1.Time fields; the node controller
    # reads heartbeat age to detect dead kubelets, node_controller.go:587)
    last_heartbeat_time: float = 0.0
    last_transition_time: float = 0.0
    reason: str = ""
    message: str = ""

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "NodeCondition":
        return cls(type=d.get("type", ""), status=d.get("status", "Unknown"),
                   last_heartbeat_time=_cond_time(d.get("lastHeartbeatTime")),
                   last_transition_time=_cond_time(d.get("lastTransitionTime")),
                   reason=d.get("reason", "") or "",
                   message=d.get("message", "") or "")

    def to_dict(self) -> dict[str, Any]:
        # wire format is RFC3339 (metav1.Time) so a stock Go control plane
        # can unmarshal what we emit; from_dict accepts both forms
        out = {"type": self.type, "status": self.status}
        if self.last_heartbeat_time:
            out["lastHeartbeatTime"] = _rfc3339(self.last_heartbeat_time)
        if self.last_transition_time:
            out["lastTransitionTime"] = _rfc3339(self.last_transition_time)
        if self.reason:
            out["reason"] = self.reason
        if self.message:
            out["message"] = self.message
        return out


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)
    provider_id: str = ""
    # per-node pod subnet (v1.NodeSpec PodCIDR; the route controller
    # programs a cloud route per CIDR)
    pod_cidr: str = ""
    # dynamic kubelet config (alpha v1.NodeSpec.ConfigSource,
    # pkg/kubelet/kubeletconfig): {"configMap": {"name", "namespace"}}
    config_source: dict[str, Any] | None = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "NodeSpec":
        return cls(
            unschedulable=bool(d.get("unschedulable", False)),
            taints=[Taint.from_dict(t) for t in d.get("taints") or []],
            provider_id=d.get("providerID", "") or "",
            pod_cidr=d.get("podCIDR", "") or "",
            config_source=copy.deepcopy(d.get("configSource")),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.unschedulable:
            out["unschedulable"] = True
        if self.taints:
            out["taints"] = [t.to_dict() for t in self.taints]
        if self.provider_id:
            out["providerID"] = self.provider_id
        if self.pod_cidr:
            out["podCIDR"] = self.pod_cidr
        if self.config_source is not None:
            out["configSource"] = copy.deepcopy(self.config_source)
        return out


@dataclass
class NodeStatus:
    capacity: dict[str, str] = field(default_factory=dict)
    allocatable: dict[str, str] = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)
    # raw v1 ContainerImage dicts: {"names": [...], "sizeBytes": int}
    # (ImageLocalityPriority reads node.Status.Images, image_locality.go:71)
    images: list[dict[str, Any]] = field(default_factory=list)
    # attach/detach controller's actual world: [{"name": ..., "devicePath":
    # ...}] + kubelet's in-use marks (v1.NodeStatus VolumesAttached/InUse)
    volumes_attached: list[dict[str, Any]] = field(default_factory=list)
    volumes_in_use: list[str] = field(default_factory=list)
    # {"kubeletEndpoint": {"Port": N}} — how the apiserver node proxy finds
    # the kubelet's API (v1.NodeStatus DaemonEndpoints)
    daemon_endpoints: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "NodeStatus":
        return cls(
            capacity={k: str(v) for k, v in (d.get("capacity") or {}).items()},
            allocatable={k: str(v) for k, v in (d.get("allocatable") or {}).items()},
            conditions=[NodeCondition.from_dict(c) for c in d.get("conditions") or []],
            images=copy.deepcopy(d.get("images") or []),
            volumes_attached=copy.deepcopy(d.get("volumesAttached") or []),
            volumes_in_use=list(d.get("volumesInUse") or []),
            daemon_endpoints=copy.deepcopy(d.get("daemonEndpoints") or {}),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.capacity:
            out["capacity"] = dict(self.capacity)
        if self.allocatable:
            out["allocatable"] = dict(self.allocatable)
        if self.conditions:
            out["conditions"] = [c.to_dict() for c in self.conditions]
        if self.images:
            out["images"] = copy.deepcopy(self.images)
        if self.volumes_attached:
            out["volumesAttached"] = copy.deepcopy(self.volumes_attached)
        if self.volumes_in_use:
            out["volumesInUse"] = list(self.volumes_in_use)
        if self.daemon_endpoints:
            out["daemonEndpoints"] = copy.deepcopy(self.daemon_endpoints)
        return out

    def effective_allocatable(self) -> dict[str, str]:
        """allocatable falls back to capacity when unset (reference defaulting
        behavior in pkg/api/v1/defaults)."""
        return self.allocatable or self.capacity


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    @property
    def key(self) -> str:
        return self.metadata.name

    def clone(self) -> "Node":
        return Node(
            metadata=self.metadata.clone(),
            spec=NodeSpec(unschedulable=self.spec.unschedulable,
                          taints=[Taint(t.key, t.value, t.effect)
                                  for t in self.spec.taints],
                          provider_id=self.spec.provider_id,
                          pod_cidr=self.spec.pod_cidr,
                          config_source=copy.deepcopy(
                              self.spec.config_source)),
            status=NodeStatus(capacity=dict(self.status.capacity),
                              allocatable=dict(self.status.allocatable),
                              conditions=[
                                  NodeCondition(c.type, c.status,
                                                c.last_heartbeat_time,
                                                c.last_transition_time,
                                                c.reason)
                                  for c in self.status.conditions],
                              images=copy.deepcopy(self.status.images),
                              volumes_attached=copy.deepcopy(
                                  self.status.volumes_attached),
                              volumes_in_use=list(
                                  self.status.volumes_in_use),
                              daemon_endpoints=copy.deepcopy(
                                  self.status.daemon_endpoints)),
        )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Node":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=NodeSpec.from_dict(d.get("spec") or {}),
            status=NodeStatus.from_dict(d.get("status") or {}),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }


@dataclass
class Event:
    """Cluster event object (reference: events are first-class API objects
    recorded via EventBroadcaster, client-go/tools/record/event.go:78)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: dict[str, Any] = field(default_factory=dict)
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    source_component: str = ""

    kind = "Event"

    def clone(self) -> "Event":
        return Event(metadata=self.metadata.clone(),
                     involved_object=dict(self.involved_object),
                     reason=self.reason, message=self.message, type=self.type,
                     count=self.count, source_component=self.source_component)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Event":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            involved_object=dict(d.get("involvedObject") or {}),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            type=d.get("type", "Normal"),
            count=int(d.get("count", 1)),
            source_component=(d.get("source") or {}).get("component", ""),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": self.metadata.to_dict(),
            "involvedObject": dict(self.involved_object),
            "reason": self.reason,
            "message": self.message,
            "type": self.type,
            "count": self.count,
            "source": {"component": self.source_component},
        }


@dataclass
class PersistentVolume:
    """Cluster-scoped storage object (reference staging/src/k8s.io/api/core/v1
    PersistentVolume; the scheduler reads its labels for NoVolumeZoneConflict,
    predicates.go:461-470, and its node-affinity annotation for
    NoVolumeNodeConflict, predicates.go:1345)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict[str, Any] = field(default_factory=dict)  # raw PV source spec
    status: dict[str, Any] = field(default_factory=dict)

    kind = "PersistentVolume"

    @property
    def key(self) -> str:
        return self.metadata.name

    @property
    def phase(self) -> str:
        return self.status.get("phase", "Pending")

    def clone(self) -> "PersistentVolume":
        return PersistentVolume(metadata=self.metadata.clone(),
                                spec=copy.deepcopy(self.spec),
                                status=copy.deepcopy(self.status))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PersistentVolume":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   spec=copy.deepcopy(d.get("spec") or {}),
                   status=copy.deepcopy(d.get("status") or {}))

    def to_dict(self) -> dict[str, Any]:
        out = {"apiVersion": "v1", "kind": "PersistentVolume",
               "metadata": self.metadata.to_dict(),
               "spec": copy.deepcopy(self.spec)}
        if self.status:
            out["status"] = copy.deepcopy(self.status)
        return out


@dataclass
class PersistentVolumeClaim:
    """Namespaced claim bound to a PV by name (spec.volumeName; the scheduler
    resolves pod volume -> PVC -> PV, predicates.go:230-270)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict[str, Any] = field(default_factory=dict)
    status: dict[str, Any] = field(default_factory=dict)

    kind = "PersistentVolumeClaim"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @property
    def volume_name(self) -> str:
        return self.spec.get("volumeName", "") or ""

    @property
    def phase(self) -> str:
        return self.status.get("phase", "Pending")

    def clone(self) -> "PersistentVolumeClaim":
        return PersistentVolumeClaim(metadata=self.metadata.clone(),
                                     spec=copy.deepcopy(self.spec),
                                     status=copy.deepcopy(self.status))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PersistentVolumeClaim":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   spec=copy.deepcopy(d.get("spec") or {}),
                   status=copy.deepcopy(d.get("status") or {}))

    def to_dict(self) -> dict[str, Any]:
        out = {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
               "metadata": self.metadata.to_dict(),
               "spec": copy.deepcopy(self.spec)}
        if self.status:
            out["status"] = copy.deepcopy(self.status)
        return out


@dataclass
class _SpecStatusObject:
    """Generic spec/status object shape for config-ish kinds."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict[str, Any] = field(default_factory=dict)
    status: dict[str, Any] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self):
        return type(self)(metadata=self.metadata.clone(),
                          spec=copy.deepcopy(self.spec),
                          status=copy.deepcopy(self.status))

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   spec=copy.deepcopy(d.get("spec") or {}),
                   status=copy.deepcopy(d.get("status") or {}))

    def to_dict(self) -> dict[str, Any]:
        out = {"apiVersion": getattr(self, "api_version", "v1"),
               "kind": self.kind,
               "metadata": self.metadata.to_dict(),
               "spec": copy.deepcopy(self.spec)}
        if self.status:
            out["status"] = copy.deepcopy(self.status)
        return out


@dataclass
class Service(_SpecStatusObject):
    """Service with a map selector (reference v1.Service; the scheduler's
    SelectorSpreadPriority and ServiceAffinity look up services matching a
    pod, selector_spreading.go:61)."""

    kind = "Service"

    @property
    def selector(self) -> dict[str, str] | None:
        """None (absent) vs {} matters: the reference lister skips only nil
        selectors — a non-nil empty map selects everything
        (service_expansion.go:45-50, labels.Set{}.AsSelector())."""
        sel = self.spec.get("selector")
        return None if sel is None else dict(sel)


@dataclass
class Endpoints:
    """v1 Endpoints: the Service -> ready-pod address mapping maintained by
    the endpoint controller (pkg/controller/endpoint), and the object whose
    annotation carries the leader-election record
    (client-go/tools/leaderelection/resourcelock/endpointslock.go)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: list[dict[str, Any]] = field(default_factory=list)

    kind = "Endpoints"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "Endpoints":
        return Endpoints(metadata=self.metadata.clone(),
                         subsets=copy.deepcopy(self.subsets))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Endpoints":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   subsets=copy.deepcopy(d.get("subsets") or []))

    def to_dict(self) -> dict[str, Any]:
        out = {"apiVersion": "v1", "kind": "Endpoints",
               "metadata": self.metadata.to_dict()}
        if self.subsets:
            out["subsets"] = copy.deepcopy(self.subsets)
        return out


@dataclass
class _Workload:
    """Shared shape of the pod-owning workload kinds (RC/RS/StatefulSet):
    metadata + raw spec holding replicas/selector/template."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict[str, Any] = field(default_factory=dict)
    status: dict[str, Any] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @property
    def replicas(self) -> int:
        r = self.spec.get("replicas")
        return 1 if r is None else int(r)

    def clone(self):
        return type(self)(metadata=self.metadata.clone(),
                          spec=copy.deepcopy(self.spec),
                          status=copy.deepcopy(self.status))

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   spec=copy.deepcopy(d.get("spec") or {}),
                   status=copy.deepcopy(d.get("status") or {}))

    def to_dict(self) -> dict[str, Any]:
        return {"apiVersion": self.api_version, "kind": self.kind,
                "metadata": self.metadata.to_dict(),
                "spec": copy.deepcopy(self.spec),
                "status": copy.deepcopy(self.status)}


@dataclass
class ReplicationController(_Workload):
    """v1 ReplicationController: map-style spec.selector
    (selector_spreading.go:68 SelectorFromSet)."""

    kind = "ReplicationController"
    api_version = "v1"

    @property
    def selector(self) -> dict[str, str]:
        return dict(self.spec.get("selector") or {})


@dataclass
class ReplicaSet(_Workload):
    """extensions/v1beta1 ReplicaSet: LabelSelector-style spec.selector
    (selector_spreading.go:73 LabelSelectorAsSelector)."""

    kind = "ReplicaSet"
    api_version = "extensions/v1beta1"

    @property
    def selector(self) -> dict[str, Any]:
        return dict(self.spec.get("selector") or {})


@dataclass
class StatefulSet(_Workload):
    """apps/v1beta1 StatefulSet (selector_spreading.go:80)."""

    kind = "StatefulSet"
    api_version = "apps/v1beta1"

    @property
    def selector(self) -> dict[str, Any]:
        return dict(self.spec.get("selector") or {})


@dataclass
class Deployment(_Workload):
    """extensions/v1beta1 Deployment: LabelSelector spec.selector + pod
    template + strategy (reference pkg/controller/deployment; types at
    staging/src/k8s.io/api/extensions/v1beta1/types.go)."""

    kind = "Deployment"
    api_version = "extensions/v1beta1"

    @property
    def selector(self) -> dict[str, Any]:
        return dict(self.spec.get("selector") or {})

    @property
    def strategy_type(self) -> str:
        return (self.spec.get("strategy") or {}).get("type", "RollingUpdate")


@dataclass
class Namespace(_SpecStatusObject):
    """v1 Namespace (cluster-scoped; stored under the conventional ""
    namespace key). status.phase Active|Terminating drives the lifecycle
    admission plugin and the namespace controller's cascade deletion
    (pkg/controller/namespace)."""

    kind = "Namespace"

    @property
    def phase(self) -> str:
        return self.status.get("phase", "Active")


@dataclass
class CustomResourceDefinition(_SpecStatusObject):
    """apiextensions CustomResourceDefinition: registers a new REST
    resource served generically (apiextensions-apiserver analog;
    spec: {group, version, names: {plural, kind}, scope})."""

    kind = "CustomResourceDefinition"
    api_version = "apiextensions.k8s.io/v1beta1"

    @property
    def plural(self) -> str:
        return (self.spec.get("names") or {}).get("plural", "")

    @property
    def target_kind(self) -> str:
        return (self.spec.get("names") or {}).get("kind", "")


@dataclass
class GenericObject:
    """Schema-less object backing custom resources: whatever JSON arrives,
    keyed like every other object (the apiextensions CustomResource)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    body: dict[str, Any] = field(default_factory=dict)
    kind: str = ""

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "GenericObject":
        return GenericObject(metadata=self.metadata.clone(),
                             body=copy.deepcopy(self.body), kind=self.kind)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "GenericObject":
        body = {k: copy.deepcopy(v) for k, v in d.items()
                if k != "metadata"}
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   body=body, kind=d.get("kind", ""))

    def to_dict(self) -> dict[str, Any]:
        out = copy.deepcopy(self.body)
        out["kind"] = self.kind
        out["metadata"] = self.metadata.to_dict()
        return out


@dataclass
class Cluster(_SpecStatusObject):
    """federation/v1beta1 Cluster: a member cluster registered with the
    federation control plane (reference federation/apis/federation/types.go;
    spec.serverAddress points at the member apiserver).

    status.capacity is written by the ClusterHealthController's probe:
    {allocatable, free (both v1 resource maps summed over the member's
    schedulable Ready nodes; free = allocatable minus bound pod requests),
    zones (sorted zone labels seen on those nodes), nodes (count),
    headroom (sum over the member's NodeGroups of maxSize minus the
    larger of targetSize/readyNodes — how many more nodes its autoscaler
    may still add; 0 with no NodeGroups: no growth possible)}.
    status.planner is written by the federation GlobalPlanner."""

    kind = "Cluster"
    api_version = "federation/v1beta1"

    @property
    def server_address(self) -> str:
        addr = self.spec.get("serverAddress", "")
        if addr:
            return addr
        # kubefed join writes the CIDR-keyed form (join.go): first
        # populated entry wins
        for entry in self.spec.get("serverAddressByClientCIDRs") or []:
            if entry.get("serverAddress"):
                return entry["serverAddress"]
        return ""

    @property
    def ready(self) -> bool:
        return any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in self.status.get("conditions", []))

    @property
    def capacity(self) -> dict[str, Any]:
        return self.status.get("capacity") or {}

    @property
    def allocatable_capacity(self) -> dict[str, str]:
        return dict(self.capacity.get("allocatable") or {})

    @property
    def free_capacity(self) -> dict[str, str]:
        return dict(self.capacity.get("free") or {})

    @property
    def zones(self) -> tuple[str, ...]:
        return tuple(self.capacity.get("zones") or ())

    @property
    def headroom(self) -> int:
        return int(self.capacity.get("headroom", 0) or 0)

    @property
    def planner_status(self) -> dict[str, Any]:
        return self.status.get("planner") or {}


@dataclass
class LimitRange(_SpecStatusObject):
    """v1 LimitRange: per-namespace container request/limit defaults and
    bounds enforced by the LimitRanger admission plugin
    (plugin/pkg/admission/limitranger)."""

    kind = "LimitRange"


@dataclass
class ResourceQuota(_SpecStatusObject):
    """v1 ResourceQuota: per-namespace aggregate resource caps enforced by
    the ResourceQuota admission plugin (plugin/pkg/admission/resourcequota)."""

    kind = "ResourceQuota"


@dataclass
class Job(_Workload):
    """batch/v1 Job: run-to-completion workload (reference
    pkg/controller/job/jobcontroller.go; types
    staging/src/k8s.io/api/batch/v1/types.go)."""

    kind = "Job"
    api_version = "batch/v1"

    @property
    def selector(self) -> dict[str, Any]:
        sel = self.spec.get("selector")
        if sel:
            return dict(sel)
        # the reference defaults the selector to the template labels
        labels = ((self.spec.get("template") or {}).get("metadata") or {}
                  ).get("labels") or {}
        return {"matchLabels": dict(labels)} if labels else {}

    @property
    def completions(self) -> int:
        c = self.spec.get("completions")
        return 1 if c is None else int(c)

    @property
    def parallelism(self) -> int:
        p = self.spec.get("parallelism")
        return 1 if p is None else int(p)


@dataclass
class PodGroup(_SpecStatusObject):
    """Gang-scheduling group: spec.minMember pods must place atomically or
    none do (the coscheduling PodGroup shape — kube-batch/scheduler-plugins
    PodGroup CRD — over this tree's all-or-nothing batched solver).

    spec: minMember (int, required), scheduleTimeoutSeconds (float,
    optional — pending members requeue once a group waits this long for
    quorum). status: phase Pending | Placing | Placed | Timeout, plus the
    gang controller's counters (placed, members)."""

    kind = "PodGroup"
    api_version = "scheduling.ktpu.io/v1alpha1"

    PHASES = ("Pending", "Placing", "Placed", "Timeout")

    @property
    def min_member(self) -> int:
        m = self.spec.get("minMember")
        return 1 if m is None else int(m)

    @property
    def schedule_timeout_seconds(self) -> float:
        t = self.spec.get("scheduleTimeoutSeconds")
        return float(t) if t is not None else 30.0

    @property
    def phase(self) -> str:
        return self.status.get("phase") or "Pending"


@dataclass
class NodeGroup(_SpecStatusObject):
    """Autoscaler node group: the API mirror of one cloud-provider pool
    (the cluster-autoscaler NodeGroup contract surfaced as an object, so
    `kubectl get nodegroups` shows pool bounds and the autoscaler's view).

    spec: minSize/maxSize (ints, maxSize >= minSize >= 0),
    cloudProviderGroup (the provider-side pool name; defaults to
    metadata.name). status: targetSize (cloud desired count), readyNodes
    (registered Ready members), lastScaleUp/lastScaleDown (unix seconds,
    0 = never) — written by the autoscaler's reconcile, never by users."""

    kind = "NodeGroup"
    api_version = "autoscaling.ktpu.io/v1alpha1"

    @property
    def min_size(self) -> int:
        return int(self.spec.get("minSize", 0) or 0)

    @property
    def max_size(self) -> int:
        return int(self.spec.get("maxSize", 0) or 0)

    @property
    def cloud_provider_group(self) -> str:
        return self.spec.get("cloudProviderGroup") or self.metadata.name

    @property
    def target_size(self) -> int:
        return int(self.status.get("targetSize", 0) or 0)

    @property
    def ready_nodes(self) -> int:
        return int(self.status.get("readyNodes", 0) or 0)


@dataclass
class DeschedulePolicy(_SpecStatusObject):
    """Descheduler policy: tuning knobs for the gang-defragmentation
    control loop (the solver-driven analogue of upstream's
    descheduler-policy ConfigMap, surfaced as a first-class object so
    `kubectl get deschedulepolicies` shows what the planner may do).

    spec: dryRun (bool — plan and count, never evict), maxMovesPerCycle
    (int >= 1, cap on evictions per defrag plan), priorityCutoff (int —
    only pods at or below this priority are move candidates),
    cooldownSeconds (float — per-node stamp horizon that also blocks
    autoscaler scale-down), rollbackSeconds (float — deadline for a
    displaced gang to land before the plan is rolled back). status:
    written by the descheduler's reconcile (cycles, moves, rollbacks,
    gangsDefragged), never by users."""

    kind = "DeschedulePolicy"
    api_version = "descheduling.ktpu.io/v1alpha1"

    @property
    def dry_run(self) -> bool:
        return bool(self.spec.get("dryRun", False))

    @property
    def max_moves_per_cycle(self) -> int:
        m = self.spec.get("maxMovesPerCycle")
        return 8 if m is None else int(m)

    @property
    def priority_cutoff(self) -> int:
        c = self.spec.get("priorityCutoff")
        return 0 if c is None else int(c)

    @property
    def cooldown_seconds(self) -> float:
        t = self.spec.get("cooldownSeconds")
        return 300.0 if t is None else float(t)

    @property
    def rollback_seconds(self) -> float:
        t = self.spec.get("rollbackSeconds")
        return 60.0 if t is None else float(t)


@dataclass
class PriorityClass:
    """scheduling.k8s.io PriorityClass (the v1.8-alpha shape,
    pkg/apis/scheduling/types.go): maps a name to an integer priority
    stamped onto pod specs at admission. Non-namespaced; top-level
    value/globalDefault/description rather than spec/status."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    description: str = ""

    kind = "PriorityClass"
    api_version = "scheduling.k8s.io/v1alpha1"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "PriorityClass":
        return PriorityClass(metadata=self.metadata.clone(),
                             value=self.value,
                             global_default=self.global_default,
                             description=self.description)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PriorityClass":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   value=int(d.get("value", 0) or 0),
                   global_default=bool(d.get("globalDefault", False)),
                   description=d.get("description", "") or "")

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"apiVersion": self.api_version,
                               "kind": self.kind,
                               "metadata": self.metadata.to_dict(),
                               "value": self.value}
        if self.global_default:
            out["globalDefault"] = True
        if self.description:
            out["description"] = self.description
        return out


@dataclass
class FlowSchema(_SpecStatusObject):
    """API Priority & Fairness flow schema (the reference's
    flowcontrol.apiserver.k8s.io FlowSchema): classifies requests onto a
    priority level by user/group/verb/resource rules.

    spec: priorityLevel (name of a PriorityLevelConfiguration or built-in
    level), matchingPrecedence (int, lower matches first), rules (list of
    {users, groups, verbs, resources} constraint dicts; a rule matches when
    every PRESENT constraint matches, "*" in users means any authenticated
    user). Cluster-scoped."""

    kind = "FlowSchema"
    api_version = "flowcontrol.ktpu.io/v1alpha1"

    @property
    def priority_level(self) -> str:
        return self.spec.get("priorityLevel", "") or ""

    @property
    def matching_precedence(self) -> int:
        return int(self.spec.get("matchingPrecedence", 1000) or 1000)

    @property
    def rules(self) -> list:
        return self.spec.get("rules") or []


@dataclass
class PriorityLevelConfiguration(_SpecStatusObject):
    """API Priority & Fairness priority level (the reference's
    PriorityLevelConfiguration, collapsed to the queueing knobs this
    server's FlowController uses).

    spec: shares (int — this level's slice of the server's total
    concurrency), queues (fair-queue count), queueLengthLimit (bound per
    queue; beyond it requests shed with 429), handSize (shuffle-sharding
    hand). Cluster-scoped."""

    kind = "PriorityLevelConfiguration"
    api_version = "flowcontrol.ktpu.io/v1alpha1"

    @property
    def shares(self) -> int:
        return int(self.spec.get("shares", 1) or 1)

    @property
    def queues(self) -> int:
        return int(self.spec.get("queues", 4) or 4)

    @property
    def queue_length_limit(self) -> int:
        return int(self.spec.get("queueLengthLimit", 16) or 16)

    @property
    def hand_size(self) -> int:
        return int(self.spec.get("handSize", 2) or 2)


@dataclass
class AlertRule(_SpecStatusObject):
    """monitoring.ktpu.io rule consumed by the Monitor's rule engine (the
    PrometheusRule CRD position in the reference's monitoring addons).

    spec: exactly one of `record` (a recording rule writing the result
    back into the TSDB under that series name) or `alert` (a CamelCase
    alert name — CamelCase lives in spec because metadata.name must stay
    DNS-1123); `expr` (the query expression, validated parseable at
    admission); `for` (seconds a labelset must stay active before the
    alert fires); optional `labels`/`annotations` maps. Cluster-scoped:
    rules judge the whole control plane, not one namespace."""

    kind = "AlertRule"
    api_version = "monitoring.ktpu.io/v1alpha1"

    @property
    def record(self) -> str:
        return self.spec.get("record", "") or ""

    @property
    def alert(self) -> str:
        return self.spec.get("alert", "") or ""

    @property
    def expr(self) -> str:
        return self.spec.get("expr", "") or ""

    @property
    def for_s(self) -> float:
        return float(self.spec.get("for", 0) or 0)


@dataclass
class _DataObject:
    """Shared shape of the data-map kinds (Secret/ConfigMap): metadata + a
    string-keyed payload map (reference staging/src/k8s.io/api/core/v1/
    types.go Secret/ConfigMap)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self):
        return type(self)(metadata=self.metadata.clone(),
                          data=dict(self.data))

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   data=dict(d.get("data") or {}))

    def to_dict(self) -> dict[str, Any]:
        out = {"apiVersion": "v1", "kind": self.kind,
               "metadata": self.metadata.to_dict()}
        if self.data:
            out["data"] = dict(self.data)
        return out


@dataclass
class Secret(_DataObject):
    """v1 Secret (service-account tokens, pull secrets; consumed by the
    kubelet secret manager, pkg/kubelet/secret)."""

    kind = "Secret"
    type: str = "Opaque"

    def clone(self):
        out = super().clone()
        out.type = self.type
        return out

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        out = super().from_dict(d)
        out.type = d.get("type", "Opaque")
        return out

    def to_dict(self) -> dict[str, Any]:
        out = super().to_dict()
        out["type"] = self.type
        return out


@dataclass
class ConfigMap(_DataObject):
    """v1 ConfigMap (pkg/kubelet/configmap consumer; also the dynamic
    kubelet-config carrier, SURVEY.md §5.6(e))."""

    kind = "ConfigMap"


@dataclass
class ServiceAccount:
    """v1 ServiceAccount: identity for pods; the serviceaccounts controller
    guarantees one named "default" per namespace and a token Secret for each
    account (pkg/controller/serviceaccount/serviceaccounts_controller.go,
    tokens_controller.go)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: list[dict[str, Any]] = field(default_factory=list)

    kind = "ServiceAccount"

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "ServiceAccount":
        return ServiceAccount(metadata=self.metadata.clone(),
                              secrets=copy.deepcopy(self.secrets))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ServiceAccount":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   secrets=copy.deepcopy(d.get("secrets") or []))

    def to_dict(self) -> dict[str, Any]:
        out = {"apiVersion": "v1", "kind": "ServiceAccount",
               "metadata": self.metadata.to_dict()}
        if self.secrets:
            out["secrets"] = copy.deepcopy(self.secrets)
        return out


@dataclass
class DaemonSet(_Workload):
    """extensions/v1beta1 DaemonSet: one pod per eligible node, placed by
    the daemon controller directly (bypasses the scheduler — it calls
    GeneralPredicates itself, pkg/controller/daemon/daemon_controller.go:1327)."""

    kind = "DaemonSet"
    api_version = "extensions/v1beta1"

    @property
    def selector(self) -> dict[str, Any]:
        sel = self.spec.get("selector")
        if sel:
            return dict(sel)
        labels = ((self.spec.get("template") or {}).get("metadata") or {}
                  ).get("labels") or {}
        return {"matchLabels": dict(labels)} if labels else {}


@dataclass
class CronJob(_SpecStatusObject):
    """batch/v2alpha1 CronJob (pkg/controller/cronjob/cronjob_controller.go):
    spec.schedule is 5-field cron; spawns Job objects at fire times under
    spec.concurrencyPolicy Allow|Forbid|Replace."""

    kind = "CronJob"
    api_version = "batch/v2alpha1"

    @property
    def schedule(self) -> str:
        return self.spec.get("schedule", "")

    @property
    def concurrency_policy(self) -> str:
        return self.spec.get("concurrencyPolicy", "Allow")

    @property
    def suspend(self) -> bool:
        return bool(self.spec.get("suspend", False))


@dataclass
class HorizontalPodAutoscaler(_SpecStatusObject):
    """autoscaling/v1 HPA (pkg/controller/podautoscaler/horizontal.go):
    scales scaleTargetRef between minReplicas and maxReplicas to hold
    targetCPUUtilizationPercentage."""

    kind = "HorizontalPodAutoscaler"
    api_version = "autoscaling/v1"

    @property
    def target_ref(self) -> dict[str, str]:
        return dict(self.spec.get("scaleTargetRef") or {})

    @property
    def min_replicas(self) -> int:
        return int(self.spec.get("minReplicas") or 1)

    @property
    def max_replicas(self) -> int:
        return int(self.spec.get("maxReplicas") or 1)

    @property
    def target_utilization(self) -> int:
        # reference default 80% (horizontal.go defaultTargetCPUUtilizationPercentage)
        return int(self.spec.get("targetCPUUtilizationPercentage") or 80)


@dataclass
class PodDisruptionBudget(_SpecStatusObject):
    """policy/v1beta1 PDB (pkg/controller/disruption/disruption.go): the
    disruption controller computes currentHealthy/desiredHealthy/
    disruptionsAllowed; eviction honors disruptionsAllowed."""

    kind = "PodDisruptionBudget"
    api_version = "policy/v1beta1"

    @property
    def selector(self) -> dict[str, Any]:
        return dict(self.spec.get("selector") or {})


@dataclass
class APIService(_SpecStatusObject):
    """apiregistration APIService (kube-aggregator,
    staging/src/k8s.io/kube-aggregator/pkg/apis/apiregistration): routes an
    API group/version to a delegate server; spec.service/spec.serverAddress
    names the backend, local (no backend) groups are served by the core."""

    kind = "APIService"
    api_version = "apiregistration.k8s.io/v1beta1"

    @property
    def group_version(self) -> tuple[str, str]:
        return (self.spec.get("group", ""), self.spec.get("version", ""))


@dataclass
class Binding:
    """pods/binding subresource payload (reference pkg/registry/core/pod/rest;
    written by the scheduler at plugin/pkg/scheduler/scheduler.go:224)."""

    pod_name: str
    namespace: str
    target_node: str

    kind = "Binding"

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Binding":
        meta = d.get("metadata") or {}
        return cls(
            pod_name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            target_node=(d.get("target") or {}).get("name", ""),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": self.pod_name, "namespace": self.namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": self.target_node},
        }


@dataclass
class _RBACRuleObject:
    """Shared shape of Role/ClusterRole: a list of PolicyRules
    (staging/src/k8s.io/api/rbac/v1beta1/types.go PolicyRule —
    apiGroups/resources/verbs/resourceNames, '*' wildcards)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: list[dict[str, Any]] = field(default_factory=list)

    kind = ""
    api_version = "rbac.authorization.k8s.io/v1beta1"

    def clone(self):
        return type(self)(metadata=self.metadata.clone(),
                          rules=copy.deepcopy(self.rules))

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   rules=copy.deepcopy(d.get("rules") or []))

    def to_dict(self) -> dict[str, Any]:
        return {"apiVersion": self.api_version,
                "kind": self.kind,
                "metadata": self.metadata.to_dict(),
                "rules": copy.deepcopy(self.rules)}


@dataclass
class Role(_RBACRuleObject):
    """Namespaced RBAC rules (rbac/v1beta1 Role)."""

    kind = "Role"


@dataclass
class ClusterRole(_RBACRuleObject):
    """Cluster-wide RBAC rules (rbac/v1beta1 ClusterRole)."""

    kind = "ClusterRole"


@dataclass
class _RBACBindingObject:
    """Shared shape of (Cluster)RoleBinding: subjects + roleRef
    (rbac/v1beta1 Subject kinds User/Group/ServiceAccount)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: list[dict[str, Any]] = field(default_factory=list)
    role_ref: dict[str, Any] = field(default_factory=dict)

    kind = ""
    api_version = "rbac.authorization.k8s.io/v1beta1"

    def clone(self):
        return type(self)(metadata=self.metadata.clone(),
                          subjects=copy.deepcopy(self.subjects),
                          role_ref=dict(self.role_ref))

    @classmethod
    def from_dict(cls, d: dict[str, Any]):
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   subjects=copy.deepcopy(d.get("subjects") or []),
                   role_ref=dict(d.get("roleRef") or {}))

    def to_dict(self) -> dict[str, Any]:
        return {"apiVersion": self.api_version,
                "kind": self.kind,
                "metadata": self.metadata.to_dict(),
                "subjects": copy.deepcopy(self.subjects),
                "roleRef": dict(self.role_ref)}


@dataclass
class RoleBinding(_RBACBindingObject):
    """Grants a Role (or ClusterRole) within one namespace."""

    kind = "RoleBinding"


@dataclass
class ClusterRoleBinding(_RBACBindingObject):
    """Grants a ClusterRole across every namespace + cluster scope."""

    kind = "ClusterRoleBinding"


@dataclass
class CertificateSigningRequest:
    """certificates.k8s.io/v1beta1 CSR: spec carries the base64 PEM request
    + requestor identity; status carries Approved/Denied conditions and
    the issued certificate (signed by the certificate controller)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict[str, Any] = field(default_factory=dict)
    status: dict[str, Any] = field(default_factory=dict)

    kind = "CertificateSigningRequest"
    api_version = "certificates.k8s.io/v1beta1"

    def clone(self) -> "CertificateSigningRequest":
        return CertificateSigningRequest(
            metadata=self.metadata.clone(),
            spec=copy.deepcopy(self.spec),
            status=copy.deepcopy(self.status))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CertificateSigningRequest":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   spec=copy.deepcopy(d.get("spec") or {}),
                   status=copy.deepcopy(d.get("status") or {}))

    def to_dict(self) -> dict[str, Any]:
        return {"apiVersion": self.api_version, "kind": self.kind,
                "metadata": self.metadata.to_dict(),
                "spec": copy.deepcopy(self.spec),
                "status": copy.deepcopy(self.status)}
