"""Kubernetes resource.Quantity parsing.

The reference represents resource amounts as `resource.Quantity` strings
("100m", "1Gi", "0.5", "1e3") and converts them to int64 milli-units or bytes
for scheduling math (vendor/k8s.io/apimachinery/pkg/api/resource/quantity.go;
consumed at plugin/pkg/scheduler/schedulercache/node_info.go via
`Resource{MilliCPU, Memory, ...}`). We implement the same grammar with exact
decimal arithmetic so host-side encoding never loses precision before it
quantizes to device dtypes.
"""

from __future__ import annotations

from decimal import Decimal
from fractions import Fraction
from functools import lru_cache

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}


def parse_quantity(value: str | int | float) -> Fraction:
    """Parse a Kubernetes quantity into an exact Fraction of base units.

    Accepts ints/floats for convenience (treated as base units). String
    parses are memoized — clusters reuse a handful of distinct quantity
    strings, and Fraction/Decimal construction dominates the host-side
    accounting path otherwise.
    """
    if isinstance(value, Fraction):
        return value
    if isinstance(value, (int, float)):
        return Fraction(Decimal(str(value)))
    return _parse_str(value)


@lru_cache(maxsize=65536)
def _parse_str(value: str) -> Fraction:
    s = value.strip()
    if not s:
        raise ValueError("empty quantity")

    for suffix, mult in _BINARY_SUFFIXES.items():
        if s.endswith(suffix):
            return Fraction(Decimal(s[: -len(suffix)])) * mult

    # decimal-exponent form: 123e4 / 1.5E2 (no suffix letters besides e/E)
    num = s
    suffix = ""
    if s[-1] in _DECIMAL_SUFFIXES and s[-1] not in "eE":
        num, suffix = s[:-1], s[-1]
    try:
        return Fraction(Decimal(num)) * _DECIMAL_SUFFIXES[suffix]
    except Exception as e:  # noqa: BLE001
        raise ValueError(f"unparseable quantity {value!r}") from e


@lru_cache(maxsize=65536)
def to_milli(value: str | int | float) -> int:
    """Quantity -> integer milli-units, rounding up (reference rounds CPU
    quantities up to milli scale: resource.Quantity.MilliValue)."""
    frac = parse_quantity(value) * 1000
    return -((-frac.numerator) // frac.denominator)  # ceil


@lru_cache(maxsize=65536)
def to_int(value: str | int | float) -> int:
    """Quantity -> integer base units (bytes for memory), rounding up."""
    frac = parse_quantity(value)
    return -((-frac.numerator) // frac.denominator)
