"""Binary wire codec: the protobuf content-type for hot-path API traffic.

The reference's serializer negotiates `application/vnd.kubernetes.protobuf`
per request (CodecFactory, runtime/serializer/codec_factory.go; the
protobuf serializer at runtime/serializer/protobuf/protobuf.go:75 writes a
4-byte magic prefix + an Unknown envelope holding the typed message bytes).
This module is that codec for the framework's wire: dict payloads in the
v1 camelCase JSON shape (what encode_object/decode_object produce/consume)
encode to/from the wire.proto messages; kinds without a typed message ride
the Unknown envelope as JSON bytes (the runtime.RawExtension escape hatch),
so every payload can negotiate the binary content type.

Generated code is built from wire.proto with the system protoc on first
import (cached in _wiregen/, keyed by source mtime) and served by the upb C
runtime. If protoc or the protobuf runtime is missing, `available()` is
False and callers stay on JSON — negotiation degrades, nothing breaks.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess

log = logging.getLogger(__name__)

MAGIC = b"k8s\x00"  # protobuf.go:45 serializer prefix
CONTENT_TYPE = "application/vnd.kubernetes.protobuf"

_pb = None


def _load() -> None:
    global _pb
    here = os.path.dirname(__file__)
    src = os.path.join(here, "wire.proto")
    gen_dir = os.path.join(here, "_wiregen")
    gen = os.path.join(gen_dir, "wire_pb2.py")
    try:
        if (not os.path.exists(gen)
                or os.path.getmtime(gen) < os.path.getmtime(src)):
            os.makedirs(gen_dir, exist_ok=True)
            init = os.path.join(gen_dir, "__init__.py")
            if not os.path.exists(init):
                with open(init, "w", encoding="utf-8"):
                    pass
            # generate into a temp dir + atomic rename: concurrent first
            # importers must never see a half-written module (they would
            # silently degrade to JSON while peers speak protobuf)
            import tempfile
            with tempfile.TemporaryDirectory(dir=here) as tmp:
                subprocess.run(
                    ["protoc", f"-I{here}", f"--python_out={tmp}", src],
                    check=True, capture_output=True, timeout=60)
                os.replace(os.path.join(tmp, "wire_pb2.py"), gen)
        from kubernetes_tpu.api._wiregen import wire_pb2
        _pb = wire_pb2
    except (OSError, subprocess.SubprocessError, ImportError) as e:
        log.debug("protobuf wire codec unavailable (%s); JSON only", e)


_load()


def available() -> bool:
    return _pb is not None


# ---- field mapping: v1 JSON dict shape <-> proto messages ----
#
# to_dict() omits empty/default fields and from_dict() defaults them back,
# so the mapping only carries what is present; decoded dicts are
# from_dict-equivalent, not byte-identical JSON.


def _epoch(value) -> float:
    from kubernetes_tpu.api.objects import _cond_time
    return _cond_time(value)


def _meta_to(m, d: dict) -> None:
    m.name = d.get("name", "")
    m.namespace = d.get("namespace", "") or ""
    m.uid = d.get("uid", "") or ""
    for k, v in (d.get("labels") or {}).items():
        m.labels[k] = v
    for k, v in (d.get("annotations") or {}).items():
        m.annotations[k] = v
    m.resource_version = str(d.get("resourceVersion", "") or "")
    if d.get("ownerReferences"):
        m.owner_references_json = json.dumps(d["ownerReferences"]).encode()
    if d.get("creationTimestamp"):
        m.creation_timestamp = _epoch(d["creationTimestamp"])
    if d.get("deletionTimestamp") is not None:
        m.deletion_timestamp = _epoch(d["deletionTimestamp"])
    for f in d.get("finalizers") or []:
        m.finalizers.append(f)


def _meta_from(m) -> dict:
    d: dict = {"name": m.name}
    if m.namespace:
        d["namespace"] = m.namespace
    if m.uid:
        d["uid"] = m.uid
    if m.labels:
        d["labels"] = dict(m.labels)
    if m.annotations:
        d["annotations"] = dict(m.annotations)
    if m.resource_version:
        d["resourceVersion"] = m.resource_version
    if m.owner_references_json:
        d["ownerReferences"] = json.loads(m.owner_references_json)
    if m.creation_timestamp:
        d["creationTimestamp"] = m.creation_timestamp
    if m.HasField("deletion_timestamp"):
        d["deletionTimestamp"] = m.deletion_timestamp
    if m.finalizers:
        d["finalizers"] = list(m.finalizers)
    return d


def _pod_to(msg, d: dict) -> None:
    _meta_to(msg.metadata, d.get("metadata") or {})
    spec = d.get("spec") or {}
    s = msg.spec
    s.node_name = spec.get("nodeName", "") or ""
    for k, v in (spec.get("nodeSelector") or {}).items():
        s.node_selector[k] = v
    for c in spec.get("containers") or []:
        pc = s.containers.add()
        pc.name = c.get("name", "")
        pc.image = c.get("image", "") or ""
        res = c.get("resources") or {}
        for k, v in (res.get("requests") or {}).items():
            pc.requests[k] = str(v)
        for k, v in (res.get("limits") or {}).items():
            pc.limits[k] = str(v)
        for p in c.get("ports") or []:
            pp = pc.ports.add()
            pp.container_port = int(p.get("containerPort", 0))
            pp.host_port = int(p.get("hostPort", 0))
            pp.protocol = p.get("protocol", "") or ""
            pp.host_ip = p.get("hostIP", "") or ""
        if c.get("livenessProbe"):
            pc.liveness_probe_json = json.dumps(c["livenessProbe"]).encode()
        if c.get("readinessProbe"):
            pc.readiness_probe_json = json.dumps(
                c["readinessProbe"]).encode()
    for t in spec.get("tolerations") or []:
        pt = s.tolerations.add()
        pt.key = t.get("key", "") or ""
        pt.operator = t.get("operator", "") or ""
        pt.value = t.get("value", "") or ""
        pt.effect = t.get("effect", "") or ""
        if t.get("tolerationSeconds") is not None:
            pt.toleration_seconds = int(t["tolerationSeconds"])
    if spec.get("affinity"):
        s.affinity_json = json.dumps(spec["affinity"]).encode()
    if spec.get("volumes"):
        s.volumes_json = json.dumps(spec["volumes"]).encode()
    s.scheduler_name = spec.get("schedulerName", "") or ""
    s.restart_policy = spec.get("restartPolicy", "") or ""
    s.priority = int(spec.get("priority", 0) or 0)
    s.service_account_name = spec.get("serviceAccountName", "") or ""
    status = d.get("status") or {}
    msg.status.phase = status.get("phase", "") or ""
    if status.get("conditions"):
        msg.status.conditions_json = json.dumps(
            status["conditions"]).encode()
    msg.status.host_ip = status.get("hostIP", "") or ""
    if status.get("containerStatuses"):
        msg.status.container_statuses_json = json.dumps(
            status["containerStatuses"]).encode()


def _pod_from(msg) -> dict:
    s = msg.spec
    spec: dict = {}
    if s.node_name:
        spec["nodeName"] = s.node_name
    if s.node_selector:
        spec["nodeSelector"] = dict(s.node_selector)
    if s.containers:
        containers = []
        for pc in s.containers:
            c: dict = {"name": pc.name}
            if pc.image:
                c["image"] = pc.image
            res: dict = {}
            if pc.requests:
                res["requests"] = dict(pc.requests)
            if pc.limits:
                res["limits"] = dict(pc.limits)
            if res:
                c["resources"] = res
            if pc.ports:
                c["ports"] = [{
                    "containerPort": pp.container_port,
                    "hostPort": pp.host_port,
                    **({"protocol": pp.protocol} if pp.protocol else {}),
                    **({"hostIP": pp.host_ip} if pp.host_ip else {}),
                } for pp in pc.ports]
            if pc.liveness_probe_json:
                c["livenessProbe"] = json.loads(pc.liveness_probe_json)
            if pc.readiness_probe_json:
                c["readinessProbe"] = json.loads(pc.readiness_probe_json)
            containers.append(c)
        spec["containers"] = containers
    if s.tolerations:
        tolerations = []
        for pt in s.tolerations:
            t: dict = {}
            if pt.key:
                t["key"] = pt.key
            if pt.operator:
                t["operator"] = pt.operator
            if pt.value:
                t["value"] = pt.value
            if pt.effect:
                t["effect"] = pt.effect
            if pt.HasField("toleration_seconds"):
                t["tolerationSeconds"] = pt.toleration_seconds
            tolerations.append(t)
        spec["tolerations"] = tolerations
    if s.affinity_json:
        spec["affinity"] = json.loads(s.affinity_json)
    if s.volumes_json:
        spec["volumes"] = json.loads(s.volumes_json)
    if s.scheduler_name:
        spec["schedulerName"] = s.scheduler_name
    if s.restart_policy:
        spec["restartPolicy"] = s.restart_policy
    if s.priority:
        spec["priority"] = s.priority
    if s.service_account_name:
        spec["serviceAccountName"] = s.service_account_name
    status: dict = {}
    if msg.status.phase:
        status["phase"] = msg.status.phase
    if msg.status.conditions_json:
        status["conditions"] = json.loads(msg.status.conditions_json)
    if msg.status.host_ip:
        status["hostIP"] = msg.status.host_ip
    if msg.status.container_statuses_json:
        status["containerStatuses"] = json.loads(
            msg.status.container_statuses_json)
    return {"kind": "Pod", "apiVersion": "v1",
            "metadata": _meta_from(msg.metadata), "spec": spec,
            "status": status}


def _node_to(msg, d: dict) -> None:
    _meta_to(msg.metadata, d.get("metadata") or {})
    spec = d.get("spec") or {}
    msg.spec.unschedulable = bool(spec.get("unschedulable", False))
    for t in spec.get("taints") or []:
        pt = msg.spec.taints.add()
        pt.key = t.get("key", "") or ""
        pt.value = t.get("value", "") or ""
        pt.effect = t.get("effect", "") or ""
    msg.spec.provider_id = spec.get("providerID", "") or ""
    msg.spec.pod_cidr = spec.get("podCIDR", "") or ""
    status = d.get("status") or {}
    st = msg.status
    for k, v in (status.get("capacity") or {}).items():
        st.capacity[k] = str(v)
    for k, v in (status.get("allocatable") or {}).items():
        st.allocatable[k] = str(v)
    for c in status.get("conditions") or []:
        pc = st.conditions.add()
        pc.type = c.get("type", "") or ""
        pc.status = c.get("status", "") or ""
        pc.last_heartbeat_time = _epoch(c.get("lastHeartbeatTime"))
        pc.last_transition_time = _epoch(c.get("lastTransitionTime"))
        pc.reason = c.get("reason", "") or ""
    if status.get("images"):
        st.images_json = json.dumps(status["images"]).encode()
    if status.get("volumesAttached"):
        st.volumes_attached_json = json.dumps(
            status["volumesAttached"]).encode()
    for v in status.get("volumesInUse") or []:
        st.volumes_in_use.append(v)
    if status.get("daemonEndpoints"):
        st.daemon_endpoints_json = json.dumps(
            status["daemonEndpoints"]).encode()


def _node_from(msg) -> dict:
    spec: dict = {}
    if msg.spec.unschedulable:
        spec["unschedulable"] = True
    if msg.spec.taints:
        spec["taints"] = [{
            "key": t.key,
            **({"value": t.value} if t.value else {}),
            "effect": t.effect} for t in msg.spec.taints]
    if msg.spec.provider_id:
        spec["providerID"] = msg.spec.provider_id
    if msg.spec.pod_cidr:
        spec["podCIDR"] = msg.spec.pod_cidr
    st = msg.status
    status: dict = {}
    if st.capacity:
        status["capacity"] = dict(st.capacity)
    if st.allocatable:
        status["allocatable"] = dict(st.allocatable)
    if st.conditions:
        conditions = []
        for c in st.conditions:
            cd: dict = {"type": c.type, "status": c.status}
            if c.last_heartbeat_time:
                cd["lastHeartbeatTime"] = c.last_heartbeat_time
            if c.last_transition_time:
                cd["lastTransitionTime"] = c.last_transition_time
            if c.reason:
                cd["reason"] = c.reason
            conditions.append(cd)
        status["conditions"] = conditions
    if st.images_json:
        status["images"] = json.loads(st.images_json)
    if st.volumes_attached_json:
        status["volumesAttached"] = json.loads(st.volumes_attached_json)
    if st.volumes_in_use:
        status["volumesInUse"] = list(st.volumes_in_use)
    if st.daemon_endpoints_json:
        status["daemonEndpoints"] = json.loads(st.daemon_endpoints_json)
    return {"kind": "Node", "apiVersion": "v1",
            "metadata": _meta_from(msg.metadata), "spec": spec,
            "status": status}


def _binding_to(msg, d: dict) -> None:
    meta = d.get("metadata") or {}
    msg.name = meta.get("name", "")
    msg.namespace = meta.get("namespace", "") or ""
    msg.target_node = (d.get("target") or {}).get("name", "")


def _binding_from(msg) -> dict:
    return {"kind": "Binding", "apiVersion": "v1",
            "metadata": {"name": msg.name,
                         "namespace": msg.namespace or "default"},
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": msg.target_node}}


def _event_to(msg, d: dict) -> None:
    _meta_to(msg.metadata, d.get("metadata") or {})
    if d.get("involvedObject"):
        msg.involved_object_json = json.dumps(d["involvedObject"]).encode()
    msg.reason = d.get("reason", "") or ""
    msg.message = d.get("message", "") or ""
    msg.type = d.get("type", "") or ""
    msg.count = int(d.get("count", 1) or 1)
    msg.source_component = (d.get("source") or {}).get("component", "") or ""


def _event_from(msg) -> dict:
    return {"kind": "Event", "apiVersion": "v1",
            "metadata": _meta_from(msg.metadata),
            "involvedObject": (json.loads(msg.involved_object_json)
                               if msg.involved_object_json else {}),
            "reason": msg.reason, "message": msg.message,
            "type": msg.type or "Normal", "count": msg.count or 1,
            "source": {"component": msg.source_component}}


_TYPED = {  # kind -> (message factory name, fill, restore)
    "Pod": ("Pod", _pod_to, _pod_from),
    "Node": ("Node", _node_to, _node_from),
    "Binding": ("Binding", _binding_to, _binding_from),
    "Event": ("Event", _event_to, _event_from),
}


def _encode_unknown(d: dict) -> bytes:
    """One object dict -> Unknown envelope bytes (no magic prefix)."""
    kind = d.get("kind", "")
    u = _pb.Unknown()
    u.kind = kind
    typed = _TYPED.get(kind)
    if typed is not None:
        msg_name, fill, _restore = typed
        msg = getattr(_pb, msg_name)()
        fill(msg, d)
        u.raw = msg.SerializeToString()
    else:
        u.raw = json.dumps(d).encode()
        u.raw_is_json = True
    return u.SerializeToString()


def _decode_unknown(data: bytes) -> dict:
    u = _pb.Unknown()
    u.ParseFromString(data)
    return _restore_unknown(u)


def _restore_unknown(u) -> dict:
    if u.raw_is_json:
        return json.loads(u.raw)
    typed = _TYPED.get(u.kind)
    if typed is None:
        raise ValueError(f"undecodable wire kind {u.kind!r}")
    msg_name, _fill, restore = typed
    msg = getattr(_pb, msg_name)()
    msg.ParseFromString(u.raw)
    return restore(msg)


def encode_payload(payload: dict) -> bytes:
    """Any response/request payload dict -> magic-prefixed wire bytes.
    List payloads ({kind: "XList", items: [...]}) become KList."""
    kind = payload.get("kind", "")
    if kind.endswith("List") and "items" in payload:
        kl = _pb.KList()
        kl.kind = kind
        kl.resource_version = str(
            (payload.get("metadata") or {}).get("resourceVersion", ""))
        for item in payload["items"]:
            kl.items.append(_encode_unknown(item))
        u = _pb.Unknown()
        u.kind = "KList"
        u.raw = kl.SerializeToString()
        return MAGIC + u.SerializeToString()
    return MAGIC + _encode_unknown(payload)


def decode_payload(data: bytes) -> dict:
    """Wire bytes -> payload dict. Raises ValueError on ANY undecodable
    input (protobuf DecodeError is normalized so callers handle one
    exception shape for both content types — json.JSONDecodeError already
    IS a ValueError)."""
    try:
        return _decode_payload(data)
    except ValueError:
        raise
    except Exception as e:  # DecodeError and friends
        raise ValueError(f"undecodable protobuf payload: {e}") from e


def _decode_payload(data: bytes) -> dict:
    if not data.startswith(MAGIC):
        raise ValueError("missing protobuf wire magic")
    u = _pb.Unknown()
    u.ParseFromString(data[len(MAGIC):])
    if u.kind == "KList" and not u.raw_is_json:
        kl = _pb.KList()
        kl.ParseFromString(u.raw)
        return {"kind": kl.kind,
                "metadata": {"resourceVersion": kl.resource_version},
                "items": [_decode_unknown(i) for i in kl.items]}
    return _restore_unknown(u)


# ---- watch framing: 4-byte big-endian length + WatchFrame bytes ----


def encode_watch_frame(event_type: str, resource_version: int,
                       obj_dict: dict) -> bytes:
    f = _pb.WatchFrame()
    f.type = event_type
    f.resource_version = resource_version
    f.object = _encode_unknown(obj_dict)
    body = f.SerializeToString()
    return len(body).to_bytes(4, "big") + body


HEARTBEAT = (0).to_bytes(4, "big")


def decode_watch_frame(body: bytes) -> dict:
    """Frame bytes (after the length prefix) -> the JSON frame shape.
    Raises ValueError on any undecodable input (like decode_payload)."""
    try:
        f = _pb.WatchFrame()
        f.ParseFromString(body)
        return {"type": f.type, "resourceVersion": f.resource_version,
                "object": _decode_unknown(f.object)}
    except ValueError:
        raise
    except Exception as e:
        raise ValueError(f"undecodable watch frame: {e}") from e
