from kubernetes_tpu.api.quantity import parse_quantity  # noqa: F401
from kubernetes_tpu.api.objects import (  # noqa: F401
    Binding,
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
)
