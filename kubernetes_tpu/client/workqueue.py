"""Rate-limited work queues with per-item exponential backoff.

Combines the semantics of client-go's workqueue (dedup: an item re-added
while being processed is reprocessed once, never concurrently;
client-go/util/workqueue/) and the scheduler's PodBackoff (exponential
per-pod delay, doubling to a max of 60s by default; reference
plugin/pkg/scheduler/util/backoff_utils.go and factory.go:897
MakeDefaultErrorFunc).
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Hashable


class Backoff:
    """Per-item exponential backoff (backoff_utils.go semantics)."""

    def __init__(self, initial: float = 1.0, max_duration: float = 60.0):
        self.initial = initial
        self.max_duration = max_duration
        self._durations: dict[Hashable, float] = {}
        self._last: dict[Hashable, float] = {}

    def next_delay(self, item: Hashable) -> float:
        cur = self._durations.get(item, 0.0)
        nxt = min(cur * 2 if cur else self.initial, self.max_duration)
        self._durations[item] = nxt
        self._last[item] = time.monotonic()
        return nxt

    def reset(self, item: Hashable) -> None:
        self._durations.pop(item, None)
        self._last.pop(item, None)

    def gc(self, max_age: float = 600.0) -> None:
        cutoff = time.monotonic() - max_age
        for item in [i for i, t in self._last.items() if t < cutoff]:
            self._durations.pop(item, None)
            self._last.pop(item, None)


class BackoffQueue:
    """Async dedup queue with optional delayed re-adds.

    - `add(item)`: enqueue now (no-op if queued; marked dirty if processing)
    - `add_after(item, delay)`: enqueue once `delay` elapses
    - `get()` / `get_batch(n)`: pop items, marking them processing
    - `done(item)`: finish processing; if dirtied meanwhile, requeue
    """

    def __init__(self, name: str | None = None, registry=None):
        self._queue: list[Hashable] = []
        self._queued: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._dirty: set[Hashable] = set()
        self._delayed: list[tuple[float, int, Hashable]] = []
        self._seq = 0
        self._event = asyncio.Event()
        self._closed = False
        # metrics engage only for NAMED queues (client-go's
        # NewNamedRateLimitingQueue contract — unnamed queues stay free of
        # per-item accounting); `name` may be assigned after construction
        # (controllers learn their name post-__init__) and children
        # re-resolve lazily
        self.name = name
        self._registry = registry
        self._mx: tuple | None = None
        self._added_at: dict[Hashable, float] = {}
        self._started_at: dict[Hashable, float] = {}

    def _metrics(self) -> tuple | None:
        """(name, depth, adds, retries, queue_dur, work_dur) children for
        the current queue name — the client-go workqueue metrics provider
        families (workqueue/metrics.go), labeled by queue."""
        if self.name is None:
            return None
        if self._mx is None or self._mx[0] != self.name:
            from kubernetes_tpu.obs import metrics as m

            reg = self._registry if self._registry is not None else m.REGISTRY
            lat_buckets = m.exponential_buckets(1e-5, 4.0, 10)
            self._mx = (
                self.name,
                reg.gauge("workqueue_depth",
                          "Current depth of the workqueue.",
                          ("name",)).labels(self.name),
                reg.counter("workqueue_adds_total",
                            "Total adds handled by the workqueue.",
                            ("name",)).labels(self.name),
                reg.counter("workqueue_retries_total",
                            "Total delayed (backoff) re-adds of items "
                            "requeued after a failure.",
                            ("name",)).labels(self.name),
                reg.histogram("workqueue_queue_duration_seconds",
                              "How long an item stays queued before "
                              "processing starts.",
                              ("name",), buckets=lat_buckets
                              ).labels(self.name),
                reg.histogram("workqueue_work_duration_seconds",
                              "How long processing an item takes.",
                              ("name",), buckets=lat_buckets
                              ).labels(self.name),
            )
        return self._mx

    def __len__(self) -> int:
        return len(self._queue)

    def add(self, item: Hashable) -> None:
        if item in self._processing:
            self._dirty.add(item)
            return
        if item in self._queued:
            return
        self._queued.add(item)
        self._queue.append(item)
        mx = self._metrics()
        if mx is not None:
            mx[2].inc()
            mx[1].set(len(self._queue))
            self._added_at[item] = time.monotonic()
        self._event.set()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        mx = self._metrics()
        if mx is not None:
            mx[3].inc()
        self._seq += 1
        heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, item))
        self._event.set()

    def done(self, item: Hashable) -> None:
        self._processing.discard(item)
        mx = self._metrics()
        if mx is not None:
            started = self._started_at.pop(item, None)
            if started is not None:
                mx[5].observe(time.monotonic() - started)
        if item in self._dirty:
            self._dirty.discard(item)
            self.add(item)

    def close(self) -> None:
        self._closed = True
        self._event.set()

    def _drain_delayed(self) -> float | None:
        """Move due delayed items into the queue; return seconds until the
        next delayed item (None if no delayed items)."""
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            self.add(item)
        return self._delayed[0][0] - now if self._delayed else None

    async def get_batch(self, max_items: int, wait: float | None = None) -> list[Hashable]:
        """Pop up to max_items; blocks until at least one is available (or
        `wait` elapses -> empty list; queue closed -> empty list)."""
        deadline = time.monotonic() + wait if wait is not None else None
        while True:
            if self._closed:
                return []
            next_delay = self._drain_delayed()
            if self._queue:
                n = min(max_items, len(self._queue))
                items = self._queue[:n]
                del self._queue[:n]
                for item in items:
                    self._queued.discard(item)
                    self._processing.add(item)
                mx = self._metrics()
                if mx is not None:
                    now = time.monotonic()
                    observe = mx[4].observe
                    added_pop = self._added_at.pop
                    for item in items:
                        observe(now - added_pop(item, now))
                        self._started_at[item] = now
                    mx[1].set(len(self._queue))
                return items
            timeout = next_delay
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return []
                timeout = min(timeout, remain) if timeout is not None else remain
            self._event.clear()
            try:
                await asyncio.wait_for(self._event.wait(), timeout)
            except asyncio.TimeoutError:
                if deadline is not None and time.monotonic() >= deadline:
                    return []

    async def get(self, wait: float | None = None) -> Hashable | None:
        items = await self.get_batch(1, wait=wait)
        return items[0] if items else None
