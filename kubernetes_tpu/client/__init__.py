from kubernetes_tpu.client.informer import Informer  # noqa: F401
from kubernetes_tpu.client.workqueue import BackoffQueue  # noqa: F401
