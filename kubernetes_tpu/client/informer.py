"""Informer: list+watch cache with resume semantics.

The asyncio re-design of client-go's Reflector/SharedIndexInformer
(client-go/tools/cache/reflector.go:239 ListAndWatch: full List, then Watch
from the list's resourceVersion; on an expired resume point, relist). The
local cache is a dict the way the reference's ThreadSafeStore is; handlers
fire in watch order on the owning asyncio loop, so no locking is needed.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Callable

from kubernetes_tpu.apiserver.store import Expired, ObjectStore, WatchEvent

log = logging.getLogger(__name__)

Handler = Callable[[WatchEvent], None]

# relist backoff: base->cap doubling, reset after a successful list (the
# Reflector's backoff manager shape — a dead store must not be hammered at
# a fixed 50ms by every informer in the process at once)
RELIST_BACKOFF_INITIAL = 0.05
RELIST_BACKOFF_MAX = 5.0

# During a relist's delta replay, yield the event loop every this many
# dispatched synthetic events. A relist under churn (watch history expired
# or the watcher dropped as a slow consumer) replays hundreds of
# ADDED/MODIFIED events through every registered handler; doing that in
# one callback holds the loop for 100ms+ — co-resident heartbeats,
# schedulers, and stall watchdogs all read it as a control-plane stall.
RELIST_YIELD_EVERY = 32

_reflector_mx: dict[str, tuple] = {}


def _metrics(kind: str) -> tuple:
    """(lists, list_duration, watches, relists) children for one kind — the
    client-go reflector metrics families (cache/reflector_metrics.go),
    labeled by watched kind."""
    mx = _reflector_mx.get(kind)
    if mx is None:
        from kubernetes_tpu.obs import metrics as m

        mx = (
            m.REGISTRY.counter("reflector_lists_total",
                               "Full lists performed by informers.",
                               ("kind",)).labels(kind),
            m.REGISTRY.histogram("reflector_list_duration_seconds",
                                 "How long an informer's full list+replay "
                                 "takes.", ("kind",)).labels(kind),
            m.REGISTRY.counter("reflector_watches_total",
                               "Watch streams opened by informers.",
                               ("kind",)).labels(kind),
            m.REGISTRY.counter("informer_relists_total",
                               "Relists after a watch ended, expired, or "
                               "the list/watch cycle failed.",
                               ("kind",)).labels(kind),
            m.REGISTRY.counter("informer_failover_resumes_total",
                               "Watches resumed from the last delivered "
                               "resourceVersion after a transport failure "
                               "(replica failover) — the cheap path that "
                               "spares a full relist.",
                               ("kind",)).labels(kind),
        )
        _reflector_mx[kind] = mx
    return mx


class Informer:
    def __init__(self, store: ObjectStore, kind: str,
                 relist_backoff_initial: float = RELIST_BACKOFF_INITIAL,
                 relist_backoff_max: float = RELIST_BACKOFF_MAX,
                 rng: random.Random | None = None):
        self.store = store
        self.kind = kind
        self.cache: dict[tuple[str, str], Any] = {}
        self._handlers: list[Handler] = []
        self._task: asyncio.Task | None = None
        self._synced = asyncio.Event()
        self._backoff_initial = relist_backoff_initial
        self._backoff_max = relist_backoff_max
        self._relist_delay = relist_backoff_initial
        self._rng = rng if rng is not None else random
        # server Retry-After hint from the last failed cycle: the next
        # relist waits at least this long, whatever the local backoff says
        self._retry_hint = 0.0
        # HA failover: last delivered resourceVersion + whether the last
        # cycle died in TRANSPORT (replica killed/drained mid-stream) —
        # only then is resume-from-rv attempted before a full relist. A
        # clean stream end (evicted slow consumer, expired resume point)
        # keeps the relist contract.
        self._last_rv: int | None = None
        self._resume_next = False

    def add_handler(self, handler: Handler) -> None:
        self._handlers.append(handler)

    # ---- lister interface ----

    def get(self, name: str, namespace: str = "default") -> Any | None:
        return self.cache.get((namespace, name))

    def items(self) -> list[Any]:
        return list(self.cache.values())

    async def wait_for_sync(self) -> None:
        await self._synced.wait()

    # ---- lifecycle ----

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def _backoff_next(self) -> float:
        """Current relist delay; doubles toward the cap until a successful
        list resets it (client-go's ListAndWatch backoff manager)."""
        delay = self._relist_delay
        self._relist_delay = min(2 * delay, self._backoff_max)
        return delay

    async def _run(self) -> None:
        first = True
        while True:
            if not first:
                # jittered (0.5x-1.5x) so N informers relisting after one
                # store hiccup don't stampede it in lockstep; an APF
                # Retry-After hint from the last 429 sets the floor — the
                # server knows its queue depth better than local doubling
                delay = self._backoff_next()
                hint, self._retry_hint = self._retry_hint, 0.0
                await asyncio.sleep(
                    max(hint, delay * (0.5 + self._rng.random())))
                if self._resume_next and await self._try_resume():
                    continue
                _metrics(self.kind)[3].inc()
            first = False
            self._resume_next = False
            try:
                await self._list_and_watch()
                # clean watch end (expired resume point or evicted as a
                # slow consumer): the successful list inside already reset
                # the backoff, so the next relist runs at the base delay
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — reflector loops survive anything
                self._retry_hint = float(
                    getattr(e, "retry_after", 0.0) or 0.0)
                self._resume_next = isinstance(
                    e, (ConnectionError, TimeoutError, asyncio.TimeoutError))
                log.exception(
                    "informer %s: list/watch failed; %s", self.kind,
                    "resuming from last rv" if self._resume_next
                    else "relisting")

    async def _try_resume(self) -> bool:
        """Failover resume: after a watch died in transport (its replica
        was killed or drained), try a watch from the last delivered rv —
        the replica-aware RemoteStore opens it on a surviving endpoint —
        before paying for a full relist. False (Expired/transport failure
        on the new endpoint too) falls back to the relist path."""
        if self._last_rv is None:
            return False
        mx = _metrics(self.kind)
        try:
            stream = self.store.watch(self.kind, since=self._last_rv)
        except (Expired, ConnectionError, OSError, ValueError):
            return False
        try:
            # the first next() surfaces a deferred handshake failure
            # (_LazyWatch): 410 -> Expired, dead endpoint -> ConnectionError
            event = await stream.next(timeout=1.0)
        except (Expired, ConnectionError, TimeoutError,
                asyncio.TimeoutError, OSError, ValueError):
            stream.stop()
            return False
        mx[4].inc()
        self._relist_delay = self._backoff_initial  # healthy again
        self._resume_next = False
        try:
            while True:
                if event is not None:
                    self._last_rv = event.resource_version
                    self._apply(event)
                    self._dispatch(event)
                event = await stream.next()
                if event is None:  # clean stream end -> relist contract
                    return True
        except (ConnectionError, TimeoutError, asyncio.TimeoutError):
            self._resume_next = True  # died in transport again: re-resume
            return True
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            log.exception("informer %s: resumed watch failed; relisting",
                          self.kind)
            return True
        finally:
            stream.stop()

    async def _list_and_watch(self) -> None:
        import time

        mx = _metrics(self.kind)
        t_list = time.monotonic()
        items, rv = self.store.list_with_version(self.kind)
        fresh = {(o.metadata.namespace, o.metadata.name): o for o in items}
        # replay the delta between cache and fresh list as synthetic
        # events, yielding every RELIST_YIELD_EVERY dispatches so a big
        # replay stays cooperative; the cache is updated incrementally so
        # readers interleaved at a yield point see exactly the objects
        # whose events have been dispatched so far
        dispatched = 0
        for key, obj in fresh.items():
            old = self.cache.get(key)
            if old is None:
                self.cache[key] = obj
                self._dispatch(WatchEvent("ADDED", self.kind, obj, rv))
            elif old.metadata.resource_version != obj.metadata.resource_version:
                self.cache[key] = obj
                self._dispatch(WatchEvent("MODIFIED", self.kind, obj, rv))
            else:
                continue
            dispatched += 1
            if dispatched % RELIST_YIELD_EVERY == 0:
                await asyncio.sleep(0)
        for key in list(self.cache.keys() - fresh.keys()):
            self._dispatch(WatchEvent("DELETED", self.kind,
                                      self.cache.pop(key), rv))
            dispatched += 1
            if dispatched % RELIST_YIELD_EVERY == 0:
                await asyncio.sleep(0)
        self.cache = dict(fresh)
        self._synced.set()
        self._relist_delay = self._backoff_initial  # healthy again
        self._last_rv = rv
        mx[0].inc()
        mx[1].observe(time.monotonic() - t_list)

        try:
            stream = self.store.watch(self.kind, since=rv)
        except Expired:
            return  # relist
        mx[2].inc()
        try:
            async for event in stream:
                self._last_rv = event.resource_version
                self._apply(event)
                self._dispatch(event)
        finally:
            stream.stop()

    def _apply(self, event: WatchEvent) -> None:
        key = (event.obj.metadata.namespace, event.obj.metadata.name)
        if event.type == "DELETED":
            self.cache.pop(key, None)
        else:
            self.cache[key] = event.obj

    def _dispatch(self, event: WatchEvent) -> None:
        for h in self._handlers:
            try:
                h(event)
            except Exception:  # noqa: BLE001
                log.exception("informer %s: handler failed on %s",
                              self.kind, event.type)
