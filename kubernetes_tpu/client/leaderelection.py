"""Leader election via CAS on a lock object's annotation.

The client-go LeaderElector re-design (client-go/tools/leaderelection/
leaderelection.go:138 Run, :172 acquire, :146 renew; record format
resourcelock.LeaderElectionRecord in the
``control-plane.alpha.kubernetes.io/leader`` annotation of an Endpoints
object — endpointslock.go). The store's `guaranteed_update` CAS plays the
role of the apiserver's resourceVersion-checked update.

Semantics preserved from the reference:
- a candidate acquires when the record is absent, expired
  (renewTime + leaseDuration < now), or already its own;
- the holder renews every retry_period and must succeed within
  renew_deadline or it stops leading;
- `leaderTransitions` increments only when the holder identity changes;
- losing the lease calls on_stopped_leading — the reference process exits
  and its replica takes over from shared state (crash-only HA).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable

from kubernetes_tpu.api.objects import Endpoints, ObjectMeta
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    TooManyRequests,
)

log = logging.getLogger(__name__)

LEADER_ANNOTATION = "control-plane.alpha.kubernetes.io/leader"

# componentconfig defaults (leaderelection.go / options)
LEASE_DURATION = 15.0
RENEW_DEADLINE = 10.0
RETRY_PERIOD = 2.0


@dataclass
class LeaderElectionRecord:
    holder_identity: str
    lease_duration_seconds: float
    acquire_time: float
    renew_time: float
    leader_transitions: int = 0

    def to_json(self) -> str:
        return json.dumps({
            "holderIdentity": self.holder_identity,
            "leaseDurationSeconds": self.lease_duration_seconds,
            "acquireTime": self.acquire_time,
            "renewTime": self.renew_time,
            "leaderTransitions": self.leader_transitions,
        })

    @classmethod
    def from_json(cls, raw: str) -> "LeaderElectionRecord | None":
        try:
            d = json.loads(raw)
            return cls(
                holder_identity=d.get("holderIdentity", ""),
                lease_duration_seconds=float(
                    d.get("leaseDurationSeconds", LEASE_DURATION)),
                acquire_time=float(d.get("acquireTime", 0.0)),
                renew_time=float(d.get("renewTime", 0.0)),
                leader_transitions=int(d.get("leaderTransitions", 0)),
            )
        except (ValueError, TypeError):
            return None


class LeaderElector:
    def __init__(self, store, identity: str,
                 lock_name: str = "kube-scheduler",
                 lock_namespace: str = "kube-system", *,
                 lease_duration: float = LEASE_DURATION,
                 renew_deadline: float = RENEW_DEADLINE,
                 retry_period: float = RETRY_PERIOD,
                 on_started_leading: Callable[[], Awaitable] | None = None,
                 on_stopped_leading: Callable[[], None] | None = None,
                 rng: random.Random | None = None):
        self.store = store
        self.identity = identity
        self.lock_name = lock_name
        self.lock_namespace = lock_namespace
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        self._stop = False
        self._rng = rng if rng is not None else random.Random()

    def _jittered(self, period: float) -> float:
        """retry_period with ±10% jitter (wait.JitterUntil's JitterFactor):
        standbys polling for an expired lease — and leaders renewing —
        must not thunder against the store in lockstep."""
        return period * (0.9 + 0.2 * self._rng.random())

    # ---- lock record I/O ----

    def _get_record(self) -> LeaderElectionRecord | None:
        try:
            obj = self.store.get("Endpoints", self.lock_name,
                                 self.lock_namespace)
        except NotFound:
            return None
        except (TooManyRequests, ConnectionError, TimeoutError):
            # a throttled read — or a dead/draining replica the client is
            # mid-failover around — is a failed attempt, not a crash AND
            # not "no record" (treating it as absent would race a create
            # against the real holder): the acquire/renew loop retries on
            # its jittered period, and the deadline anchors to the last
            # SUCCESSFUL renew, so leadership survives any outage shorter
            # than renew_deadline
            raise _Unavailable() from None
        raw = obj.metadata.annotations.get(LEADER_ANNOTATION)
        return LeaderElectionRecord.from_json(raw) if raw else None

    def _try_acquire_or_renew(self, now: float) -> bool:
        """One acquire-or-renew attempt (tryAcquireOrRenew,
        leaderelection.go:210). Returns True while holding the lease."""
        try:
            current = self._get_record()
        except _Unavailable:
            return False
        if current is None:
            record = LeaderElectionRecord(
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now)
            return self._write_record(record, create_ok=True)
        expired = current.renew_time + current.lease_duration_seconds < now
        if current.holder_identity != self.identity and not expired:
            return False  # someone else holds an unexpired lease
        record = LeaderElectionRecord(
            holder_identity=self.identity,
            lease_duration_seconds=self.lease_duration,
            acquire_time=(current.acquire_time
                          if current.holder_identity == self.identity
                          else now),
            renew_time=now,
            leader_transitions=(current.leader_transitions
                                if current.holder_identity == self.identity
                                else current.leader_transitions + 1))
        return self._write_record(record)

    def _write_record(self, record: LeaderElectionRecord,
                      create_ok: bool = False) -> bool:
        if create_ok:
            obj = Endpoints(metadata=ObjectMeta(
                name=self.lock_name, namespace=self.lock_namespace,
                annotations={LEADER_ANNOTATION: record.to_json()}))
            try:
                self.store.create(obj)
                return True
            except AlreadyExists:
                pass  # raced another candidate: fall through to CAS update
            except (TooManyRequests, ConnectionError, TimeoutError):
                # throttled, or a dead replica mid-failover: this attempt
                # failed, retry on the jittered period
                return False

        def mutate(obj):
            # re-check under the CAS: a racing writer may have renewed
            raw = obj.metadata.annotations.get(LEADER_ANNOTATION)
            cur = LeaderElectionRecord.from_json(raw) if raw else None
            if cur is not None and cur.holder_identity != self.identity \
                    and cur.renew_time + cur.lease_duration_seconds \
                    >= record.renew_time:
                raise _Lost()
            obj.metadata.annotations[LEADER_ANNOTATION] = record.to_json()
            return obj

        try:
            self.store.guaranteed_update("Endpoints", self.lock_name,
                                         self.lock_namespace, mutate)
            return True
        except (_Lost, Conflict, NotFound, TooManyRequests,
                ConnectionError, TimeoutError):
            return False

    # ---- run loop ----

    async def run(self) -> None:
        """Block until leadership is acquired, run on_started_leading, and
        keep renewing; returns after the lease is lost or stop() is called
        (the reference exits the process here)."""
        while not self._stop:
            if self._try_acquire_or_renew(time.time()):
                break
            await asyncio.sleep(self._jittered(self.retry_period))
        if self._stop:
            return
        self.is_leader = True
        log.info("%s: became leader of %s/%s", self.identity,
                 self.lock_namespace, self.lock_name)
        work = None
        if self.on_started_leading is not None:
            work = asyncio.get_running_loop().create_task(
                self.on_started_leading())
        try:
            # the renew deadline anchors to the last SUCCESSFUL renew (the
            # acquire counts as one): a leader whose renews fail transiently
            # but land again within the deadline keeps the lease — only
            # renew_deadline of CONSECUTIVE failure loses it
            last_renew = time.time()
            while not self._stop:
                await asyncio.sleep(self._jittered(self.retry_period))
                if work is not None and work.done():
                    # the led work died: stop renewing so a standby can take
                    # over (the reference process would have exited)
                    if not work.cancelled() and work.exception() is not None:
                        log.error("%s: leading work failed: %s",
                                  self.identity, work.exception())
                    break
                if self._try_acquire_or_renew(time.time()):
                    last_renew = time.time()
                elif time.time() - last_renew > self.renew_deadline:
                    log.warning("%s: failed to renew lease within %.1fs",
                                self.identity, self.renew_deadline)
                    break
        finally:
            self.is_leader = False
            if work is not None:
                work.cancel()
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()

    def stop(self) -> None:
        self._stop = True


class _Lost(Exception):
    pass


class _Unavailable(Exception):
    """The lock store couldn't be reached at all — distinct from "no
    record" (which would trigger a racing create) and from "held by
    another" (which would reset the acquire clock)."""
