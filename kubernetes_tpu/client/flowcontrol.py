"""Client-side flow control: the token-bucket rate limiter.

The client-go util/flowcontrol analog (throttle.go tokenBucketRateLimiter:
qps refill, burst capacity) that caps a client's request rate against the
apiserver — the scheduler_perf harness configures the reference's client at
5000 QPS / 5000 burst (test/integration/scheduler_perf/util.go:46).
`RemoteStore(rate_limiter=...)` applies it to every blocking request via
`accept()`; coroutine callers (the async watch-open path, any future async
client verb) MUST go through `accept_async()` instead — the sync path
parks whatever thread it runs on, and on the event-loop thread that means
every watcher, timer and server in the process (ktpu-lint R1
blocking-in-async polices exactly this class)."""

from __future__ import annotations

import asyncio
import time


class TokenBucketRateLimiter:
    def __init__(self, qps: float, burst: int):
        if qps <= 0:
            raise ValueError("qps must be positive")
        self.qps = qps
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        # server-pushed backpressure: a 429's Retry-After hint holds the
        # whole bucket shut until this deadline (client-go's
        # WithRetryAfter coupling of server hints into client pacing)
        self._hold_until = 0.0

    def note_retry_after(self, seconds: float) -> None:
        """Honor a server Retry-After hint: no token is granted until the
        hint elapses (capped so one garbled header can't park a client
        for minutes). RemoteStore calls this on every 429 that carries
        the header."""
        if seconds <= 0:
            return
        self._hold_until = max(
            self._hold_until, time.monotonic() + min(seconds, 60.0))

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.qps)
        self._last = now

    def _take(self) -> float:
        """Take a token if available; else the seconds until one refills.
        Returns 0.0 on success (shared by both acquire paths, so sync and
        async callers drain one bucket with identical semantics)."""
        now = time.monotonic()
        if now < self._hold_until:
            return self._hold_until - now
        self._refill(now)
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return max((1.0 - self._tokens) / self.qps, 1e-4)

    def try_accept(self) -> bool:
        """Non-blocking TryAccept (throttle.go:103)."""
        return self._take() == 0.0

    def accept(self) -> None:
        """Blocking Accept: sleep until a token is available
        (throttle.go:91). Thread-only — from a coroutine, await
        accept_async() so the event loop keeps turning."""
        while True:
            wait = self._take()
            if wait == 0.0:
                return
            # threaded blocking client path only; async callers are routed
            # to accept_async (enforced by lint R1)
            time.sleep(wait)  # ktpu: allow[blocking-in-async]

    async def accept_async(self) -> None:
        """Async Accept: await a token without blocking the event loop
        (the same bucket — mixed sync/async callers contend fairly)."""
        while True:
            wait = self._take()
            if wait == 0.0:
                return
            await asyncio.sleep(wait)
