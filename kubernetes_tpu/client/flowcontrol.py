"""Client-side flow control: the token-bucket rate limiter.

The client-go util/flowcontrol analog (throttle.go tokenBucketRateLimiter:
qps refill, burst capacity) that caps a client's request rate against the
apiserver — the scheduler_perf harness configures the reference's client at
5000 QPS / 5000 burst (test/integration/scheduler_perf/util.go:46).
`RemoteStore(rate_limiter=...)` applies it to every blocking request."""

from __future__ import annotations

import time


class TokenBucketRateLimiter:
    def __init__(self, qps: float, burst: int):
        if qps <= 0:
            raise ValueError("qps must be positive")
        self.qps = qps
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._last = time.monotonic()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_accept(self) -> bool:
        """Non-blocking TryAccept (throttle.go:103)."""
        self._refill(time.monotonic())
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def accept(self) -> None:
        """Blocking Accept: sleep until a token is available
        (throttle.go:91)."""
        while True:
            now = time.monotonic()
            self._refill(now)
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return
            time.sleep(max((1.0 - self._tokens) / self.qps, 1e-4))
