"""SPDY-class streaming: channel-framed bidirectional exec/port-forward.

The reference multiplexes exec/attach/port-forward streams over one SPDY
connection with numbered channels (client-go/tools/remotecommand/
remotecommand.go:27 stdin=0/stdout=1/stderr=2/error=3; kubelet side at
pkg/kubelet/server/remotecommand; portforward framing in
client-go/tools/portforward). This framework keeps the topology and the
channel model but swaps SPDY's framing for a minimal explicit one over an
HTTP/1.1 Upgrade:

    request:  POST <path> HTTP/1.1 + Connection: Upgrade
              + Upgrade: ktpu-stream
    response: HTTP/1.1 101 Switching Protocols, then raw frames each way:
              [1-byte channel][4-byte big-endian length][payload]

Channels: 0 stdin/up, 1 stdout/down, 2 stderr, 3 error/status (one JSON
object, e.g. {"exitCode": 0} — the v4 error-channel shape). A zero-length
frame on a data channel closes that direction."""

from __future__ import annotations

import asyncio
import json
import socket

STDIN, STDOUT, STDERR, ERROR = 0, 1, 2, 3

UPGRADE_HEADER = "ktpu-stream"


def frame(channel: int, payload: bytes) -> bytes:
    return bytes([channel]) + len(payload).to_bytes(4, "big") + payload


async def read_frame(reader: asyncio.StreamReader):
    """-> (channel, payload) or None at EOF."""
    try:
        head = await reader.readexactly(5)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    length = int.from_bytes(head[1:], "big")
    payload = await reader.readexactly(length) if length else b""
    return head[0], payload


def recv_frame_sync(sock: socket.socket):
    """Blocking-socket read of one frame; None at EOF."""
    head = b""
    while len(head) < 5:
        chunk = sock.recv(5 - len(head))
        if not chunk:
            return None
        head += chunk
    length = int.from_bytes(head[1:], "big")
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return head[0], payload


def open_upgraded(host: str, port: int, path: str, token: str = "",
                  timeout: float = 30.0) -> socket.socket:
    """Blocking client handshake: connect, upgrade, return the raw socket
    positioned after the 101 response headers."""
    sock = socket.create_connection((host, port), timeout=timeout)
    auth = f"Authorization: Bearer {token}\r\n" if token else ""
    try:
        sock.sendall(
            f"POST {path} HTTP/1.1\r\n"
            f"Host: {host}\r\n{auth}"
            f"Connection: Upgrade\r\n"
            f"Upgrade: {UPGRADE_HEADER}\r\n"
            f"Content-Length: 0\r\n\r\n".encode())
        # read byte-wise to the end of headers: a frame the server sends
        # immediately after the 101 must stay in the socket buffer, not be
        # swallowed by an over-read (headers are tiny; this runs once)
        head = b""
        while not head.endswith(b"\r\n\r\n"):
            byte = sock.recv(1)
            if not byte:
                raise ConnectionError("connection closed during upgrade")
            head += byte
        status_line = head.split(b"\r\n", 1)[0]
        if b"101" not in status_line:
            raise ConnectionError(
                f"upgrade refused: {status_line.decode(errors='replace')}")
        return sock
    except Exception:
        sock.close()
        raise


def exec_stream(host: str, port: int, path: str, stdin_chunks,
                token: str = "") -> tuple[int, str, str]:
    """Blocking interactive exec: stream stdin chunks while collecting
    stdout/stderr until the error-channel status arrives.
    -> (exit_code, stdout, stderr). Sending runs on its own thread so a
    large stdin and a large output cannot deadlock on TCP flow control
    (the server writes per line; a send-everything-first client would
    fill both socket buffers and stall)."""
    import threading

    sock = open_upgraded(host, port, path, token=token)
    out: list[bytes] = []
    err: list[bytes] = []
    code = 0

    def send_all():
        try:
            for chunk in stdin_chunks:
                sock.sendall(frame(STDIN, chunk if isinstance(chunk, bytes)
                                   else chunk.encode()))
            sock.sendall(frame(STDIN, b""))  # EOF upstream
        except OSError:
            pass  # receiver side reports the failure

    sender = threading.Thread(target=send_all, daemon=True)
    sender.start()
    try:
        while True:
            got = recv_frame_sync(sock)
            if got is None:
                break
            channel, payload = got
            if channel == STDOUT:
                out.append(payload)
            elif channel == STDERR:
                err.append(payload)
            elif channel == ERROR:
                try:
                    code = int(json.loads(payload).get("exitCode", 0))
                except ValueError:
                    code = 1
                break
    finally:
        sock.close()
        sender.join(timeout=5)
    return code, b"".join(out).decode(errors="replace"), \
        b"".join(err).decode(errors="replace")


async def pump_socket_frames(sock: socket.socket, local_reader,
                             local_writer) -> None:
    """Port-forward client half: relay local TCP bytes into STDIN frames
    and STDOUT frames back into the local connection until either side
    closes (the portforward.go copy loops)."""
    loop = asyncio.get_running_loop()

    async def up():
        while True:
            data = await local_reader.read(65536)
            await loop.run_in_executor(None, sock.sendall,
                                       frame(STDIN, data))
            if not data:
                return

    async def down():
        while True:
            got = await loop.run_in_executor(None, recv_frame_sync, sock)
            if got is None:
                break
            channel, payload = got
            if channel == STDOUT:
                if not payload:
                    break
                local_writer.write(payload)
                await local_writer.drain()
            elif channel == ERROR:
                break
        local_writer.close()

    try:
        await asyncio.gather(up(), down())
    except (ConnectionError, OSError):
        pass
