"""Strategic merge patch + JSON merge patch + JSON patch.

The reference's patch machinery (pkg/util/strategicpatch/patch.go applied
by the PATCH verb handler, apiserver/pkg/endpoints/handlers/patch.go:51)
re-derived over plain dicts:

- **strategic merge patch** (application/strategic-merge-patch+json):
  maps merge recursively, `null` deletes a key; lists whose field carries a
  `patchMergeKey` in the API schema merge element-wise by that key (the Go
  types carry this in struct tags; here it is the MERGE_KEYS table);
  `$patch: delete|replace` directives inside maps/list items override.
- **JSON merge patch** (RFC 7386, application/merge-patch+json): like the
  above but every list replaces wholesale.
- **JSON patch** (RFC 6902, application/json-patch+json): an op list
  (add/remove/replace/test) against JSON-pointer paths.

`create_three_way_patch` is the kubectl-apply half
(strategicpatch.CreateThreeWayMergePatch): deletions come from
last-applied-vs-manifest, additions/updates from manifest-vs-live — so
fields a controller wrote (and the manifest never mentioned) survive.
"""

from __future__ import annotations

import copy
from typing import Any

# Content types the PATCH verb negotiates (patch.go:51 patchTypes)
STRATEGIC = "application/strategic-merge-patch+json"
MERGE = "application/merge-patch+json"
JSONPATCH = "application/json-patch+json"

# field name -> merge-key candidates: the patchMergeKey struct tags of the
# v1 types (staging/src/k8s.io/api/core/v1/types.go); lists not named here
# replace. The Go tags are per-type; dict shapes only carry field names, so
# ambiguous fields list candidates in priority order and the key actually
# present in the items wins ("ports" is containerPort on a Container but
# port on a ServiceSpec).
MERGE_KEYS: dict[str, tuple[str, ...] | None] = {
    "containers": ("name",),
    "initContainers": ("name",),
    "ports": ("containerPort", "port"),
    "env": ("name",),
    "volumes": ("name",),
    "volumeMounts": ("mountPath",),
    "tolerations": ("key",),
    "taints": ("key",),
    "conditions": ("type",),
    "imagePullSecrets": ("name",),
    "hostAliases": ("ip",),
    "finalizers": None,  # merge as a set of scalars (patchStrategy: merge)
}

# parallel-list directive prefix for scalar-set deletions
# (strategicpatch's deleteFromPrimitiveList)
DELETE_PRIMITIVE = "$deleteFromPrimitiveList/"


def _resolve_merge_key(field: str, *item_lists) -> str:
    """Pick the merge-key candidate that the actual items carry."""
    candidates = MERGE_KEYS[field]
    for cand in candidates:
        for items in item_lists:
            for item in items:
                if isinstance(item, dict) and cand in item:
                    return cand
    return candidates[0]


class PatchError(ValueError):
    pass


def _merge_keyed_list(current: list, patch: list, merge_key: str,
                      strategic: bool) -> list:
    out: list = [copy.deepcopy(i) for i in current]

    def index_of(key_val):
        for i, item in enumerate(out):
            if isinstance(item, dict) and item.get(merge_key) == key_val:
                return i
        return None

    for p in patch:
        if not isinstance(p, dict):
            raise PatchError(
                f"merge-key list patch item must be an object, got {p!r}")
        directive = p.get("$patch")
        if directive == "replace":
            # {"$patch": "replace"} as a bare item: the REST of the patch
            # list replaces the current list wholesale
            rest = [i for i in patch if i is not p]
            return [copy.deepcopy(i) for i in rest]
        key_val = p.get(merge_key)
        if key_val is None:
            raise PatchError(
                f"list patch item missing merge key {merge_key!r}: {p!r}")
        idx = index_of(key_val)
        if directive == "delete":
            if idx is not None:
                out.pop(idx)
            continue
        if idx is None:
            item = {k: copy.deepcopy(v) for k, v in p.items()
                    if k != "$patch"}
            out.append(item)
        else:
            out[idx] = strategic_merge(out[idx], p)
    return out


def _merge_scalar_set(current: list, patch: list) -> list:
    out = list(current)
    for v in patch:
        if v not in out:
            out.append(v)
    return out


def strategic_merge(current: Any, patch: Any) -> Any:
    """Apply one strategic-merge-patch level. current/patch are the JSON
    dict shapes; returns a new value (inputs unmodified)."""
    if not isinstance(patch, dict) or not isinstance(current, dict):
        return copy.deepcopy(patch)
    if patch.get("$patch") == "replace":
        out = {k: copy.deepcopy(v) for k, v in patch.items()
               if k != "$patch"}
        return out
    out = {k: copy.deepcopy(v) for k, v in current.items()}
    for key, pval in patch.items():
        if key == "$patch":
            continue
        if key.startswith(DELETE_PRIMITIVE):
            # parallel-list deletion for scalar-set lists: remove the named
            # values from the target list (deleteFromPrimitiveList)
            field = key[len(DELETE_PRIMITIVE):]
            cur_list = out.get(field)
            if isinstance(cur_list, list) and isinstance(pval, list):
                remaining = [v for v in cur_list if v not in pval]
                if remaining:
                    out[field] = remaining
                else:
                    out.pop(field, None)
            continue
        if pval is None:
            out.pop(key, None)  # null deletes (patch.go map semantics)
            continue
        cval = out.get(key)
        if isinstance(pval, list) and key in MERGE_KEYS:
            base = cval if isinstance(cval, list) else []
            if MERGE_KEYS[key] is None:
                out[key] = _merge_scalar_set(base, pval)
            else:
                merge_key = _resolve_merge_key(key, base, pval)
                out[key] = _merge_keyed_list(base, pval, merge_key,
                                             strategic=True)
        elif isinstance(pval, dict):
            out[key] = strategic_merge(
                cval if isinstance(cval, dict) else {}, pval)
        else:
            out[key] = copy.deepcopy(pval)
    return out


def json_merge(current: Any, patch: Any) -> Any:
    """RFC 7386 merge patch: like strategic merge but lists replace."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    out = {k: copy.deepcopy(v) for k, v in current.items()} \
        if isinstance(current, dict) else {}
    for key, pval in patch.items():
        if pval is None:
            out.pop(key, None)
        elif isinstance(pval, dict):
            out[key] = json_merge(out.get(key), pval)
        else:
            out[key] = copy.deepcopy(pval)
    return out


def json_patch(current: Any, ops: list) -> Any:
    """RFC 6902: add/remove/replace/test against JSON-pointer paths."""
    doc = copy.deepcopy(current)

    def walk(path: str):
        if not path.startswith("/"):
            raise PatchError(f"bad JSON pointer {path!r}")
        parts = [p.replace("~1", "/").replace("~0", "~")
                 for p in path.split("/")[1:]]
        parent, key = None, None
        node = doc
        for part in parts:
            parent = node
            if isinstance(node, list):
                key = len(node) if part == "-" else int(part)
                node = node[key] if key < len(node) else None
            elif isinstance(node, dict):
                key = part
                node = node.get(part)
            else:
                raise PatchError(f"path {path!r} traverses a scalar")
        return parent, key, node

    for op in ops:
        action = op.get("op")
        try:
            parent, key, node = walk(op.get("path", ""))
            if action == "add":
                if isinstance(parent, list):
                    parent.insert(key, copy.deepcopy(op["value"]))
                else:
                    parent[key] = copy.deepcopy(op["value"])
            elif action == "replace":
                parent[key] = copy.deepcopy(op["value"])
            elif action == "remove":
                if isinstance(parent, list):
                    parent.pop(key)
                else:
                    parent.pop(key, None)
            elif action == "test":
                if node != op.get("value"):
                    raise PatchError(
                        f"test failed at {op.get('path')}: {node!r} != "
                        f"{op.get('value')!r}")
            else:
                raise PatchError(f"unsupported JSON patch op {action!r}")
        except PatchError:
            raise
        except (IndexError, KeyError, TypeError, ValueError) as e:
            # out-of-range index, missing value field, scalar traversal —
            # all client errors, normalized so the server answers 400
            raise PatchError(
                f"bad JSON patch op {op!r}: {type(e).__name__}: {e}") from e
    return doc


def apply_patch(current: dict, patch, content_type: str) -> dict:
    if content_type.startswith(STRATEGIC):
        return strategic_merge(current, patch)
    if content_type.startswith(MERGE):
        return json_merge(current, patch)
    if content_type.startswith(JSONPATCH):
        if not isinstance(patch, list):
            raise PatchError("JSON patch body must be an op list")
        return json_patch(current, patch)
    raise PatchError(f"unsupported patch content type {content_type!r}")


# ---- three-way merge (kubectl apply) ----


def _diff_for_update(modified: Any, live: Any) -> Any:
    """Patch fragment turning `live` into `modified` for every field
    `modified` mentions (fields only in `live` are untouched)."""
    if not isinstance(modified, dict) or not isinstance(live, dict):
        return copy.deepcopy(modified)
    out: dict = {}
    for key, mval in modified.items():
        lval = live.get(key)
        if isinstance(mval, list) and key in MERGE_KEYS \
                and MERGE_KEYS[key] is not None:
            base = lval if isinstance(lval, list) else []
            merge_key = _resolve_merge_key(key, base, mval)
            frag = []
            for item in mval:
                key_val = item.get(merge_key) if isinstance(item, dict) \
                    else None
                match = next((b for b in base
                              if isinstance(b, dict)
                              and b.get(merge_key) == key_val), None)
                if match is None:
                    frag.append(copy.deepcopy(item))
                else:
                    d = _diff_for_update(item, match)
                    if d:
                        d[merge_key] = key_val
                        frag.append(d)
            if frag:
                out[key] = frag
        elif isinstance(mval, dict):
            d = _diff_for_update(mval, lval if isinstance(lval, dict)
                                 else {})
            if d or not isinstance(lval, dict):
                out[key] = d
        elif mval != lval:
            out[key] = copy.deepcopy(mval)
    return out


def _deletions(original: Any, modified: Any) -> Any:
    """Patch fragment deleting what `original` had and `modified` dropped."""
    if not isinstance(original, dict) or not isinstance(modified, dict):
        return {}
    out: dict = {}
    for key, oval in original.items():
        scalar_set = isinstance(oval, list) and key in MERGE_KEYS \
            and MERGE_KEYS[key] is None
        keyed = isinstance(oval, list) and key in MERGE_KEYS \
            and MERGE_KEYS[key] is not None
        mval = modified.get(key) if key in modified else None
        if key not in modified:
            if keyed:
                merge_key = _resolve_merge_key(key, oval)
                out[key] = [{merge_key: i.get(merge_key),
                             "$patch": "delete"}
                            for i in oval if isinstance(i, dict)]
            elif scalar_set:
                # delete only the values apply owned — controller-appended
                # entries (e.g. protection finalizers) must survive
                out[DELETE_PRIMITIVE + key] = list(oval)
            else:
                out[key] = None
            continue
        if isinstance(oval, dict) and isinstance(mval, dict):
            d = _deletions(oval, mval)
            if d:
                out[key] = d
        elif keyed and isinstance(mval, list):
            merge_key = _resolve_merge_key(key, oval, mval)
            have = {i.get(merge_key) for i in mval if isinstance(i, dict)}
            dels = [{merge_key: i.get(merge_key), "$patch": "delete"}
                    for i in oval
                    if isinstance(i, dict) and i.get(merge_key) not in have]
            if dels:
                out[key] = dels
        elif scalar_set and isinstance(mval, list):
            dropped = [v for v in oval if v not in mval]
            if dropped:
                out[DELETE_PRIMITIVE + key] = dropped
    return out


def create_three_way_patch(original: dict, modified: dict,
                           live: dict) -> dict:
    """CreateThreeWayMergePatch: deletions from original->modified merged
    under updates from live->modified — controller-owned fields the
    manifest never mentioned survive the apply."""
    patch = _diff_for_update(modified, live)
    dels = _deletions(original, modified)
    return _overlay(dels, patch)


def _overlay(base: dict, over: dict) -> dict:
    """Deep-merge two patch fragments (over wins; keyed lists concatenate,
    delete directives first so a re-added item lands after its deletion)."""
    out = copy.deepcopy(base)
    for key, oval in over.items():
        bval = out.get(key)
        if isinstance(bval, dict) and isinstance(oval, dict):
            out[key] = _overlay(bval, oval)
        elif isinstance(bval, list) and isinstance(oval, list):
            out[key] = bval + copy.deepcopy(oval)
        else:
            out[key] = copy.deepcopy(oval)
    return out
