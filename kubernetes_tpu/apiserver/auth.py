"""Authentication + authorization for the HTTP apiserver.

The vintage reference's authn/authz surface scoped to its two simplest,
fully-offline modes:

- **Bearer-token authentication** (apiserver/pkg/authentication/token;
  --token-auth-file: csv of token,user,uid,\"group1,group2\"): the
  Authorization header resolves to (user, groups) or 401.
- **ABAC authorization** (pkg/auth/authorizer/abac/abac.go; policy file of
  JSON lines {"user"|"group", "resource", "namespace", "readonly"}): a
  request is allowed when ANY policy line matches; "*" wildcards; readonly
  policies allow only get/list/watch. Deny -> 403.

Both are optional: an APIServer without an authenticator serves
unauthenticated (the in-process/test topology)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class UserInfo:
    name: str
    groups: tuple[str, ...] = ()


class TokenAuthenticator:
    def __init__(self, tokens: dict[str, UserInfo]):
        self.tokens = tokens

    @classmethod
    def from_csv(cls, text: str) -> "TokenAuthenticator":
        """token,user,uid[,\"group1,group2\"] per line (tokenfile.go)."""
        import csv
        import io

        tokens: dict[str, UserInfo] = {}
        for lineno, row in enumerate(csv.reader(io.StringIO(text)), 1):
            if not row or row[0].startswith("#"):
                continue
            if len(row) < 2:
                raise ValueError(
                    f"malformed token file line {lineno}: expected "
                    f"token,user[,uid[,groups]], got {len(row)} field(s)")
            token, user = row[0].strip(), row[1].strip()
            groups = tuple(g.strip() for g in row[3].split(",")) \
                if len(row) > 3 and row[3] else ()
            tokens[token] = UserInfo(name=user, groups=groups)
        return cls(tokens)

    def authenticate(self, headers: dict[str, str]) -> UserInfo | None:
        auth = headers.get("authorization", "")
        if not auth.lower().startswith("bearer "):
            return None
        return self.tokens.get(auth[7:].strip())


READONLY_VERBS = frozenset({"get", "list", "watch"})


@dataclass
class ABACPolicy:
    user: str = ""        # "" never matches; "*" matches everyone
    group: str = ""
    resource: str = "*"
    namespace: str = "*"
    readonly: bool = False

    def matches(self, user: UserInfo, verb: str, resource: str,
                namespace: str) -> bool:
        subject_ok = (self.user == "*" or self.user == user.name
                      or self.group == "*" or self.group in user.groups)
        if not subject_ok:
            return False
        if self.resource not in ("*", resource):
            return False
        # cluster-scoped requests (namespace "") only match wildcard-
        # namespace policies: a policy sandboxing a user to one namespace
        # must never grant Nodes/PVs (abac.go matches namespace exactly)
        if self.namespace not in ("*", namespace) or (
                namespace == "" and self.namespace != "*"):
            return False
        return not self.readonly or verb in READONLY_VERBS


class ABACAuthorizer:
    def __init__(self, policies: list[ABACPolicy]):
        self.policies = policies

    @classmethod
    def from_policy_file(cls, text: str) -> "ABACAuthorizer":
        """One JSON object per line (abac.go policy file format)."""
        policies = []
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            d = json.loads(line)
            spec = d.get("spec", d)  # v1beta1 wraps in spec; v0 is flat
            policies.append(ABACPolicy(
                user=spec.get("user", ""),
                group=spec.get("group", ""),
                resource=spec.get("resource", "*") or "*",
                namespace=spec.get("namespace", "*") or "*",
                readonly=bool(spec.get("readonly", False))))
        return cls(policies)

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: str) -> bool:
        return any(p.matches(user, verb, resource, namespace)
                   for p in self.policies)
