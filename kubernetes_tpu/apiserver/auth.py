"""Authentication + authorization for the HTTP apiserver.

The vintage reference's authn/authz surface scoped to its two simplest,
fully-offline modes:

- **Bearer-token authentication** (apiserver/pkg/authentication/token;
  --token-auth-file: csv of token,user,uid,\"group1,group2\"): the
  Authorization header resolves to (user, groups) or 401.
- **ABAC authorization** (pkg/auth/authorizer/abac/abac.go; policy file of
  JSON lines {"user"|"group", "resource", "namespace", "readonly"}): a
  request is allowed when ANY policy line matches; "*" wildcards; readonly
  policies allow only get/list/watch. Deny -> 403.

Both are optional: an APIServer without an authenticator serves
unauthenticated (the in-process/test topology)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class UserInfo:
    name: str
    groups: tuple[str, ...] = ()


class TokenAuthenticator:
    def __init__(self, tokens: dict[str, UserInfo]):
        self.tokens = tokens

    @classmethod
    def from_csv(cls, text: str) -> "TokenAuthenticator":
        """token,user,uid[,\"group1,group2\"] per line (tokenfile.go)."""
        import csv
        import io

        tokens: dict[str, UserInfo] = {}
        for lineno, row in enumerate(csv.reader(io.StringIO(text)), 1):
            if not row or row[0].startswith("#"):
                continue
            if len(row) < 2:
                raise ValueError(
                    f"malformed token file line {lineno}: expected "
                    f"token,user[,uid[,groups]], got {len(row)} field(s)")
            token, user = row[0].strip(), row[1].strip()
            groups = tuple(g.strip() for g in row[3].split(",")) \
                if len(row) > 3 and row[3] else ()
            tokens[token] = UserInfo(name=user, groups=groups)
        return cls(tokens)

    def authenticate(self, headers: dict[str, str],
                     peercert: dict | None = None) -> UserInfo | None:
        del peercert  # header-only authenticator
        auth = headers.get("authorization", "")
        if not auth.lower().startswith("bearer "):
            return None
        return self.tokens.get(auth[7:].strip())


class X509Authenticator:
    """Client-certificate authentication (reference
    apiserver/pkg/authentication/request/x509/x509.go:149
    CommonNameUserConversion): a TLS peer certificate verified against the
    --client-ca-file resolves to user = Subject.CommonName and
    groups = Subject.Organization entries.

    Verification itself happens in the TLS handshake (the server's
    SSLContext carries the client CA with CERT_OPTIONAL, so a connection
    may also arrive certless and fall through to the next authenticator) —
    by the time `peercert` is non-None here, the chain already validated.
    """

    def authenticate(self, headers: dict[str, str],
                     peercert: dict | None = None) -> UserInfo | None:
        del headers
        if not peercert:
            return None
        name = ""
        groups: list[str] = []
        for rdn in peercert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName":
                    name = value
                elif key == "organizationName":
                    groups.append(value)
        if not name:
            return None
        return UserInfo(name=name, groups=tuple(groups))


class UnionAuthenticator:
    """Request-union authentication (apiserver/pkg/authentication/request/
    union/union.go): first authenticator to resolve a user wins. The
    apiserver composes x509 before bearer tokens, like the reference's
    --client-ca-file + --token-auth-file stack."""

    def __init__(self, *authenticators):
        self.authenticators = [a for a in authenticators if a is not None]

    def authenticate(self, headers: dict[str, str],
                     peercert: dict | None = None) -> UserInfo | None:
        for a in self.authenticators:
            user = a.authenticate(headers, peercert)
            if user is not None:
                return user
        return None


READONLY_VERBS = frozenset({"get", "list", "watch"})


@dataclass
class ABACPolicy:
    user: str = ""        # "" never matches; "*" matches everyone
    group: str = ""
    resource: str = "*"
    namespace: str = "*"
    readonly: bool = False

    def matches(self, user: UserInfo, verb: str, resource: str,
                namespace: str) -> bool:
        subject_ok = (self.user == "*" or self.user == user.name
                      or self.group == "*" or self.group in user.groups)
        if not subject_ok:
            return False
        if self.resource not in ("*", resource):
            return False
        # cluster-scoped requests (namespace "") only match wildcard-
        # namespace policies: a policy sandboxing a user to one namespace
        # must never grant Nodes/PVs (abac.go matches namespace exactly)
        if self.namespace not in ("*", namespace) or (
                namespace == "" and self.namespace != "*"):
            return False
        return not self.readonly or verb in READONLY_VERBS


class ABACAuthorizer:
    def __init__(self, policies: list[ABACPolicy]):
        self.policies = policies

    @classmethod
    def from_policy_file(cls, text: str) -> "ABACAuthorizer":
        """One JSON object per line (abac.go policy file format)."""
        policies = []
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            d = json.loads(line)
            spec = d.get("spec", d)  # v1beta1 wraps in spec; v0 is flat
            policies.append(ABACPolicy(
                user=spec.get("user", ""),
                group=spec.get("group", ""),
                resource=spec.get("resource", "*") or "*",
                namespace=spec.get("namespace", "*") or "*",
                readonly=bool(spec.get("readonly", False))))
        return cls(policies)

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: str, name: str = "") -> bool:
        del name  # ABAC has no per-object-name scoping (abac.go)
        return any(p.matches(user, verb, resource, namespace)
                   for p in self.policies)


# ---- RBAC (plugin/pkg/auth/authorizer/rbac/rbac.go:43) ----


def _rule_allows(rule: dict, verb: str, resource: str,
                 name: str = "") -> bool:
    """PolicyRule match (rbac.go RuleAllows / VerbMatches etc.):
    '*' wildcards; apiGroups are accepted wholesale (single-group wire).
    A rule carrying resourceNames matches only named requests whose name
    is listed (so list/create, which have no name, never match it —
    rbac.go ResourceNameMatches)."""
    verbs = rule.get("verbs") or []
    if "*" not in verbs and verb not in verbs:
        return False
    resources = rule.get("resources") or []
    if "*" not in resources and resource not in resources:
        return False
    names = rule.get("resourceNames") or []
    return not names or (bool(name) and name in names)


def _subject_matches(subject: dict, user) -> bool:
    kind = subject.get("kind", "")
    name = subject.get("name", "")
    if kind == "User":
        return name == user.name or name == "*"
    if kind == "Group":
        return name in user.groups or name == "*"
    if kind == "ServiceAccount":
        ns = subject.get("namespace", "default")
        return user.name == f"system:serviceaccount:{ns}:{name}"
    return False


class RBACAuthorizer:
    """Role/ClusterRole rule matching over the live store
    (rbac.go:43 RBACAuthorizer.Authorize: walk the bindings whose subjects
    cover the user, collect their roles' rules, allow on any match).

    ClusterRoleBindings grant in every namespace and at cluster scope;
    RoleBindings grant only inside their own namespace and may reference
    either a Role (same namespace) or a ClusterRole (rule reuse)."""

    def __init__(self, store):
        self.store = store

    def _rules_for_ref(self, role_ref: dict, namespace: str | None):
        kind = role_ref.get("kind", "")
        name = role_ref.get("name", "")
        try:
            if kind == "ClusterRole":
                return self.store.get("ClusterRole", name, "default").rules
            if kind == "Role" and namespace is not None:
                return self.store.get("Role", name, namespace).rules
        except KeyError:
            return []
        return []

    def authorize(self, user, verb: str, resource: str,
                  namespace: str, name: str = "") -> bool:
        for crb in self.store.list("ClusterRoleBinding",
                                   copy_objects=False):
            if any(_subject_matches(s, user) for s in crb.subjects):
                rules = self._rules_for_ref(crb.role_ref, None)
                if any(_rule_allows(r, verb, resource, name)
                       for r in rules):
                    return True
        if namespace:
            for rb in self.store.list("RoleBinding", namespace,
                                      copy_objects=False):
                if any(_subject_matches(s, user) for s in rb.subjects):
                    rules = self._rules_for_ref(rb.role_ref, namespace)
                    if any(_rule_allows(r, verb, resource, name)
                           for r in rules):
                        return True
        return False


class UnionAuthorizer:
    """--authorization-mode=Node,ABAC,RBAC chaining: allow when ANY mode
    allows (apiserver/pkg/authorization/union)."""

    def __init__(self, *authorizers):
        self.authorizers = [a for a in authorizers if a is not None]

    def authorize(self, user, verb: str, resource: str,
                  namespace: str, name: str = "") -> bool:
        return any(a.authorize(user, verb, resource, namespace, name)
                   for a in self.authorizers)


# ---- Node authorizer (plugin/pkg/auth/authorizer/node/node_authorizer.go) ----

NODES_GROUP = "system:nodes"
NODE_USER_PREFIX = "system:node:"

# read surface every kubelet needs (node_authorizer.go:70-86 delegates these
# to the system:node cluster role's read rules)
_NODE_READ_RESOURCES = frozenset({
    "nodes", "pods", "services", "endpoints", "persistentvolumes",
    "persistentvolumeclaims",
})
# pod-referenced object kinds whose reads are scoped through the node's
# bound pods (the reference's graph edges, node_authorizer.go:112-160)
_POD_SCOPED_RESOURCES = frozenset({"secrets", "configmaps"})


class NodeAuthorizer:
    """Scope node identities to their own objects (the reference builds a
    live graph, plugin/pkg/auth/authorizer/node/graph.go; at this store's
    scale the same edges are answered by direct lookups):

    - only handles users named system:node:<name> in group system:nodes —
      anyone else defers to the next authorizer in the union;
    - cluster-wide reads of the kubelet's informer surface
      (nodes/pods/services/endpoints/PVs/PVCs);
    - secrets/configmaps readable only when a pod BOUND TO THIS NODE
      references them (graph.go edge semantics);
    - node writes only on its own Node object (status updates/heartbeats);
    - pod writes (status update, delete, binding-free create for mirror
      pods) only for pods bound to this node;
    - event creation and CSR creation (certificate rotation) allowed.

    Body-level scoping (a node minting a pod that *references* someone
    else's secret to walk through the pod-scoped read edge) is the
    NodeRestriction admission plugin's job (admission.NodeRestriction) —
    the authorizer only ever sees (verb, resource, name).
    """

    def __init__(self, store):
        self.store = store

    @staticmethod
    def _node_name(user) -> str | None:
        if NODES_GROUP not in user.groups:
            return None
        if not user.name.startswith(NODE_USER_PREFIX):
            return None
        return user.name[len(NODE_USER_PREFIX):]

    def _pod_on_node(self, node: str, namespace: str, name: str) -> bool:
        try:
            pod = self.store.get("Pod", name, namespace or "default")
        except KeyError:
            return False
        return pod.spec.node_name == node

    def _references_from_node_pods(self, node: str, resource: str,
                                   namespace: str, name: str) -> bool:
        for pod in self.store.list("Pod", namespace or "default",
                                   copy_objects=False):
            if pod.spec.node_name != node:
                continue
            for vol in pod.spec.volumes:
                src = vol.get("secret") if resource == "secrets" \
                    else vol.get("configMap")
                if src and src.get("secretName", src.get("name")) == name:
                    return True
        return False

    def authorize(self, user, verb: str, resource: str,
                  namespace: str, name: str = "") -> bool:
        node = self._node_name(user)
        if node is None:
            return False  # not a node identity: defer to the union
        if resource in _NODE_READ_RESOURCES and verb in READONLY_VERBS:
            return True
        if resource in _POD_SCOPED_RESOURCES and verb == "get":
            return self._references_from_node_pods(
                node, resource, namespace, name)
        if resource == "nodes":
            # heartbeats + status: only the node's own object
            return verb in ("create", "update", "patch") and (
                not name or name == node)
        if resource == "pods":
            if verb == "create":
                return True  # mirror pods (binding happens server-side)
            if verb in ("update", "patch", "delete"):
                return bool(name) and self._pod_on_node(
                    node, namespace, name)
            return False
        if resource == "events":
            return verb in ("create", "update", "patch")
        if resource == "certificatesigningrequests":
            return verb in ("create", "get", "list", "watch")
        return False


# ---- webhook authorizer (plugin/pkg/auth/authorizer/webhook) -------------


class WebhookAuthorizer:
    """SubjectAccessReview over HTTP (plugin/pkg/auth/authorizer/webhook/
    webhook.go:153): POST a SAR for each decision, read status.allowed.
    Allowed decisions cache for `authorized_ttl` seconds (webhook.go's
    --authorization-webhook-cache-authorized-ttl); denials cache for the
    much shorter `unauthorized_ttl` (the reference's
    --authorization-webhook-cache-unauthorized-ttl, default 30s vs our 10s)
    so a retry storm from a denied client doesn't hammer the webhook while
    a new grant still takes effect quickly. An unreachable webhook denies
    (fail closed, like the reference's error path) without caching — an
    outage must not pin denials past its own end."""

    def __init__(self, url: str, authorized_ttl: float = 60.0,
                 timeout: float = 2.0, unauthorized_ttl: float = 10.0):
        self.url = url
        self.authorized_ttl = authorized_ttl
        self.unauthorized_ttl = unauthorized_ttl
        self.timeout = timeout
        self._cache: dict[tuple, float] = {}
        self._denied: dict[tuple, float] = {}

    def authorize(self, user, verb: str, resource: str,
                  namespace: str, name: str = "") -> bool:
        import json as _json
        import time
        import urllib.error
        import urllib.request

        key = (user.name, user.groups, verb, resource, namespace, name)
        expires = self._cache.get(key)
        if expires is not None and expires > time.monotonic():
            return True
        expires = self._denied.get(key)
        if expires is not None and expires > time.monotonic():
            return False
        review = {
            "kind": "SubjectAccessReview",
            "spec": {
                "user": user.name,
                "groups": list(user.groups),
                "resourceAttributes": {
                    "verb": verb, "resource": resource,
                    "namespace": namespace, "name": name,
                },
            },
        }
        try:
            req = urllib.request.Request(
                self.url, data=_json.dumps(review).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                answer = _json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError, TimeoutError):
            return False  # fail closed
        allowed = bool((answer.get("status") or {}).get("allowed", False))
        if allowed:
            self._cache[key] = time.monotonic() + self.authorized_ttl
            if len(self._cache) > 4096:
                now = time.monotonic()
                self._cache = {k: v for k, v in self._cache.items()
                               if v > now}
        else:
            self._denied[key] = time.monotonic() + self.unauthorized_ttl
            if len(self._denied) > 4096:
                now = time.monotonic()
                self._denied = {k: v for k, v in self._denied.items()
                                if v > now}
        return allowed


# ---- impersonation (apiserver/pkg/endpoints/filters/impersonation.go:39) --


def impersonate(authorizer, user: UserInfo,
                headers: dict[str, str]) -> tuple[UserInfo | None, bool]:
    """Apply Impersonate-User / Impersonate-Group headers.

    Returns (effective_user, ok). The requester must be authorized for the
    `impersonate` verb on `users` (and on `groups` for each requested
    group) — filters/impersonation.go:66-102; on any failure the request
    is forbidden rather than served as the original user (the reference
    401/403s instead of silently dropping the headers)."""
    target = headers.get("impersonate-user", "")
    if not target:
        return user, True
    if authorizer is None or not authorizer.authorize(
            user, "impersonate", "users", "", target):
        return None, False
    groups = tuple(v.strip() for k, v in headers.items()
                   if k == "impersonate-group" for v in v.split(",")
                   if v.strip())
    for g in groups:
        if not authorizer.authorize(user, "impersonate", "groups", "", g):
            return None, False
    # every impersonated identity is an authenticated one — the reference
    # unconditionally appends system:authenticated (impersonation.go:124)
    # so RBAC rules bound to that group keep applying to the new identity
    if "system:authenticated" not in groups:
        groups = groups + ("system:authenticated",)
    return UserInfo(name=target, groups=groups), True
