"""API Priority & Fairness: per-flow fair queues replacing flat max-in-flight.

The server-side analog of the reference's APF feature (staging/src/k8s.io/
apiserver/pkg/util/flowcontrol): requests are classified by FlowSchema
(match on user/group/verb/resource, ordered by matchingPrecedence) onto a
PriorityLevel, each level owning a slice of the server's total concurrency
plus a set of bounded fair queues. A flow (schema + user distinguisher)
shuffle-shards onto a small hand of queues and enqueues on the shortest, so
one noisy tenant saturates its own queues while other flows — above all the
scheduler/kubelet `system` level — keep their assured seats. Surplus load
gets an honest 429 with a Retry-After hint instead of unbounded queueing
(the flat WithMaxInFlightLimit behavior this replaces).

Built-in config (overridable by FlowSchema / PriorityLevelConfiguration
objects in the store, reloaded on a short TTL):

    system    — `system:kube-*` users and the `system:nodes`/`system:masters`
                groups (scheduler, kubelets, controller manager); most shares
    workload  — every other authenticated user
    catch-all — everything else (including anonymous); fewest shares

Single-event-loop discipline: all state is touched only from the serving
loop, so there are no locks; the latency sample deques are read cross-thread
by the overload drill (append/iterate are atomic under the GIL).
"""

from __future__ import annotations

import asyncio
import fnmatch
import time
import zlib
from collections import deque
from typing import Any

_ANONYMOUS = "system:anonymous"

# built-in priority levels: name -> (shares, queues, queue_length, hand_size)
DEFAULT_PRIORITY_LEVELS: dict[str, dict] = {
    "system": {"shares": 30, "queues": 8, "queueLengthLimit": 128,
               "handSize": 4},
    "workload": {"shares": 20, "queues": 16, "queueLengthLimit": 64,
                 "handSize": 4},
    "catch-all": {"shares": 5, "queues": 4, "queueLengthLimit": 16,
                  "handSize": 2},
}

# built-in flow schemas, ordered by matchingPrecedence (lower wins). A rule
# matches when every present constraint matches; "*" in `users` means any
# AUTHENTICATED user (never system:anonymous — the reference's catch-all
# subject split between system:authenticated and system:unauthenticated).
DEFAULT_FLOW_SCHEMAS: list[dict] = [
    {"name": "system", "priorityLevel": "system",
     "matchingPrecedence": 100,
     "rules": [{"users": ["system:kube-*", "system:apiserver",
                          "system:kubelet*"]},
               {"groups": ["system:nodes", "system:masters"]}]},
    {"name": "workload", "priorityLevel": "workload",
     "matchingPrecedence": 9000,
     "rules": [{"users": ["*"]}]},
    {"name": "catch-all", "priorityLevel": "catch-all",
     "matchingPrecedence": 10000,
     "rules": [{}]},
]

_mx: tuple | None = None


def _flow_metrics() -> tuple:
    """(dispatched, rejected, queued) counters labeled by flow schema —
    the apiserver_flowcontrol_* families (apf metrics.go), registered on
    first use."""
    global _mx
    if _mx is None:
        from kubernetes_tpu.obs import metrics as m

        _mx = (
            m.REGISTRY.counter(
                "apiserver_flowcontrol_dispatched_total",
                "Requests that got a seat, by flow schema.", ("flow",)),
            m.REGISTRY.counter(
                "apiserver_flowcontrol_rejected_total",
                "Requests shed with 429, by flow schema.", ("flow",)),
            m.REGISTRY.counter(
                "apiserver_flowcontrol_queued_total",
                "Requests that waited in a fair queue, by flow schema.",
                ("flow",)),
        )
    return _mx


class FlowRejected(Exception):
    """Request shed by flow control — HTTP 429 + Retry-After."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = retry_after


class _Level:
    """One priority level: a concurrency slice + shuffle-sharded queues.

    Mutated in place on config reload so seats held across a reload still
    release against the same counters."""

    __slots__ = ("name", "shares", "limit", "queues", "queue_length",
                 "hand_size", "in_flight", "_next_q")

    def __init__(self, name: str, spec: dict):
        self.name = name
        self.in_flight = 0
        self._next_q = 0
        self.queues: list[deque] = []
        self.limit = 0
        self.configure(spec)

    def configure(self, spec: dict) -> None:
        self.shares = max(1, int(spec.get("shares", 1)))
        n_queues = max(1, int(spec.get("queues", 4)))
        self.queue_length = max(1, int(spec.get("queueLengthLimit", 16)))
        self.hand_size = max(1, min(int(spec.get("handSize", 2)), n_queues))
        # grow-only so waiters parked in existing queues survive a reload
        while len(self.queues) < n_queues:
            self.queues.append(deque())

    def queued(self) -> int:
        return sum(len(q) for q in self.queues)


class _Seat:
    """One admitted request. Held until release(); carries the flow schema
    name for metrics/latency attribution and the seat width the request
    was charged (the work estimator's LIST cost)."""

    __slots__ = ("level", "flow", "width")

    def __init__(self, level: _Level, flow: str, width: int = 1):
        self.level = level
        self.flow = flow
        self.width = width


class _Schema:
    __slots__ = ("name", "level", "precedence", "rules")

    def __init__(self, name: str, level: str, precedence: int, rules: list):
        self.name = name
        self.level = level
        self.precedence = precedence
        self.rules = rules or [{}]

    def matches(self, user_name: str, groups: tuple, verb: str,
                resource: str) -> bool:
        for rule in self.rules:
            if self._rule_matches(rule, user_name, groups, verb, resource):
                return True
        return False

    @staticmethod
    def _rule_matches(rule: dict, user_name: str, groups: tuple, verb: str,
                      resource: str) -> bool:
        users = rule.get("users")
        if users:
            for pat in users:
                if pat == "*":
                    if user_name != _ANONYMOUS:
                        break
                elif fnmatch.fnmatchcase(user_name, pat):
                    break
            else:
                return False
        want_groups = rule.get("groups")
        if want_groups and not set(want_groups) & set(groups):
            return False
        verbs = rule.get("verbs")
        if verbs and "*" not in verbs and verb not in verbs:
            return False
        resources = rule.get("resources")
        if resources and "*" not in resources \
                and resource not in resources:
            return False
        return True


class FlowController:
    """Seats + fair queues over one total concurrency budget.

    `total_concurrency` keeps the old max_in_flight meaning: the sum of
    seats across levels (0 = shed everything, preserving the flat gate's
    test contract). `store` (optional) supplies FlowSchema /
    PriorityLevelConfiguration overrides, reloaded at most every
    `refresh_s` seconds."""

    def __init__(self, total_concurrency: int = 400, store: Any = None,
                 queue_wait_s: float = 2.0, refresh_s: float = 1.0):
        self.total = total_concurrency
        self.store = store
        self.queue_wait_s = queue_wait_s
        self.refresh_s = refresh_s
        self._last_refresh = 0.0
        self.levels: dict[str, _Level] = {}
        self.schemas: list[_Schema] = []
        # plain mirrors of the labeled counters, readable cross-thread by
        # the overload drill without scraping the registry
        self.dispatched: dict[str, int] = {}
        self.rejected: dict[str, int] = {}
        self.queued: dict[str, int] = {}
        # per-schema seat-to-release latency samples (seconds)
        self.latency_samples: dict[str, deque] = {}
        self._apply_config(DEFAULT_PRIORITY_LEVELS, DEFAULT_FLOW_SCHEMAS)

    # ---- configuration ----

    def _apply_config(self, levels: dict[str, dict],
                      schemas: list[dict]) -> None:
        for name, spec in levels.items():
            lvl = self.levels.get(name)
            if lvl is None:
                self.levels[name] = _Level(name, spec)
            else:
                lvl.configure(spec)
        total_shares = sum(lv.shares for lv in self.levels.values()) or 1
        for lv in self.levels.values():
            lv.limit = 0 if self.total <= 0 else max(
                1, self.total * lv.shares // total_shares)
        parsed = []
        for s in schemas:
            level = s.get("priorityLevel") or "catch-all"
            if level not in self.levels:
                level = "catch-all"
            parsed.append(_Schema(
                s.get("name") or level, level,
                int(s.get("matchingPrecedence", 1000)),
                s.get("rules") or [{}]))
        parsed.sort(key=lambda s: (s.precedence, s.name))
        self.schemas = parsed

    def configure(self, levels: dict[str, dict] | None = None,
                  schemas: list[dict] | None = None) -> None:
        """Public configuration hook for embedders: layer extra priority
        levels / flow schemas over the built-ins (wins by name, same merge
        the store-driven refresh applies). The solversvc front end uses
        this to install a dedicated `solversvc` level so tenant solve
        traffic gets its own seat budget and shuffle-sharded queues
        instead of competing inside `workload`."""
        base_levels = {name: dict(spec)
                       for name, spec in DEFAULT_PRIORITY_LEVELS.items()}
        base_levels.update(levels or {})
        merged = {s["name"]: dict(s) for s in DEFAULT_FLOW_SCHEMAS}
        for s in schemas or []:
            merged[s["name"]] = dict(s)
        self._apply_config(base_levels, list(merged.values()))

    def _maybe_refresh(self) -> None:
        """Layer store-defined FlowSchema / PriorityLevelConfiguration
        objects over the built-ins (objects win by name; unknown levels on
        a schema fall back to catch-all)."""
        if self.store is None:
            return
        now = time.monotonic()
        if now - self._last_refresh < self.refresh_s:
            return
        self._last_refresh = now
        try:
            plcs = self.store.list("PriorityLevelConfiguration",
                                   copy_objects=False)
            fss = self.store.list("FlowSchema", copy_objects=False)
        except Exception:  # noqa: BLE001 — config reload is best-effort;
            # a throttled/faulted store must not take admission down with it
            return
        levels = {name: dict(spec)
                  for name, spec in DEFAULT_PRIORITY_LEVELS.items()}
        for plc in plcs:
            levels[plc.metadata.name] = dict(plc.spec)
        schemas = {s["name"]: dict(s) for s in DEFAULT_FLOW_SCHEMAS}
        for fs in fss:
            schemas[fs.metadata.name] = {"name": fs.metadata.name,
                                         **fs.spec}
        self._apply_config(levels, list(schemas.values()))

    # ---- classification ----

    def classify(self, user: Any, verb: str,
                 resource: str) -> tuple[_Schema, str]:
        """-> (schema, flow key). The distinguisher is the user name (the
        reference's ByUser flow distinguisher), so each tenant is its own
        flow inside the level."""
        self._maybe_refresh()
        name = getattr(user, "name", None) or _ANONYMOUS
        groups = tuple(getattr(user, "groups", ()) or ())
        if name == _ANONYMOUS:
            groups = groups + ("system:unauthenticated",)
        for schema in self.schemas:
            if schema.matches(name, groups, verb, resource):
                return schema, f"{schema.name}/{name}"
        return self.schemas[-1], f"{self.schemas[-1].name}/{name}"

    # ---- seats ----

    def _shuffle_shard(self, level: _Level, flow: str) -> deque:
        """Hash the flow key over `hand_size` candidate queues and take the
        shortest — two flows rarely share a whole hand, so a saturated flow
        cannot blanket every queue (shuffle sharding, apf queueset)."""
        best = None
        n = len(level.queues)
        for i in range(level.hand_size):
            idx = zlib.crc32(f"{flow}/{i}".encode()) % n
            q = level.queues[idx]
            if best is None or len(q) < len(best):
                best = q
        return best

    def _retry_after(self, level: _Level) -> float:
        """Honest hint: roughly how long until this level's backlog drains
        at its seat budget (floored at 1s, the reference's constant)."""
        if level.limit <= 0:
            return 1.0
        return max(1.0, round(level.queued() / level.limit, 1))

    async def acquire(self, user: Any, verb: str, resource: str,
                      width: int = 1) -> _Seat:
        """Admit or queue one request; raises FlowRejected (429) when the
        level is saturated and its fair queue is full, when the controller
        has no concurrency at all, or when the queue wait times out.

        `width` is the work estimate in seats (the reference's APF work
        estimator): an expensive collection LIST occupies several seats so
        a handful of big lists cannot monopolize the level the way a
        handful of cheap GETs never could. Clamped to the level's limit so
        an over-wide request can still be admitted on an idle level."""
        schema, flow = self.classify(user, verb, resource)
        level = self.levels[schema.level]
        mx = _flow_metrics()
        if level.limit <= 0:
            self._count(self.rejected, schema.name)
            mx[1].labels(schema.name).inc()
            raise FlowRejected(
                f"too many requests: priority level {level.name!r} has no "
                f"concurrency", retry_after=self._retry_after(level))
        width = max(1, min(int(width), level.limit))
        # fast path only when nobody is queued: with widths, spare seats can
        # coexist with a parked wide waiter, and a fresh narrow request must
        # not sneak past it
        if level.in_flight + width <= level.limit and level.queued() == 0:
            level.in_flight += width
            self._count(self.dispatched, schema.name)
            mx[0].labels(schema.name).inc()
            return _Seat(level, schema.name, width)
        queue = self._shuffle_shard(level, flow)
        if len(queue) >= level.queue_length:
            self._count(self.rejected, schema.name)
            mx[1].labels(schema.name).inc()
            raise FlowRejected(
                f"too many requests: flow {flow!r} queue is full "
                f"({level.queue_length} waiting)",
                retry_after=self._retry_after(level))
        fut = asyncio.get_running_loop().create_future()
        entry = (fut, width)
        queue.append(entry)
        self._count(self.queued, schema.name)
        mx[2].labels(schema.name).inc()
        try:
            await asyncio.wait_for(fut, self.queue_wait_s)
        except asyncio.TimeoutError:
            try:
                queue.remove(entry)
            except ValueError:
                pass
            self._count(self.rejected, schema.name)
            mx[1].labels(schema.name).inc()
            raise FlowRejected(
                f"too many requests: flow {flow!r} timed out after "
                f"{self.queue_wait_s:.0f}s in queue",
                retry_after=self._retry_after(level)) from None
        # _dispatch_waiters already charged our width against in_flight
        self._count(self.dispatched, schema.name)
        mx[0].labels(schema.name).inc()
        return _Seat(level, schema.name, width)

    def release(self, seat: _Seat | None) -> None:
        """Return the seat's width to the level, then hand the freed
        capacity to queued waiters (round-robin across non-empty queues, so
        no flow's queue starves)."""
        if seat is None:
            return
        seat.level.in_flight -= seat.width
        self._dispatch_waiters(seat.level)

    @staticmethod
    def _dispatch_waiters(level: _Level) -> None:
        """Wake queued waiters while their widths fit in the freed
        capacity. One waiter per queue per pass (round-robin); within a
        queue strict FIFO, so a narrow request never sneaks past a wide
        one parked ahead of it in the same queue."""
        n = len(level.queues)
        while True:
            dispatched = False
            for off in range(n):
                qi = (level._next_q + off) % n
                q = level.queues[qi]
                while q and q[0][0].cancelled():
                    q.popleft()  # timed-out waiter already gave up
                if not q:
                    continue
                fut, width = q[0]
                if level.in_flight + width > level.limit:
                    continue  # this queue's head doesn't fit; try others
                q.popleft()
                level.in_flight += width
                level._next_q = (qi + 1) % n
                fut.set_result(True)
                dispatched = True
                break  # restart the scan from the new round-robin cursor
            if not dispatched:
                return

    def note_latency(self, seat: _Seat | None, seconds: float) -> None:
        if seat is None:
            return
        samples = self.latency_samples.get(seat.flow)
        if samples is None:
            samples = self.latency_samples.setdefault(
                seat.flow, deque(maxlen=8192))
        samples.append(seconds)

    @staticmethod
    def _count(counter: dict, flow: str) -> None:
        counter[flow] = counter.get(flow, 0) + 1

    def p99_ms(self, flow: str) -> float:
        """p99 of the recorded seat latencies for one flow schema, in ms
        (0.0 with no samples) — the overload drill's bounded-latency
        figure, readable cross-thread."""
        samples = sorted(self.latency_samples.get(flow, ()))
        if not samples:
            return 0.0
        return 1e3 * samples[min(len(samples) - 1,
                                 int(0.99 * (len(samples) - 1)))]


def solve_seats(n_pods: int) -> int:
    """APF work estimate for one solve request: device time is roughly
    linear in the pod count, so charge one seat per started 16 pods (the
    reference's LIST work estimator shape applied to solver work). A
    single-pod extender verb is 1 seat; a 64-pod native batch is 4 —
    a tenant shipping huge batches drains its seat budget proportionally."""
    return 1 + max(0, int(n_pods) - 1) // 16
