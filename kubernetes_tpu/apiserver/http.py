"""HTTP front-end for the ObjectStore: the generic-apiserver REST surface.

Serves the reference's resource route shapes (registerResourceHandlers,
staging/src/k8s.io/apiserver/pkg/endpoints/installer.go:195) over the
in-memory store:

    GET    /api/v1/{plural}                          cluster-wide list
    GET    /api/v1/{plural}?watch=1&resourceVersion=N  chunked watch stream
    GET    /api/v1/namespaces/{ns}/{plural}          namespaced list
    GET    /api/v1/namespaces/{ns}/{plural}/{name}   get
    POST   /api/v1/namespaces/{ns}/{plural}          create
    PUT    /api/v1/namespaces/{ns}/{plural}/{name}   update (CAS on
                                                     resourceVersion)
    DELETE /api/v1/namespaces/{ns}/{plural}/{name}   delete
    POST   /api/v1/namespaces/{ns}/pods/{name}/binding  the pods/binding
           subresource (pkg/registry/core/pod/rest)

`/apis/{group}/{version}/...` routes alias the same resources (the vintage
tree serves workloads under extensions/v1beta1 and apps/v1beta1).

Watch semantics match the reference's chunked-frame protocol
(endpoints/handlers/watch.go): each event is one JSON line
`{"type": "ADDED", "object": {...}}`; a resume point older than the ring
buffer answers **410 Gone**, telling the client to relist — exactly the
Reflector contract the in-process store enforces with `Expired`.

`RemoteStore` is the client half: an ObjectStore-compatible facade whose
CRUD speaks blocking HTTP (small JSON bodies on a local/trusted network —
the reference's client-go default QPS model) and whose `watch()` returns an
async stream, so `Informer`, `Scheduler`, controllers, and the extender run
over TCP unchanged.
"""

from __future__ import annotations

import asyncio
import json
import logging
import select
import socket
import threading
import time
from typing import Any
from urllib.parse import parse_qs, urlsplit

from kubernetes_tpu.api import objects as objs
from kubernetes_tpu.api import wire
from kubernetes_tpu.api.objects import Binding
from kubernetes_tpu.obs import metrics as obs_metrics
from kubernetes_tpu.obs import tracing as _tracing
from kubernetes_tpu.obs.http import http_head, obs_response
from kubernetes_tpu.apiserver.admission import AdmissionError
from kubernetes_tpu.apiserver.flowcontrol import FlowRejected
from kubernetes_tpu.apiserver.validation import ValidationError
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    Expired,
    FencedWrite,
    NotFound,
    ObjectStore,
    TooManyRequests,
    WatchEvent,
)

log = logging.getLogger(__name__)

# plural REST resource <-> kind (discovery surface of the vintage tree)
RESOURCES: dict[str, str] = {
    "pods": "Pod",
    "nodes": "Node",
    "services": "Service",
    "endpoints": "Endpoints",
    "events": "Event",
    "bindings": "Binding",
    "persistentvolumes": "PersistentVolume",
    "persistentvolumeclaims": "PersistentVolumeClaim",
    "replicationcontrollers": "ReplicationController",
    "replicasets": "ReplicaSet",
    "statefulsets": "StatefulSet",
    "deployments": "Deployment",
    "jobs": "Job",
    "limitranges": "LimitRange",
    "resourcequotas": "ResourceQuota",
    "namespaces": "Namespace",
    "customresourcedefinitions": "CustomResourceDefinition",
    "clusters": "Cluster",
    "secrets": "Secret",
    "configmaps": "ConfigMap",
    "serviceaccounts": "ServiceAccount",
    "daemonsets": "DaemonSet",
    "cronjobs": "CronJob",
    "horizontalpodautoscalers": "HorizontalPodAutoscaler",
    "poddisruptionbudgets": "PodDisruptionBudget",
    "apiservices": "APIService",
    # scheduling.ktpu.io (gang scheduling)
    "podgroups": "PodGroup",
    # autoscaling.ktpu.io (cluster autoscaler node pools)
    "nodegroups": "NodeGroup",
    # descheduling.ktpu.io (gang defragmentation)
    "deschedulepolicies": "DeschedulePolicy",
    # scheduling.k8s.io (pod priority & preemption)
    "priorityclasses": "PriorityClass",
    # flowcontrol.ktpu.io (API priority & fairness)
    "flowschemas": "FlowSchema",
    "prioritylevelconfigurations": "PriorityLevelConfiguration",
    # monitoring.ktpu.io (the Monitor's recording/alerting rules)
    "alertrules": "AlertRule",
    "roles": "Role",
    "clusterroles": "ClusterRole",
    "rolebindings": "RoleBinding",
    "clusterrolebindings": "ClusterRoleBinding",
    "certificatesigningrequests": "CertificateSigningRequest",
    # admissionregistration.k8s.io (served as a GenericObject; consumed by
    # the GenericAdmissionWebhook plugin)
    "externaladmissionhookconfigurations":
        "ExternalAdmissionHookConfiguration",
    # storage.k8s.io (served as a GenericObject; consumed by the PV
    # binder's dynamic-provisioning path)
    "storageclasses": "StorageClass",
}
KIND_TO_CLS = {cls.kind: cls for cls in (
    objs.Pod, objs.Node, objs.Service, objs.Endpoints, objs.Event,
    objs.PersistentVolume, objs.PersistentVolumeClaim,
    objs.ReplicationController, objs.ReplicaSet, objs.StatefulSet,
    objs.Deployment, objs.Job, objs.LimitRange, objs.ResourceQuota,
    objs.Namespace, objs.CustomResourceDefinition, objs.Cluster,
    objs.Secret, objs.ConfigMap, objs.ServiceAccount, objs.DaemonSet,
    objs.CronJob, objs.HorizontalPodAutoscaler, objs.PodDisruptionBudget,
    objs.APIService, objs.PodGroup, objs.NodeGroup, objs.DeschedulePolicy,
    objs.PriorityClass,
    objs.FlowSchema, objs.PriorityLevelConfiguration, objs.AlertRule,
    objs.Role, objs.ClusterRole,
    objs.RoleBinding, objs.ClusterRoleBinding,
    objs.CertificateSigningRequest)}
PLURAL_OF = {kind: plural for plural, kind in RESOURCES.items()}

_req_mx: tuple | None = None


def _request_metrics() -> tuple:
    """(request_count, request_latencies, inflight) — the reference's
    apiserver metrics families (endpoints/metrics/metrics.go), registered
    on first request."""
    global _req_mx
    if _req_mx is None:
        m = obs_metrics
        _req_mx = (
            m.REGISTRY.counter(
                "apiserver_request_count",
                "Requests handled, by verb, resource and response code.",
                ("verb", "resource", "code")),
            m.REGISTRY.histogram(
                "apiserver_request_latencies_microseconds",
                "Request handling latency, by verb and resource.",
                ("verb", "resource"),
                buckets=m.exponential_buckets(100.0, 2.0, 16)),
            m.REGISTRY.gauge(
                "apiserver_current_inflight_requests",
                "Requests currently being served (non-long-running)."),
        )
    return _req_mx


def _resource_of(path: str) -> str:
    """The plural resource segment of a request path ("" for discovery
    and other shapeless paths) — the metric label, no kind resolution."""
    try:
        _ns, plural, _name, _sub = _split_path(path)
        return plural
    except NotFound:
        return ""


async def read_http_request(reader: asyncio.StreamReader):
    """Parse one request off a stream -> (method, target, headers, body),
    or None at EOF. The one HTTP/1.1 request parser shared by the
    apiserver and the kubelet API server."""
    request_line = await reader.readline()
    if not request_line:
        return None
    try:
        method, target, _ = request_line.decode().split(None, 2)
    except ValueError:
        raise ValueError("bad request line") from None
    headers: dict[str, str] = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode().partition(":")
        key = name.strip().lower()
        if key in headers:
            # repeated list-valued headers (e.g. kubectl's multiple
            # Impersonate-Group lines) combine per RFC 7230 §3.2.2 —
            # dropping all but the last would silently skip their
            # authorization checks
            headers[key] += ", " + value.strip()
        else:
            headers[key] = value.strip()
    length = int(headers.get("content-length", 0))
    body = await reader.readexactly(length) if length else b""
    return method, target, headers, body


def parse_status_line(head: bytes) -> int:
    """Status code from a response head, or ValueError on non-HTTP."""
    try:
        return int(head.split(None, 2)[1])
    except (IndexError, ValueError):
        raise ValueError("empty or non-HTTP reply") from None


def parse_label_selector(spec: str) -> dict[str, str]:
    """The equality-only labelSelector grammar this API serves
    (`k=v,k2=v2`). Malformed parts raise instead of silently matching
    everything/nothing — shared by the kubectl -l flag and the
    DeleteCollection query parameter."""
    out: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, value = part.partition("=")
        if not eq or not key or "!" in key:
            raise ValueError(
                f"bad label selector {part!r}: only k=v,... equality "
                f"selectors are supported")
        out[key] = value
    return out


def _split_path(path: str):
    """-> (ns | None, plural, name | None, subresource | None) — the raw
    resource shape of a request path, no kind resolution. Authorization
    runs on THIS (so aggregated/unknown resources stay inside ABAC — a
    proxied group must not bypass the authorizer just because the core
    registry can't resolve its plural); routing resolves the kind after.

    `/namespaces/{x}` with nothing after it addresses the Namespace
    RESOURCE itself (cluster-scoped); with a trailing resource segment it
    scopes the request to namespace x (installer.go path shapes)."""
    parts = [p for p in path.strip("/").split("/") if p]
    # strip the version prefix: api/v1 or apis/{group}/{version}
    if parts[:1] == ["api"]:
        parts = parts[2:]
    elif parts[:1] == ["apis"]:
        parts = parts[3:]
    else:
        raise NotFound(f"unknown path {path!r}")
    ns = None
    if parts[:1] == ["namespaces"] and len(parts) >= 3:
        ns = parts[1]
        parts = parts[2:]
    if not parts:
        raise NotFound(f"unknown path {path!r}")
    plural, name, sub = parts[0], None, None
    if len(parts) >= 2:
        name = parts[1]
    if len(parts) >= 3:
        sub = parts[2]
    return ns, plural, name, sub


def decode_object(kind: str, body: dict) -> Any:
    cls = KIND_TO_CLS.get(kind)
    if cls is None:
        # custom resources decode generically (apiextensions serving path)
        obj = objs.GenericObject.from_dict(body)
        obj.kind = kind or obj.kind
        if not obj.kind:
            raise NotFound("object has no kind")
        return obj
    return cls.from_dict(body)


def encode_object(obj: Any) -> dict:
    out = obj.to_dict()
    out.setdefault("kind", obj.kind)
    return out


class _WatchServe:
    """Server-side handle for one live watch connection: drain() uses it
    to tell the serve loop to end with a terminal DRAIN frame, waking a
    stream blocked in next() (cache streams are woken by
    WatchCache.drain_subscribers; raw store streams by detaching the
    watcher entry)."""

    __slots__ = ("_store", "_stream", "draining")

    def __init__(self, store, stream):
        self._store = store
        self._stream = stream
        self.draining = False

    def request_drain(self) -> None:
        self.draining = True
        entry = getattr(self._stream, "_entry", None)
        if entry is not None:
            self._store._detach_watcher(entry)


class _WatchSink:
    """Off-loop delivery target for one sharded watch connection: the
    owning FanoutShard thread writes encoded-once frames straight to the
    connection's socket (non-blocking send + select retry under a
    deadline), so the serving loop never touches watch-stream bytes after
    the response headers. TLS connections — and any transport without a
    raw socket — fall back to loop-marshalled writes through
    `call_soon_threadsafe`, the one sanctioned thread→loop crossing. A
    per-connection lock serializes shard-thread frame writes with the
    serve coroutine's heartbeat and terminal DRAIN frames (which go
    through the same lock via `asyncio.to_thread`)."""

    SEND_TIMEOUT_S = 5.0

    def __init__(self, writer: asyncio.StreamWriter,
                 loop: asyncio.AbstractEventLoop, ns: str | None,
                 binary: bool, last_rv: int):
        self._writer = writer
        self._loop = loop
        self._ns = ns
        self._binary = binary
        self.last_rv = last_rv
        self._lock = threading.Lock()
        self._pending: list[tuple[bytes, int]] = []  # pre-arm buffer
        self._armed = False
        self.ended: str | None = None  # terminal reason, set once
        self.end_event = asyncio.Event()  # loop-side park for the serve
        self.last_write = time.monotonic()
        sock = writer.get_extra_info("socket")
        # asyncio hands out a TransportSocket facade whose send() is
        # deprecated; shard threads need the real socket underneath
        sock = getattr(sock, "_sock", sock)
        if writer.get_extra_info("ssl_object") is not None:
            sock = None
        self._sock = sock

    # ---- shard-thread side (the WatchCache.watch_sink contract) ----

    def __call__(self, frame) -> None:
        from kubernetes_tpu.apiserver.watchcache import SinkClosed

        event = frame.event
        if self._ns and event.obj.metadata.namespace != self._ns:
            return  # namespace filter; last_rv tracks matching events only
        data = frame.wire_bytes() if self._binary else frame.json_bytes()
        with self._lock:
            if self.ended is not None:
                raise SinkClosed("watch connection already ended")
            if not self._armed:
                # headers still in flight on the loop: buffer, arm() flushes
                self._pending.append((data, event.resource_version))
                return
            self._send(data)
            self.last_rv = event.resource_version

    def on_end(self, reason: str) -> None:
        with self._lock:
            if self.ended is None:
                self.ended = reason
        try:
            self._loop.call_soon_threadsafe(self.end_event.set)
        except RuntimeError:
            pass  # loop already closed mid-teardown

    # ---- serve-coroutine side (always via asyncio.to_thread) ----

    def arm(self) -> None:
        """Flush frames buffered while the headers were in flight, then
        switch to direct writes. Runs in a worker thread, off the loop."""
        with self._lock:
            for data, rv in self._pending:
                self._send(data)
                self.last_rv = rv
            self._pending.clear()
            self._armed = True

    def force_loop_writes(self) -> None:
        """Permanently route writes through the loop (transport buffer
        never emptied after the headers — direct socket writes would
        interleave with it)."""
        with self._lock:
            self._sock = None

    def heartbeat(self, interval: float) -> None:
        from kubernetes_tpu.apiserver.watchcache import SinkClosed

        with self._lock:
            if self.ended is not None:
                raise SinkClosed("watch connection already ended")
            if time.monotonic() - self.last_write >= interval:
                self._send(wire.HEARTBEAT if self._binary else b"\n")

    def send_raw(self, data: bytes) -> None:
        with self._lock:
            self._send(data)

    def close(self) -> None:
        with self._lock:
            if self.ended is None:
                self.ended = "closed"

    # ---- the actual write (lock held) ----

    def _send(self, data: bytes) -> None:
        from kubernetes_tpu.apiserver.watchcache import SinkClosed

        if self._sock is None:
            try:
                self._loop.call_soon_threadsafe(self._writer.write, data)
            except RuntimeError as e:
                raise SinkClosed(str(e)) from e
            self.last_write = time.monotonic()
            return
        deadline = time.monotonic() + self.SEND_TIMEOUT_S
        view = memoryview(data)
        while view.nbytes:
            try:
                sent = self._sock.send(view)
                view = view[sent:]
            except (BlockingIOError, InterruptedError):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # kernel send buffer stayed full for the whole
                    # deadline: slow consumer — the caller evicts
                    raise TimeoutError("watch client too slow")
                select.select([], [self._sock], [], min(0.05, remaining))
            except OSError as e:
                raise SinkClosed(str(e)) from e
        self.last_write = time.monotonic()


class APIServer:
    """Asyncio HTTP/1.1 apiserver over one ObjectStore.

    `authenticator`/`authorizer` (apiserver.auth) take the reference
    handler-chain's WithAuthentication/WithAuthorization positions
    (apiserver/pkg/server/config.go:470-478): no authenticator = open
    server (the in-process topology); with one, requests resolve to a user
    (else 401) and, with an authorizer, must pass ABAC (else 403)."""

    def __init__(self, store: ObjectStore, host: str = "127.0.0.1",
                 port: int = 0, authenticator=None, authorizer=None,
                 audit_path: str | None = None,
                 max_in_flight: int = 400,
                 tls_cert_file: str | None = None,
                 tls_key_file: str | None = None,
                 client_ca_file: str | None = None,
                 watch_cache: bool = False,
                 replica_id: str = ""):
        self.store = store
        self.host = host
        self.port = port
        # HA: which control-plane replica this process is (the reference's
        # stateless-apiservers-over-shared-etcd shape: N APIServers may
        # share ONE ObjectStore, each with its own watch cache, APF queues
        # and obs mux — coherence comes from the store's resourceVersions)
        self.replica_id = replica_id
        self._draining = False
        # fault injection (HA drills): accept connections but never answer
        # a byte — the worst partial failure, detectable only by client
        # I/O timeouts (FaultPlane.black_hole_replica flips it)
        self._black_holed = False
        # every live connection's writer, so kill() can hard-abort them
        # (SIGKILL-style: clients see a mid-stream reset, not a drain)
        self._conns: set[asyncio.StreamWriter] = set()
        # active watch serves: stream + writer, so drain() can hand them a
        # terminal "go reconnect now" frame instead of letting them idle
        # out against a dead replica
        self._watch_serves: set[Any] = set()
        self.authenticator = authenticator
        self.authorizer = authorizer
        self._authz_blocking: bool | None = None  # resolved on first request
        # secure serving (apiserver/pkg/server/secure_serving.go:
        # --tls-cert-file/--tls-private-key-file); None = plaintext
        self.tls_cert_file = tls_cert_file
        self.tls_key_file = tls_key_file
        # --client-ca-file: client certs verified against this CA resolve
        # to users via X509Authenticator (CN/O); optional, so token-only
        # clients still connect certless
        self.client_ca_file = client_ca_file
        self._server: asyncio.AbstractServer | None = None
        # WithAudit (config.go:474): one JSON line per request decision
        self._audit = open(audit_path, "a", encoding="utf-8") \
            if audit_path else None
        # APF (WithPriorityAndFairness, config.go:470) replaces the flat
        # WithMaxInFlightLimit gate: `max_in_flight` becomes the total seat
        # budget split across priority levels by their shares, with per-flow
        # fair queues behind it — a noisy tenant saturates its own level's
        # queues and gets honest 429+Retry-After while scheduler/kubelet
        # traffic keeps flowing through the `system` level. Watches and
        # node-proxy/aggregated relays bypass the filter BY DESIGN — the
        # reference's longRunningRequestCheck exempts them (maxinflight.go),
        # since informer watches would otherwise pin the budget permanently.
        self._in_flight = 0
        self.max_in_flight = max_in_flight
        from kubernetes_tpu.apiserver.flowcontrol import FlowController

        self.flow = FlowController(max_in_flight, store=store)
        # watch cache: one store subscription fanned out to N HTTP watchers
        # (constructed lazily on the serving loop at first watch)
        self._watch_cache_enabled = watch_cache
        self.watch_cache = None

    def _audit_log(self, user, method: str, path: str, status: int,
                   latency_ms: float | None = None,
                   response_bytes: int | None = None) -> None:
        if self._audit is None:
            return
        import time as _time

        record = {
            "ts": _time.time(),
            "user": getattr(user, "name", "") or "system:anonymous",
            "verb": method, "requestURI": path,
            "responseStatus": status}
        if latency_ms is not None:
            record["latencyMs"] = round(latency_ms, 3)
        if response_bytes is not None:
            record["responseBytes"] = response_bytes
        self._audit.write(json.dumps(record) + "\n")
        self._audit.flush()

    def _observe_request(self, method: str, path: str, status: int,
                         seconds: float) -> None:
        mx = _request_metrics()
        resource = _resource_of(path)
        mx[0].labels(method, resource, str(status)).inc()
        mx[1].labels(method, resource).observe(1e6 * seconds)

    def _authz_blocks(self) -> bool:
        """True when the authorizer chain can do network I/O (a webhook
        SAR POST): those decisions must run off the event loop or one slow
        webhook stalls every connection."""
        from kubernetes_tpu.apiserver.auth import WebhookAuthorizer

        a = self.authorizer
        if isinstance(a, WebhookAuthorizer):
            return True
        chain = getattr(a, "authorizers", None) or ()
        return any(isinstance(x, WebhookAuthorizer) for x in chain)

    def _authfilter(self, method: str, path: str,
                    headers: dict[str, str], peercert: dict | None = None):
        """-> ((status, payload) | None to proceed, authenticated user)."""
        if self.authenticator is None:
            return None, None
        user = self.authenticator.authenticate(headers, peercert)
        if user is None:
            return (401, {"kind": "Status", "reason": "Unauthorized",
                          "message": "no client certificate or valid "
                                     "bearer token presented"}), None
        # WithImpersonation (filters/impersonation.go:39) sits between
        # authn and authz: the effective user must be granted, and all
        # later authorization runs as the impersonated identity
        from kubernetes_tpu.apiserver.auth import impersonate

        requester = user
        user, ok = impersonate(self.authorizer, user, headers)
        if not ok:
            # audit the REQUESTER: the denied escalation attempt is the
            # one event that must stay attributed
            return (403, {"kind": "Status", "reason": "Forbidden",
                          "message": "impersonation denied"}), requester
        if self.authorizer is None:
            return None, user
        try:
            ns, plural, name, _sub = _split_path(path)
        except NotFound:
            # shape-less paths serve only GET discovery (/api, /apis,
            # /apis/{g}/{v}, /version) — open to every AUTHENTICATED user
            # like the reference's system:discovery role; anything else
            # 404s in routing. Resource-shaped paths never land here.
            return None, user
        verb = {"GET": "get" if name else "list", "POST": "create",
                "PUT": "update", "PATCH": "patch",
                "DELETE": "delete"}.get(method, method)
        # cluster-scoped (and cross-namespace) requests authorize against
        # namespace "" — only wildcard-namespace policies may grant them;
        # the object name feeds RBAC resourceNames scoping
        if self.authorizer.authorize(user, verb, plural, ns or "",
                                     name or ""):
            return None, user
        return (403, {"kind": "Status", "reason": "Forbidden",
                      "message": f"user {user.name!r} cannot {verb} "
                                 f"{plural} in {ns or 'cluster scope'}"}), user

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def start(self) -> None:
        ssl_ctx = None
        if self.tls_cert_file and self.tls_key_file:
            import ssl

            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.tls_cert_file, self.tls_key_file)
            if self.client_ca_file:
                ssl_ctx.load_verify_locations(cafile=self.client_ca_file)
                # OPTIONAL, not REQUIRED: bearer-token clients without a
                # certificate must still be able to connect (the union
                # authenticator tries x509 first, then tokens)
                ssl_ctx.verify_mode = ssl.CERT_OPTIONAL
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, ssl=ssl_ctx)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self.watch_cache is not None:
            # awaitable teardown: reaps the cancelled pump/worker tasks
            # and joins shard threads (stop() alone leaks pending tasks)
            await self.watch_cache.aclose()
            self.watch_cache = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._audit is not None:
            self._audit.close()
            self._audit = None

    # ---- HA replica lifecycle ----

    def kill(self) -> None:
        """SIGKILL-style death: abort every open transport NOW. Clients
        see connection resets mid-request/mid-stream — the failure mode a
        rolling restart must survive. Synchronous on purpose (a killed
        process doesn't await)."""
        self._draining = True
        if self._server is not None:
            self._server.close()
            self._server = None
        for w in list(self._conns):
            transport = w.transport
            if transport is not None:
                transport.abort()
        self._conns.clear()
        if self.watch_cache is not None:
            self.watch_cache.stop()
            self.watch_cache = None

    async def drain(self, timeout: float = 5.0) -> None:
        """Graceful shutdown: stop accepting (readyz goes 503 first, new
        requests bounce), let in-flight requests finish, then hand every
        live watcher a terminal DRAIN frame — "go reconnect now" — instead
        of letting them idle against a dead replica. Ends with stop()."""
        import time as _time

        self._draining = True
        deadline = _time.monotonic() + timeout
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        while self._in_flight > 0 and _time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        if self.watch_cache is not None:
            self.watch_cache.drain_subscribers()
        for serve in list(self._watch_serves):
            serve.request_drain()
        # the serve loops own their writers; give them a few ticks to
        # write the terminal frame and close
        while self._watch_serves and _time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        await self.stop()

    _ENDPOINTS_NAME = "kubernetes"

    def advertise(self) -> None:
        """Publish this replica's host:port into the well-known
        `default/kubernetes` Endpoints object (the reference's
        master-count endpoint reconciler) so replica-aware clients can
        discover the full set with one GET."""
        addr = {"ip": self.host, "port": self.port,
                "replica": self.replica_id or f"{self.host}:{self.port}"}

        def mutate(obj):
            subset = obj.subsets[0] if obj.subsets else {}
            addrs = [a for a in subset.get("addresses", [])
                     if (a.get("ip"), a.get("port"))
                     != (addr["ip"], addr["port"])]
            addrs.append(dict(addr))
            obj.subsets = [{"addresses": addrs}]
            return obj

        try:
            self.store.guaranteed_update(
                "Endpoints", self._ENDPOINTS_NAME, "default", mutate)
        except NotFound:
            ep = objs.Endpoints()
            ep.metadata.name = self._ENDPOINTS_NAME
            ep.metadata.namespace = "default"
            ep.subsets = [{"addresses": [dict(addr)]}]
            try:
                self.store.create(ep)
            except AlreadyExists:
                self.store.guaranteed_update(
                    "Endpoints", self._ENDPOINTS_NAME, "default", mutate)

    def unadvertise(self) -> None:
        """Remove this replica from the discovery Endpoints (drain path)."""
        def mutate(obj):
            subset = obj.subsets[0] if obj.subsets else {}
            addrs = [a for a in subset.get("addresses", [])
                     if (a.get("ip"), a.get("port"))
                     != (self.host, self.port)]
            obj.subsets = [{"addresses": addrs}] if addrs else []
            return obj

        try:
            self.store.guaranteed_update(
                "Endpoints", self._ENDPOINTS_NAME, "default", mutate)
        except NotFound:
            pass

    # ---- connection handling ----

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            while True:
                if self._black_holed:
                    # hold the connection open without reading or answering
                    # until the fault lifts (then close so the client's
                    # retry lands on a working replica) or the client's
                    # socket timeout fires
                    while self._black_holed:
                        await asyncio.sleep(0.02)
                    return
                try:
                    parsed = await read_http_request(reader)
                except ValueError:
                    await _respond(writer, 400, {"message": "bad request"})
                    return
                if parsed is None:
                    return
                if self._black_holed:  # request arrived as the hole opened
                    while self._black_holed:
                        await asyncio.sleep(0.02)
                    return
                method, target, headers, body = parsed
                import time as _time

                t_start = _time.perf_counter()
                url = urlsplit(target)
                query = {k: v[-1] for k, v in parse_qs(url.query).items()}
                # observability endpoints sit in FRONT of the filter chain
                # (the reference installs /metrics and healthz on the mux
                # before the resource handlers, server/config.go:513)
                obs = obs_response(
                    method, url.path, registry=obs_metrics.REGISTRY,
                    ready_checks={
                        "serving": lambda: self._server is not None,
                        # a draining replica fails /readyz FIRST so
                        # health-checking clients stop picking it before
                        # its listener closes (load-balancer semantics)
                        "accepting": lambda: not self._draining})
                if obs is not None:
                    status, obs_body, ctype = obs
                    writer.write(http_head(status, obs_body, ctype))
                    await writer.drain()
                    return
                if self._draining:
                    # graceful shutdown: new API requests bounce with an
                    # honest 503 (clients fail over to another replica);
                    # obs endpoints above still answer so /readyz reports
                    # the drain rather than timing out
                    await _respond(writer, 503, {
                        "kind": "Status", "reason": "ServiceUnavailable",
                        "message": "apiserver is shutting down"})
                    return
                # distributed tracing: continue the caller's trace when the
                # request carries a sampled W3C traceparent (head-based
                # sampling — the ROOT decided; the server never re-rolls)
                traceparent = headers.get("traceparent")
                parent_ctx = _tracing.parse_traceparent(traceparent or "")
                req_span = None
                if parent_ctx is not None and parent_ctx.sampled:
                    req_span = _tracing.TRACER.begin_span(
                        f"apiserver.{method.lower()}", parent=parent_ctx,
                        tid="apiserver", attrs={"path": url.path})
                # content negotiation (CodecFactory position): protobuf
                # in/out when the peer asks for it, JSON otherwise
                accept_pb = wire.available() and \
                    wire.CONTENT_TYPE in headers.get("accept", "")
                if wire.available() and headers.get(
                        "content-type", "").startswith(wire.CONTENT_TYPE):
                    loads = _wire_loads
                else:
                    loads = json.loads
                if self._authz_blocking is None:
                    self._authz_blocking = self._authz_blocks()
                auth_verb = "GET" if query.get("watch") in ("1", "true") \
                    else method
                peercert = writer.get_extra_info("peercert")
                if self._authz_blocking:
                    # webhook SAR does a blocking POST: keep the loop free
                    denied, user = await asyncio.to_thread(
                        self._authfilter, auth_verb, url.path, headers,
                        peercert)
                else:
                    denied, user = self._authfilter(auth_verb, url.path,
                                                    headers, peercert)
                if denied is not None:
                    if req_span is not None:
                        req_span.end("error")
                    nbytes = await _respond(writer, *denied)
                    lat = _time.perf_counter() - t_start
                    self._observe_request(method, url.path, denied[0], lat)
                    self._audit_log(user, method, target, denied[0],
                                    latency_ms=1e3 * lat,
                                    response_bytes=nbytes)
                    return
                if query.get("watch") in ("1", "true"):
                    if req_span is not None:
                        # the watch owns the connection from here; the
                        # server-side span covers admission into it
                        req_span.end("ok")
                    svc = self._api_service_for(url.path)
                    if svc is not None:
                        # aggregated watch: relay the byte stream to the
                        # extension apiserver (chunked frames pass through)
                        addr = urlsplit(svc.spec["serverAddress"])
                        status = await self._relay_raw(
                            writer, addr.hostname, addr.port or 80,
                            method, target, body)
                        self._audit_log(
                            user, method, target, status,
                            latency_ms=1e3 * (_time.perf_counter()
                                              - t_start))
                        return
                    self._audit_log(user, method, target, 200)
                    await self._serve_watch(writer, url.path, query,
                                            binary=accept_pb)
                    return  # watch owns the connection until it closes
                node_proxy = self._node_proxy_target(url.path)
                if node_proxy is not None:
                    if req_span is not None:
                        req_span.end("ok")
                    status = await self._proxy_to_node(
                        writer, method, node_proxy, url.query, body,
                        upgrade=headers.get("upgrade", ""),
                        client_reader=reader)
                    self._audit_log(
                        user, method, target, status,
                        latency_ms=1e3 * (_time.perf_counter() - t_start))
                    return  # the relay owns the connection
                # APF: classify into a flow and take a seat, queueing
                # fairly behind the level's concurrency share — or shed
                # with an honest 429 + Retry-After hint when the flow's
                # queues are full (WithPriorityAndFairness position)
                try:
                    seat = await self.flow.acquire(
                        user, method, _resource_of(url.path),
                        width=self._request_width(method, url.path))
                except FlowRejected as rejected:
                    if req_span is not None:
                        req_span.end("throttled")
                    nbytes = await _respond(
                        writer, 429, {
                            "kind": "Status", "reason": "TooManyRequests",
                            "message": str(rejected)},
                        extra_headers={"Retry-After": str(
                            max(1, round(rejected.retry_after)))})
                    lat = _time.perf_counter() - t_start
                    self._observe_request(method, url.path, 429, lat)
                    self._audit_log(user, method, target, 429,
                                    latency_ms=1e3 * lat,
                                    response_bytes=nbytes)
                    return
                self._in_flight += 1
                _request_metrics()[2].set(self._in_flight)
                try:
                    # hold the seat across one loop tick: the route work
                    # below is synchronous, so without a suspension point
                    # here no two requests would ever hold seats at once
                    # and the fair queues could never engage
                    await asyncio.sleep(0)
                    proxied = await self._aggregate(
                        method, target, body,
                        content_type=headers.get("content-type",
                                                 "application/json"))
                    if proxied is not None:
                        status, payload = proxied
                    else:
                        from kubernetes_tpu.apiserver.admission import (
                            request_user,
                        )

                        with request_user(user):
                            status, payload = self._route(
                                method, url.path, query, body, loads=loads,
                                content_type=headers.get("content-type",
                                                         ""),
                                user=user,
                                traceparent=traceparent
                                if req_span is not None else None)
                finally:
                    self._in_flight -= 1
                    _request_metrics()[2].set(self._in_flight)
                    self.flow.release(seat)
                keep = headers.get("connection", "keep-alive").lower() != "close"
                nbytes = await _respond(writer, status, payload,
                                        keep_alive=keep, binary=accept_pb)
                lat = _time.perf_counter() - t_start
                self.flow.note_latency(seat, lat)
                self._observe_request(method, url.path, status, lat)
                if req_span is not None:
                    req_span.set_attr("status", status)
                    req_span.end("ok" if status < 500 else "error")
                self._audit_log(user, method, target, status,
                                latency_ms=1e3 * lat, response_bytes=nbytes)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    def _request_width(self, method: str, path: str) -> int:
        """APF work estimator (apf listWorkEstimator): a collection GET
        costs extra seats proportional to the collection size, so a few
        concurrent big LISTs fill their level and the surplus queues or
        sheds instead of stacking serialization work on the serving loop.
        Everything else costs 1 seat."""
        if method != "GET":
            return 1
        try:
            _ns, plural, name, _sub = _split_path(path)
        except NotFound:
            return 1
        kind = RESOURCES.get(plural)
        if name is not None or kind is None:
            return 1
        count = len(self.store._objects.get(kind, ()))
        return 1 + min(9, count // 50)

    # ---- node proxy (pkg/registry/core/node/rest proxy subresource) ----

    def _node_proxy_target(self, path: str):
        """/api/v1/nodes/{name}/proxy/{rest} -> (kubelet host, port, rest)
        from the node's published daemonEndpoints, or None."""
        parts = [p for p in path.strip("/").split("/") if p]
        if len(parts) < 5 or parts[:2] != ["api", "v1"] \
                or parts[2] != "nodes" or parts[4] != "proxy":
            return None
        try:
            node = self.store.get("Node", parts[3])
        except NotFound:
            return ("", 0, "")  # sentinel: 404 downstream
        port = ((node.status.daemon_endpoints.get("kubeletEndpoint")
                 or {}).get("Port", 0))
        if not port:
            return ("", 0, "")
        return ("127.0.0.1", int(port), "/" + "/".join(parts[5:]))

    async def _proxy_to_node(self, writer, method: str, target, query: str,
                             body: bytes, upgrade: str = "",
                             client_reader=None) -> None:
        """Relay the request to the kubelet API and pipe the raw response
        bytes back — chunked log-follow streams straight through (the
        reference's upgrade-aware proxy handler, collapsed to a byte
        relay). With `upgrade` set the relay is BIDIRECTIONAL after the
        backend answers: exec/port-forward frames flow both ways (the
        SPDY-tunneling half of the reference proxy)."""
        host, port, rest = target
        if not port:
            await _respond(writer, 404, {
                "kind": "Status", "reason": "NotFound",
                "message": "node has no kubelet endpoint"})
            return 404
        path = rest + (f"?{query}" if query else "")
        return await self._relay_raw(
            writer, host, port, method, path, body,
            unreachable_message="kubelet unreachable",
            upgrade=upgrade, client_reader=client_reader)

    async def _relay_raw(self, writer, host: str, port: int, method: str,
                         path: str, body: bytes, *,
                         unreachable_message: str = "backend unreachable",
                         upgrade: str = "", client_reader=None) -> int:
        """Pipe one request to a backend and its raw response bytes back —
        the streaming relay under both the node proxy and aggregated
        watches. Returns the relayed status code (for the audit trail)."""
        try:
            up_reader, up_writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), timeout=5.0)
        except (OSError, asyncio.TimeoutError):
            await _respond(writer, 503, {
                "kind": "Status", "reason": "ServiceUnavailable",
                "message": unreachable_message})
            return 503
        status = 0
        head = b""
        pump_task = None
        try:
            extra = (f"Connection: Upgrade\r\nUpgrade: {upgrade}\r\n"
                     if upgrade else "Connection: close\r\n")
            up_writer.write(
                f"{method} {path} HTTP/1.1\r\n"
                f"Host: {host}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"{extra}\r\n".encode() + body)
            await up_writer.drain()
            if upgrade and client_reader is not None:
                async def pump_up():
                    try:
                        while True:
                            data = await client_reader.read(65536)
                            if not data:
                                break
                            up_writer.write(data)
                            await up_writer.drain()
                    except (ConnectionError, asyncio.CancelledError):
                        pass

                pump_task = asyncio.get_running_loop().create_task(
                    pump_up())
            while True:
                chunk = await up_reader.read(65536)
                if not chunk:
                    break
                if not status:
                    head += chunk
                    try:
                        status = parse_status_line(
                            head.partition(b"\r\n")[0])
                    except ValueError:
                        status = 0 if b"\r\n" not in head else -1
                writer.write(chunk)
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            if pump_task is not None:
                pump_task.cancel()
            up_writer.close()
        return status

    # ---- aggregation (kube-aggregator analog) ----

    def _api_service_for(self, path: str):
        """An APIService whose spec.group/version owns this /apis path and
        names a remote backend (spec.serverAddress). Local APIServices
        (no backend) fall through to the core handlers — the aggregator's
        'Local' services (kube-aggregator apiserver/handler_proxy.go)."""
        parts = [p for p in path.strip("/").split("/") if p]
        if len(parts) < 3 or parts[0] != "apis":
            return None
        group, version = parts[1], parts[2]
        for svc in self.store.list("APIService", copy_objects=False):
            if svc.group_version == (group, version) \
                    and svc.spec.get("serverAddress"):
                return svc
        return None

    async def _aggregate(self, method: str, target: str, body: bytes,
                         content_type: str = "application/json"):
        """Proxy one request to the owning extension apiserver, or None to
        serve locally. Unreachable backends are 503 + Available=False on
        the APIService (the aggregator's availability controller,
        kube-aggregator pkg/apiserver/handler_proxy.go + status
        controller). The peer's Content-Type is forwarded (a protobuf body
        must reach the extension server labeled as such); the backend's
        response decodes by ITS content-type — the aggregator re-encodes
        for the original client at _respond."""
        svc = self._api_service_for(urlsplit(target).path)
        if svc is None:
            return None
        addr = urlsplit(svc.spec["serverAddress"])
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(addr.hostname, addr.port or 80),
                timeout=5.0)
        except (OSError, asyncio.TimeoutError):
            self._mark_available(svc.metadata.name, False)
            return 503, {"kind": "Status", "reason": "ServiceUnavailable",
                         "message": f"APIService {svc.metadata.name}: "
                                    f"backend unreachable"}
        try:
            writer.write(
                f"{method} {target} HTTP/1.1\r\n"
                f"Host: {addr.hostname}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n".encode() + body)
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), timeout=30.0)
        except (OSError, asyncio.TimeoutError):
            self._mark_available(svc.metadata.name, False)
            return 503, {"kind": "Status", "reason": "ServiceUnavailable",
                         "message": f"APIService {svc.metadata.name}: "
                                    f"backend failed mid-request"}
        finally:
            writer.close()
        head, _, resp_body = raw.partition(b"\r\n\r\n")
        try:
            status = parse_status_line(head)
        except ValueError:
            # backend accepted the connection but spoke no HTTP (crashed
            # handler / wrong service): that's unavailable too
            self._mark_available(svc.metadata.name, False)
            return 503, {"kind": "Status", "reason": "ServiceUnavailable",
                         "message": f"APIService {svc.metadata.name}: "
                                    f"backend sent no HTTP response"}
        self._mark_available(svc.metadata.name, True)
        try:
            if resp_body and wire.CONTENT_TYPE.encode() in head.lower():
                payload = wire.decode_payload(resp_body)
            else:
                payload = json.loads(resp_body) if resp_body else {}
        except ValueError:
            payload = {"message": resp_body.decode(errors="replace")}
        return status, payload

    def _mark_available(self, name: str, ok: bool) -> None:
        cond = {"type": "Available", "status": "True" if ok else "False"}

        def mutate(obj):
            conds = [c for c in obj.status.get("conditions", [])
                     if c.get("type") != "Available"]
            conds.append(cond)
            obj.status["conditions"] = conds
            return obj

        try:
            current = self.store.get("APIService", name)
            have = next((c for c in current.status.get("conditions", [])
                         if c.get("type") == "Available"), None)
            if have and have.get("status") == cond["status"]:
                return
            self.store.guaranteed_update("APIService", name, "default",
                                         mutate)
        except (NotFound, Conflict):
            pass

    # ---- routing ----

    def _resolve_plural(self, plural: str) -> str:
        """plural -> kind, consulting registered CRDs for custom resources
        (the apiextensions serving path)."""
        kind = RESOURCES.get(plural)
        if kind is not None:
            return kind
        for crd in self.store.list("CustomResourceDefinition",
                                   copy_objects=False):
            if crd.plural == plural and crd.target_kind:
                return crd.target_kind
        raise NotFound(f"unknown resource {plural!r}")

    def _parse_path(self, path: str):
        """-> (ns | None, plural, kind, name | None, subresource | None).
        Resolves the kind exactly once per request (CRD lookups scan the
        store)."""
        ns, plural, name, sub = _split_path(path)
        return ns, plural, self._resolve_plural(plural), name, sub

    # ---- discovery (server/routes + endpoints/discovery analogs) ----

    # group/version per non-core kind, DERIVED from each class's
    # api_version (the scheme registration) — one source of truth, so a
    # new grouped kind only declares api_version on its class
    GROUPS = {
        kind: tuple(cls.api_version.split("/", 1))
        for kind, cls in KIND_TO_CLS.items()
        if "/" in getattr(cls, "api_version", "v1")}
    CLUSTER_SCOPED = frozenset({
        "Node", "PersistentVolume", "Namespace",
        "CustomResourceDefinition", "APIService", "Cluster",
        "ClusterRole", "ClusterRoleBinding",
        "CertificateSigningRequest",
        "FlowSchema", "PriorityLevelConfiguration", "AlertRule"})

    def _discovery(self, method: str, path: str):
        """-> (status, payload) for discovery paths, else None."""
        if method != "GET":
            return None
        parts = [p for p in path.strip("/").split("/") if p]
        if parts == ["version"]:
            return 200, {"major": "1", "minor": "8",
                         "gitVersion": "v1.8.0-tpu",
                         "platform": "tpu/xla"}
        if parts in (["swagger.json"], ["openapi", "v2"]):
            # schema introspection (routes/openapi.go): what kubectl
            # explain reads; generated once from the object model
            if not hasattr(self, "_swagger"):
                from kubernetes_tpu.apiserver.openapi import build_swagger
                self._swagger = build_swagger()
            return 200, self._swagger
        if parts == ["api"]:
            return 200, {"kind": "APIVersions", "versions": ["v1"]}
        if parts == ["apis"]:
            groups: dict[str, set] = {}
            for kind, (group, version) in self.GROUPS.items():
                groups.setdefault(group, set()).add(version)
            for svc in self.store.list("APIService", copy_objects=False):
                g, v = svc.group_version
                if g:
                    groups.setdefault(g, set()).add(v)
            for crd in self.store.list("CustomResourceDefinition",
                                       copy_objects=False):
                g = crd.spec.get("group", "")
                if g:
                    groups.setdefault(g, set()).add(
                        crd.spec.get("version") or "v1")
            return 200, {"kind": "APIGroupList", "groups": [
                {"name": g, "versions": [
                    {"groupVersion": f"{g}/{v}", "version": v}
                    for v in sorted(vs)]}
                for g, vs in sorted(groups.items())]}
        if parts == ["api", "v1"] or (
                len(parts) == 3 and parts[0] == "apis"):
            if parts == ["api", "v1"]:
                want = lambda kind: kind not in self.GROUPS  # noqa: E731
                gv = "v1"
            else:
                gv = f"{parts[1]}/{parts[2]}"
                want = lambda kind: self.GROUPS.get(kind) == (  # noqa: E731
                    parts[1], parts[2])
            resources = [
                {"name": plural, "kind": kind,
                 "namespaced": kind not in self.CLUSTER_SCOPED}
                for plural, kind in sorted(RESOURCES.items())
                if want(kind)]
            for crd in self.store.list("CustomResourceDefinition",
                                       copy_objects=False):
                crd_gv = (f"{crd.spec.get('group')}/"
                          f"{crd.spec.get('version') or 'v1'}")
                if crd_gv == gv and crd.plural:
                    resources.append({
                        "name": crd.plural, "kind": crd.target_kind,
                        "namespaced": crd.spec.get("scope", "Namespaced")
                        == "Namespaced"})
            if not resources and parts[:1] == ["apis"]:
                return None  # unknown group: fall through to routing 404
            return 200, {"kind": "APIResourceList", "groupVersion": gv,
                         "resources": resources}
        return None

    def _route(self, method: str, path: str, query: dict, body: bytes,
               loads=json.loads, content_type: str = "", user=None,
               traceparent: str | None = None):
        discovered = self._discovery(method, path)
        if discovered is not None:
            return discovered
        try:
            ns, _plural, kind, name, sub = self._parse_path(path)
            if sub == "binding" and method == "POST" and kind == "Pod":
                args = loads(body)
                target = (args.get("target") or {}).get("name", "")
                self.store.bind(Binding(pod_name=name,
                                        namespace=ns or "default",
                                        target_node=target))
                return 201, {"kind": "Status", "status": "Success"}
            if sub == "eviction" and method == "POST" and kind == "Pod":
                # pods/eviction subresource (pkg/registry/core/pod/storage/
                # eviction.go): delete gated by PodDisruptionBudgets; a
                # denied eviction is 429 TooManyRequests, the kubectl-drain
                # retry signal
                from kubernetes_tpu.controllers.disruption import can_evict

                pod = self.store.get("Pod", name, ns or "default")
                if not can_evict(self.store, pod):
                    # the DisruptionBudget cause distinguishes this 429
                    # from max-in-flight load shedding (eviction.go returns
                    # the same shape) — clients must not misread a shed as
                    # a PDB denial
                    return 429, {"kind": "Status",
                                 "reason": "TooManyRequests",
                                 "message": "Cannot evict pod as it would "
                                            "violate the pod's disruption "
                                            "budget.",
                                 "details": {"causes": [
                                     {"reason": "DisruptionBudget"}]}}
                self.store.delete("Pod", name, ns or "default")
                return 201, {"kind": "Status", "status": "Success"}
            if sub is not None:
                return 404, {"message": f"unknown subresource {sub!r}"}
            if method == "GET" and name is not None:
                obj = self.store.get(kind, name, ns or "default")
                return 200, encode_object(obj)
            if method == "GET":
                items = self.store.list(kind, namespace=ns,
                                        copy_objects=False)
                return 200, {
                    "kind": f"{kind}List",
                    "metadata": {"resourceVersion":
                                 str(self.store.resource_version)},
                    "items": [encode_object(o) for o in items]}
            if method == "POST":
                obj = decode_object(kind, loads(body))
                if ns:
                    obj.metadata.namespace = ns
                if kind == "Pod" and traceparent is not None:
                    # create is the trace's entry into the store: the
                    # annotation rides every watch delivery, so the
                    # scheduler and kubelet join the caller's trace
                    obj.metadata.annotations.setdefault(
                        _tracing.TRACE_ANNOTATION, traceparent)
                if kind == "CertificateSigningRequest" and user is not None:
                    # registry strategy stamps the REQUESTER's identity
                    # (pkg/registry/certificates/certificates/strategy.go:
                    # 45 PrepareForCreate) — clients cannot forge the
                    # username/groups the approving controller trusts
                    obj.spec["username"] = user.name
                    obj.spec["groups"] = list(user.groups)
                created = self.store.create(obj)
                return 201, encode_object(created)
            if method == "PATCH" and name is not None:
                # patch bodies are JSON under every patch content type
                # (patch.go:51 negotiates the three +json types)
                from kubernetes_tpu.apiserver.strategicpatch import PatchError

                try:
                    patched = self.store.patch(kind, name, ns or "default",
                                               json.loads(body),
                                               content_type)
                except PatchError as e:
                    return 400, {"kind": "Status", "reason": "BadRequest",
                                 "message": str(e)}
                return 200, encode_object(patched)
            if method == "PUT" and name is not None:
                obj = decode_object(kind, loads(body))
                if ns:
                    obj.metadata.namespace = ns
                updated = self.store.update(obj)
                return 200, encode_object(updated)
            if method == "DELETE" and name is not None:
                if kind == "Namespace":
                    # first DELETE soft-deletes into Terminating (the
                    # namespace controller cascades); a DELETE of an
                    # already-Terminating namespace finalizes it — which is
                    # how a controller running over RemoteStore removes the
                    # object after the sweep (finalize semantics)
                    from kubernetes_tpu.controllers.namespace import (
                        namespace_is_empty,
                        request_namespace_deletion,
                    )

                    current = self.store.get("Namespace", name)
                    if current.phase != "Terminating":
                        request_namespace_deletion(self.store, name)
                        return 200, encode_object(
                            self.store.get("Namespace", name))
                    if not namespace_is_empty(self.store, name):
                        # finalize only once the sweep has emptied it — an
                        # impatient repeat DELETE must not orphan contents
                        return 409, {"kind": "Status", "reason": "Conflict",
                                     "message": f"namespace {name} is "
                                                f"terminating; contents are "
                                                f"still being deleted"}
                deleted = self.store.delete(kind, name, ns or "default")
                return 200, encode_object(deleted)
            if method == "DELETE":
                # DeleteCollection (generic registry store.go): every
                # object in the (kind, namespace) scope, optional
                # labelSelector. Namespaces go through their Terminating
                # flow (same as single delete — a hard sweep would orphan
                # their contents); finalizer-bearing objects soft-delete
                # and are reported separately so retry loops converge
                selector = None
                if query.get("labelSelector"):
                    selector = parse_label_selector(
                        query["labelSelector"])
                victims = self.store.list(kind, namespace=ns,
                                          label_selector=selector,
                                          copy_objects=False)
                count = terminating = 0
                for obj in list(victims):
                    if kind == "Namespace":
                        from kubernetes_tpu.controllers.namespace import (
                            request_namespace_deletion,
                        )

                        if obj.phase != "Terminating":
                            try:
                                request_namespace_deletion(
                                    self.store, obj.metadata.name)
                            except (NotFound, Conflict):
                                continue
                        terminating += 1
                        continue
                    try:
                        out = self.store.delete(kind, obj.metadata.name,
                                                obj.metadata.namespace)
                    except NotFound:
                        continue
                    if out.metadata.finalizers:
                        terminating += 1  # soft-deleted, still present
                    else:
                        count += 1
                return 200, {"kind": "Status", "status": "Success",
                             "details": {"deleted": count,
                                         "terminating": terminating}}
            return 405, {"message": f"method {method} not allowed"}
        except FencedWrite as e:
            # replication fencing: this replica is a standby or a deposed
            # primary. 409 with a distinct reason (not Conflict — nothing
            # here is retryable against THIS endpoint) carrying the newer
            # epoch and the current primary so the client can chase it
            return 409, {"kind": "Status", "reason": "Fenced",
                         "message": str(e),
                         "details": {"epoch": e.epoch,
                                     "endpoint": e.endpoint}}
        except NotFound as e:
            return 404, {"kind": "Status", "reason": "NotFound",
                         "message": str(e)}
        except AdmissionError as e:
            return 403, {"kind": "Status", "reason": "Forbidden",
                         "message": str(e)}
        except ValidationError as e:
            return 422, {"kind": "Status", "reason": "Invalid",
                         "message": str(e)}
        except AlreadyExists as e:
            return 409, {"kind": "Status", "reason": "AlreadyExists",
                         "message": str(e)}
        except Conflict as e:
            return 409, {"kind": "Status", "reason": "Conflict",
                         "message": str(e)}
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            return 400, {"kind": "Status", "reason": "BadRequest",
                         "message": f"{type(e).__name__}: {e}"}

    # ---- watch streaming ----

    # heartbeat interval for idle watch connections; drills lower it so
    # black-holed replicas are detected in test time, not 30s
    watch_heartbeat_s = 30.0

    async def _serve_watch(self, writer: asyncio.StreamWriter, path: str,
                           query: dict, binary: bool = False) -> None:
        try:
            ns, _plural, kind, _name, _sub = self._parse_path(path)
        except NotFound as e:
            await _respond(writer, 404, {"message": str(e)})
            return
        since = query.get("resourceVersion")
        source = self.store
        if self._watch_cache_enabled:
            if self.watch_cache is None:
                # first watch constructs + primes the cache ON the serving
                # loop (start() is synchronous up to task spawn, so no
                # event lands between priming and subscribing)
                from kubernetes_tpu.apiserver.watchcache import WatchCache

                self.watch_cache = WatchCache(self.store).start()
            source = self.watch_cache
        if getattr(source, "sharded", False):
            # sharded cache: frames are written by the owning shard
            # thread, not this coroutine — different serve shape
            await self._serve_watch_sharded(writer, source, kind, ns,
                                            since, binary)
            return
        try:
            stream = source.watch(
                kind, since=int(since) if since else None)
        except Expired as e:
            # 410 Gone — the Reflector relists (watch.go / cacher semantics)
            await _respond(writer, 410, {"kind": "Status", "reason": "Gone",
                                         "message": str(e)})
            return
        content_type = wire.CONTENT_TYPE if binary else "application/json"
        writer.write(f"HTTP/1.1 200 OK\r\n"
                     f"Content-Type: {content_type}\r\n"
                     f"Transfer-Encoding: identity\r\n"
                     f"Connection: close\r\n\r\n".encode())
        serve = _WatchServe(self.store, stream)
        self._watch_serves.add(serve)
        last_rv = int(since) if since else self.store.resource_version
        try:
            while True:
                event = await stream.next(timeout=self.watch_heartbeat_s)
                if event is None:
                    if getattr(stream, "_stopped", False):
                        # stream is over (evicted, or this replica is
                        # draining) — end the connection instead of
                        # heartbeating a dead stream forever. A drain
                        # gets the explicit terminal frame: "resume from
                        # last_rv on another replica, now".
                        if serve.draining or getattr(stream, "drained",
                                                     False):
                            await self._write_drain_frame(
                                writer, last_rv, binary)
                        return
                    # heartbeat frame keeps half-open detection simple
                    writer.write(wire.HEARTBEAT if binary else b"\n")
                    await writer.drain()
                    continue
                if ns and event.obj.metadata.namespace != ns:
                    continue
                last_rv = event.resource_version
                if binary:
                    writer.write(wire.encode_watch_frame(
                        event.type, event.resource_version,
                        encode_object(event.obj)))
                else:
                    frame = {"type": event.type,
                             "resourceVersion": event.resource_version,
                             "object": encode_object(event.obj)}
                    writer.write(json.dumps(frame).encode() + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._watch_serves.discard(serve)
            stream.stop()
            writer.close()

    async def _serve_watch_sharded(self, writer: asyncio.StreamWriter,
                                   cache, kind: str | None,
                                   ns: str | None, since: str | None,
                                   binary: bool) -> None:
        """Sharded watch serving: subscribe a `_WatchSink`, so the owning
        shard thread writes every frame straight to the socket. This
        coroutine only writes the response headers, heartbeats idle
        connections, and ends the stream — with the terminal DRAIN frame
        on a graceful replica drain (same bytes as the single-loop path,
        the PR 12 FailoverWatch contract)."""
        from kubernetes_tpu.apiserver.watchcache import SinkClosed

        loop = asyncio.get_running_loop()
        last_rv = int(since) if since else self.store.resource_version
        sink = _WatchSink(writer, loop, ns, binary, last_rv)
        try:
            handle = cache.watch_sink(
                kind, since=int(since) if since else None,
                sink=sink, on_end=sink.on_end)
        except Expired as e:
            await _respond(writer, 410, {"kind": "Status", "reason": "Gone",
                                         "message": str(e)})
            return
        content_type = wire.CONTENT_TYPE if binary else "application/json"
        serve = _WatchServe(self.store, handle)
        self._watch_serves.add(serve)
        try:
            writer.write(f"HTTP/1.1 200 OK\r\n"
                         f"Content-Type: {content_type}\r\n"
                         f"Transfer-Encoding: identity\r\n"
                         f"Connection: close\r\n\r\n".encode())
            await writer.drain()
            # direct socket writes may only start once the transport's own
            # buffer is empty (drain() guarantees below-high-water, not
            # empty); if it never empties, stay loop-marshalled
            for _ in range(100):
                if writer.transport.get_write_buffer_size() == 0:
                    break
                await asyncio.sleep(0.01)
            else:
                sink.force_loop_writes()
            await asyncio.to_thread(sink.arm)
            while True:
                try:
                    await asyncio.wait_for(sink.end_event.wait(),
                                           timeout=self.watch_heartbeat_s)
                except asyncio.TimeoutError:
                    try:
                        await asyncio.to_thread(sink.heartbeat,
                                                self.watch_heartbeat_s)
                    except (SinkClosed, TimeoutError, OSError):
                        return  # client is gone
                    continue
                # stream over: evicted (consumer relists on its own),
                # closed, or drained — only a drain gets the terminal
                # "resume from last_rv on another replica, now" frame
                if sink.ended == "drained" or serve.draining:
                    status = {"kind": "Status", "reason": "Draining",
                              "message": "replica shutting down; resume "
                                         "from resourceVersion "
                                         f"{sink.last_rv} elsewhere"}
                    if binary:
                        data = wire.encode_watch_frame(
                            "DRAIN", sink.last_rv, status)
                    else:
                        data = json.dumps(
                            {"type": "DRAIN",
                             "resourceVersion": sink.last_rv,
                             "object": status}).encode() + b"\n"
                    try:
                        await asyncio.to_thread(sink.send_raw, data)
                    except (SinkClosed, TimeoutError, OSError):
                        pass
                return
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._watch_serves.discard(serve)
            handle.stop()
            sink.close()  # late shard writes raise SinkClosed, not OSError
            writer.close()

    async def _write_drain_frame(self, writer, last_rv: int,
                                 binary: bool) -> None:
        status = {"kind": "Status", "reason": "Draining",
                  "message": "replica shutting down; resume from "
                             f"resourceVersion {last_rv} elsewhere"}
        try:
            if binary:
                writer.write(wire.encode_watch_frame(
                    "DRAIN", last_rv, status))
            else:
                writer.write(json.dumps(
                    {"type": "DRAIN", "resourceVersion": last_rv,
                     "object": status}).encode() + b"\n")
            await writer.drain()
        except (ConnectionError, OSError):
            pass


def _wire_loads(body: bytes) -> dict:
    """Protobuf request-body decode, failures normalized onto the JSON
    error path (the 400 BadRequest handler catches ValueError)."""
    try:
        return wire.decode_payload(body)
    except ValueError:
        raise
    except Exception as e:  # protobuf DecodeError isn't a ValueError
        raise ValueError(f"undecodable protobuf body: {e}") from e


async def _respond(writer: asyncio.StreamWriter, status: int, payload,
                   keep_alive: bool = False, binary: bool = False,
                   extra_headers: dict[str, str] | None = None) -> int:
    """Write one response; returns the body size in bytes (the audit
    trail's responseBytes field)."""
    content_type = "application/json"
    if binary and isinstance(payload, dict) and payload.get("kind"):
        body = wire.encode_payload(payload)
        content_type = wire.CONTENT_TYPE
    else:
        body = json.dumps(payload).encode()
    reason = {200: "OK", 201: "Created", 400: "Bad Request", 404: "Not Found",
              405: "Method Not Allowed", 409: "Conflict",
              410: "Gone", 429: "Too Many Requests"}.get(status, "Error")
    conn = "keep-alive" if keep_alive else "close"
    extras = "".join(f"{k}: {v}\r\n"
                     for k, v in (extra_headers or {}).items())
    writer.write(
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"{extras}"
        f"Connection: {conn}\r\n\r\n".encode() + body)
    await writer.drain()
    return len(body)


# ---------------------------------------------------------------------------
# client half
# ---------------------------------------------------------------------------


class RemoteWatchStream:
    """Async watch frames -> WatchEvent, Informer-compatible. Frames are
    JSON lines, or length-prefixed protobuf WatchFrames when the stream was
    negotiated binary (`binary=True`)."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, binary: bool = False):
        self._reader = reader
        self._writer = writer
        self._stopped = False
        self._binary = binary
        # set when the server ended the stream with a graceful DRAIN
        # frame: the rv to resume from on another replica
        self.drain_rv: int | None = None
        # a timeout can cancel _read_frame between the length prefix and
        # the body; the parsed length survives here so the next call
        # resumes mid-frame instead of desyncing the stream (readexactly
        # leaves the buffer intact when cancelled mid-wait, so only the
        # already-consumed prefix needs carrying)
        self._pending_len: int | None = None

    async def _read_frame(self) -> dict | None:
        """One frame dict, or None for a heartbeat."""
        if self._binary:
            if self._pending_len is None:
                prefix = await self._reader.readexactly(4)
                self._pending_len = int.from_bytes(prefix, "big")
            length = self._pending_len
            if length == 0:
                self._pending_len = None
                return None  # heartbeat
            body = await self._reader.readexactly(length)
            self._pending_len = None
            return wire.decode_watch_frame(body)
        line = await self._reader.readline()
        if not line:
            raise ConnectionError("watch stream closed")
        line = line.strip()
        if not line:
            return None  # heartbeat
        return json.loads(line)

    async def next(self, timeout: float | None = None) -> WatchEvent | None:
        if self._stopped:
            return None
        try:
            while True:
                if timeout is None:
                    frame = await self._read_frame()
                else:
                    frame = await asyncio.wait_for(self._read_frame(),
                                                   timeout)
                if frame is None:
                    continue  # heartbeat
                if frame.get("type") == "DRAIN":
                    # the replica is shutting down gracefully and told us
                    # to reconnect NOW: surface as the same transport
                    # signal a hard kill produces, so every consumer's
                    # failover path (FailoverWatch resume, informer
                    # resume-then-relist) handles both identically
                    self.drain_rv = int(frame.get("resourceVersion", 0))
                    raise ConnectionError(
                        "replica draining; resume from resourceVersion "
                        f"{self.drain_rv}")
                obj = decode_object(frame["object"].get("kind"),
                                    frame["object"])
                return WatchEvent(frame["type"], obj.kind, obj,
                                  int(frame.get("resourceVersion", 0)))
        except asyncio.IncompleteReadError:
            raise ConnectionError("watch stream closed") from None
        except asyncio.TimeoutError:
            return None

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._writer.close()

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev


class FailoverWatch:
    """One logical watch across the whole replica set.

    Consumes a RemoteStore watch and, when the stream dies in transport
    (replica killed) or the replica drains (terminal DRAIN frame), reopens
    it on another endpoint with `since=<last delivered rv>` — so the
    consumer observes ONE gapless event sequence across any number of
    replica deaths. Events at or below the last delivered rv are dropped
    (a resumed stream replays nothing, but dedup by rv makes that a
    guarantee rather than a hope). A 410 on resume — the rv has aged out
    of every replica's ring — raises honest `Expired`: the consumer must
    relist; there is no silent gap path."""

    def __init__(self, store: "RemoteStore", kind: str | None,
                 since: int | None):
        self._store = store
        self._kind = kind
        self._last_rv = since
        self._stream = None
        self._stopped = False
        self.resumes = 0

    @property
    def last_rv(self) -> int | None:
        return self._last_rv

    async def next(self, timeout: float | None = None) -> WatchEvent | None:
        import random as _random
        import time as _time

        if self._stopped:
            return None
        delay = 0.05
        fail_start = None
        while True:
            if self._stream is None:
                self._stream = self._store.watch(self._kind,
                                                 since=self._last_rv)
            try:
                event = await self._stream.next(timeout=timeout)
            except Expired:
                raise  # honest 410: the consumer must relist
            except (ConnectionError, OSError, asyncio.TimeoutError):
                now = _time.monotonic()
                if fail_start is None:
                    fail_start = now
                elif now - fail_start > self._store.connect_deadline_s:
                    raise
                self._stream.stop()
                self._stream = None
                self.resumes += 1
                if self._stopped:
                    return None
                await asyncio.sleep(delay * (0.5 + _random.random()))
                delay = min(1.0, 2 * delay)
                continue
            if fail_start is not None:
                self._store.failover_total += 1
                self._store.failover_samples.append(
                    1e3 * (_time.monotonic() - fail_start))
                fail_start = None
                delay = 0.05
            if event is None:
                return None  # heartbeat / idle timeout
            if self._last_rv is not None \
                    and event.resource_version <= self._last_rv:
                continue  # boundary replay after a resume: drop, don't dupe
            self._last_rv = event.resource_version
            return event

    def stop(self) -> None:
        self._stopped = True
        if self._stream is not None:
            self._stream.stop()

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev


class RemoteStore:
    """ObjectStore-compatible client over the HTTP API: informers, the
    scheduler driver, controllers, and the extender run over TCP unchanged.

    Replica-aware (HA): pass `endpoints=[(host, port), ...]` and the client
    treats the control plane as a SET — it health-checks via /readyz,
    fails over on connect/refused/mid-stream/503 errors with jittered
    backoff, re-resolves the set from the well-known `default/kubernetes`
    Endpoints object (`discover_endpoints`), and spreads watch connections
    round-robin so every replica's fan-out cache carries load. With a
    single (host, port) the behavior is exactly the pre-HA client."""

    def __init__(self, host: str, port: int, token: str = "",
                 rate_limiter=None, wire_format: str | None = None,
                 tls: bool = False, ca_file: str | None = None,
                 insecure_skip_verify: bool = False,
                 cert_file: str | None = None,
                 key_file: str | None = None,
                 endpoints: list[tuple[str, int]] | None = None,
                 request_timeout_s: float | None = None):
        self._endpoints: list[tuple[str, int]] = \
            [(h, int(p)) for h, p in endpoints] if endpoints \
            else [(host, int(port))]
        self._active = 0
        # last endpoint that answered a request successfully: the first
        # probe after a transport failure (it is the likeliest survivor,
        # so failover skips the dead-endpoint walk in the common case)
        self._last_good: int | None = None
        # highest fencing epoch observed in any reply: replies from older
        # epochs never resurrect a deposed primary as last-good
        self._fenced_epoch = 0
        # per-connection I/O timeout: a black-holed replica (SYN accepted,
        # bytes never answered) must surface as an OSError and fail over
        # instead of hanging the caller forever. None = no bound (the
        # single-endpoint default: big LISTs may legitimately be slow).
        self.request_timeout_s = request_timeout_s
        if request_timeout_s is None and endpoints and len(endpoints) > 1:
            self.request_timeout_s = 5.0
        # failover accounting (the rolling-restart drill's p99 source)
        self.failover_total = 0
        self.failover_samples: list[float] = []
        self._watch_seq = 0
        self.token = token
        # client-go-style token bucket (client/flowcontrol.py); None = no
        # throttling, the in-process/test default
        self.rate_limiter = rate_limiter
        # TLS client side (kubeconfig's certificate-authority /
        # insecure-skip-tls-verify): ca_file pins the server cert; skip
        # verification only when explicitly asked
        self._ssl = None
        if tls:
            import ssl

            if ca_file:
                # full verification against the CA bundle INCLUDING the
                # hostname/IP-SAN check — trusting any cert the CA signed
                # regardless of host would let one leaked leaf cert
                # impersonate the apiserver
                self._ssl = ssl.create_default_context(cafile=ca_file)
            else:
                self._ssl = ssl.create_default_context()
                if insecure_skip_verify:
                    self._ssl.check_hostname = False
                    self._ssl.verify_mode = ssl.CERT_NONE
            if cert_file and key_file:
                # kubeconfig client-certificate/client-key: mTLS identity
                # (CN=user, O=groups via the server's X509Authenticator)
                self._ssl.load_cert_chain(cert_file, key_file)
        # content negotiation: "protobuf" (default when the codec is
        # available — the reference's hot-path default content type) or
        # "json"; KTPU_WIRE=json forces JSON fleet-wide
        import os as _os

        fmt = (wire_format or _os.environ.get("KTPU_WIRE", "protobuf"))
        self._pb = wire.available() and fmt == "protobuf"

    # ---- replica set ----

    @property
    def host(self) -> str:
        return self._endpoints[self._active][0]

    @property
    def port(self) -> int:
        return self._endpoints[self._active][1]

    @property
    def endpoints(self) -> list[tuple[str, int]]:
        return list(self._endpoints)

    def _advance_endpoint(self) -> None:
        """Step off a failed replica: jump to the last-known-good
        endpoint first (one jump per failure episode — it answered most
        recently, so it shaves the dead-endpoint walk out of failover
        p99), then round-robin the rest of the set. A fenced reply with a
        newer epoch clears `_last_good` before this runs (`_request`), so
        a deposed primary never gets the preferred probe."""
        lg = self._last_good
        self._last_good = None  # one preferred probe per episode
        if lg is not None and lg != self._active \
                and lg < len(self._endpoints):
            self._active = lg
            return
        self._active = (self._active + 1) % len(self._endpoints)

    # how long a write keeps chasing fenced replies before surfacing the
    # verdict — covers a promotion in flight (lease expiry + epoch mint);
    # drills shrink it along with the election timings
    fenced_grace_s = 5.0

    def _steer_to(self, endpoint: str) -> bool:
        """Point the active endpoint at an advertised "host:port" (the
        primary a fenced reply named), learning it if it isn't in the
        configured set. False when there is nothing to steer to — empty
        advertisement, unparseable, or the very endpoint that just
        answered (a stale advertisement must not pin us in place)."""
        host, _, port = endpoint.rpartition(":")
        if not host or not port.isdigit():
            return False
        target = (host, int(port))
        if target == self._endpoints[self._active]:
            return False
        if target not in self._endpoints:
            self._endpoints.append(target)
        self._active = self._endpoints.index(target)
        return True

    def _ready(self, host: str, port: int,
               timeout: float = 0.5) -> bool:
        """One short-deadline GET /readyz — False on refused/timeout/503.
        A draining replica fails this BEFORE its listener closes, so
        health-checking clients step around it without a single error."""
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout) as sock:
                if self._ssl is not None:
                    sock = self._ssl.wrap_socket(sock, server_hostname=host)
                sock.settimeout(timeout)
                sock.sendall(f"GET /readyz HTTP/1.1\r\nHost: {host}\r\n"
                             f"Connection: close\r\n\r\n".encode())
                data = b""
                while b"\r\n" not in data:
                    chunk = sock.recv(4096)
                    if not chunk:
                        break
                    data += chunk
            return parse_status_line(data.partition(b"\r\n")[0]) == 200
        except (OSError, ValueError):
            return False

    def probe_endpoints(self, timeout: float = 0.5) -> list[bool]:
        """/readyz verdict per configured endpoint, in order."""
        return [self._ready(h, p, timeout) for h, p in self._endpoints]

    def discover_endpoints(self) -> list[tuple[str, int]]:
        """Refresh the replica set from the well-known `default/kubernetes`
        Endpoints object every replica advertises into (the reference's
        master-count reconciler shape). Keeps the current set on any
        failure — discovery must never strand a working client."""
        try:
            ep = self.get("Endpoints", "kubernetes", "default")
            addrs = [(a.get("ip", ""), int(a.get("port", 0)))
                     for subset in ep.subsets
                     for a in subset.get("addresses", [])]
            addrs = [(h, p) for h, p in addrs if h and p]
        except Exception:
            return list(self._endpoints)
        if addrs:
            current = self._endpoints[self._active]
            lg = self._endpoints[self._last_good] \
                if self._last_good is not None \
                and self._last_good < len(self._endpoints) else None
            self._endpoints = addrs
            self._active = addrs.index(current) if current in addrs else 0
            self._last_good = addrs.index(lg) if lg in addrs else None
        return list(self._endpoints)

    def _auth_header(self) -> str:
        return (f"Authorization: Bearer {self.token}\r\n"
                if self.token else "")

    # overall connect deadline; within it, transient failures (a server
    # still binding its port after restart, a loaded box dropping SYNs,
    # kernel accept-queue overflow resets) retry instead of surfacing —
    # a fixed single-shot timeout made checkpoint-resume tests flake
    # whenever the CI box was busy at the moment of the one attempt
    connect_deadline_s = 30.0

    def _connect(self):
        import random as _random
        import time as _time

        deadline = _time.monotonic() + self.connect_deadline_s
        delay = 0.05
        fail_start = None
        failed_over = False
        while True:
            remaining = deadline - _time.monotonic()
            host, port = self._endpoints[self._active]
            try:
                timeout = max(1.0, remaining)
                if len(self._endpoints) > 1:
                    # replica set: a dead endpoint must fail FAST so the
                    # next one gets tried inside the caller's patience
                    timeout = min(timeout, 1.0)
                sock = socket.create_connection((host, port),
                                                timeout=timeout)
            except (ConnectionError, TimeoutError, OSError):
                if _time.monotonic() + delay >= deadline:
                    raise
                if fail_start is None:
                    fail_start = _time.monotonic()
                if len(self._endpoints) > 1:
                    # failover: step to the next replica immediately; the
                    # jittered backoff only ramps once the whole set has
                    # been walked (all-down ≈ the old single-host retry)
                    self._advance_endpoint()
                    failed_over = True
                    if self._active != 0:
                        continue
                # blocking HTTP core: runs on client threads (or inside
                # to_thread), never on the event loop
                _time.sleep(  # ktpu: allow[blocking-in-async]
                    delay * (0.5 + _random.random()))
                delay = min(1.0, 2 * delay)
                continue
            if self.request_timeout_s is not None:
                sock.settimeout(self.request_timeout_s)
            if fail_start is not None and failed_over:
                self.failover_total += 1
                self.failover_samples.append(
                    1e3 * (_time.monotonic() - fail_start))
            if self._ssl is not None:
                try:
                    return self._ssl.wrap_socket(sock, server_hostname=host)
                except Exception:
                    sock.close()
                    raise
            return sock

    # ---- blocking HTTP core (CRUD: small payloads on a trusted network) ----

    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str | None = None):
        if self.rate_limiter is not None:
            self.rate_limiter.accept()
        import time as _time

        # replica failover: a mid-stream transport failure (reset, torn
        # response, black-hole timeout) or a 503 from a draining replica
        # retries on the next endpoint. Safe for non-idempotent verbs
        # because the shared store absorbs duplicates — a replayed create
        # answers AlreadyExists, a replayed bind answers Conflict, both of
        # which every caller already handles (exactly-once is the STORE's
        # guarantee, not the transport's).
        attempts = 2 * len(self._endpoints) if len(self._endpoints) > 1 \
            else 1
        episode_start = None
        attempt = 0
        fenced_deadline = None
        while True:
            fenced = False
            try:
                status, decoded, resp_headers = self._request_once(
                    method, path, body, content_type)
                if status == 503 and attempt < attempts - 1 \
                        and len(self._endpoints) > 1:
                    raise ConnectionError(
                        decoded.get("message", "HTTP 503"))
                fenced = (status == 409
                          and decoded.get("reason") == "Fenced")
                if fenced:
                    # replication fencing (apiserver/replication.py): this
                    # endpoint is a standby or a deposed primary. A reply
                    # carrying a newer epoch also deposes the cached
                    # last-good endpoint — preferring it would hammer the
                    # deposed primary for a full backoff cycle — and names
                    # the current primary, so chase it directly.
                    details = decoded.get("details") or {}
                    epoch = int(details.get("epoch", 0) or 0)
                    if epoch >= self._fenced_epoch:
                        self._fenced_epoch = epoch
                        self._last_good = None
                    if len(self._endpoints) > 1:
                        if episode_start is None:
                            episode_start = _time.monotonic()
                        if fenced_deadline is None:
                            fenced_deadline = (_time.monotonic()
                                               + self.fenced_grace_s)
                        if _time.monotonic() < fenced_deadline:
                            if not self._steer_to(
                                    details.get("endpoint", "")):
                                # no primary advertised yet (promotion in
                                # flight): walk the set while the
                                # election settles
                                self._advance_endpoint()
                                _time.sleep(  # ktpu: allow[blocking-in-async]
                                    0.05)
                            continue
                    # single endpoint, or chase grace exhausted: surface
                    # the fenced verdict to the caller below
            except (ConnectionError, TimeoutError, OSError):
                attempt += 1
                if len(self._endpoints) <= 1 or attempt >= attempts:
                    raise
                if episode_start is None:
                    episode_start = _time.monotonic()
                self._advance_endpoint()
                continue
            if episode_start is not None and not fenced:
                # one failover episode = first failure -> next success,
                # however many endpoints it walked (the drill's p99)
                self.failover_total += 1
                self.failover_samples.append(
                    1e3 * (_time.monotonic() - episode_start))
            if not fenced:
                self._last_good = self._active
            break
        if status == 400 and self._pb and body is not None \
                and content_type is None:
            # codec-asymmetric fleet: a server without the codec can't
            # decode protobuf bodies (400). Downgrade this client to JSON
            # permanently and retry — negotiation degrades, nothing breaks
            self._pb = False
            log.warning("server cannot decode protobuf bodies; "
                        "downgrading client to JSON")
            status, decoded, resp_headers = self._request_once(
                method, path, body)
        try:
            return self._raise_for_status(status, decoded, resp_headers)
        except TooManyRequests as e:
            # server-side flow control: the Retry-After hint pauses this
            # client's own token bucket so every later call backs off too,
            # not just the caller that saw the 429
            hint = getattr(e, "retry_after", 0.0)
            if hint and self.rate_limiter is not None \
                    and hasattr(self.rate_limiter, "note_retry_after"):
                self.rate_limiter.note_retry_after(hint)
            raise

    def _request_once(self, method: str, path: str,
                      body: dict | None = None,
                      content_type: str | None = None):
        if content_type is not None:
            # caller-specified body type (the PATCH verb's three
            # +json patch types ride JSON regardless of negotiation)
            payload = json.dumps(body).encode() if body is not None else b""
            accept = (f"{wire.CONTENT_TYPE}, application/json"
                      if self._pb else "application/json")
        elif self._pb:
            payload = wire.encode_payload(body) if body is not None else b""
            content_type = wire.CONTENT_TYPE
            accept = f"{wire.CONTENT_TYPE}, application/json"
        else:
            payload = json.dumps(body).encode() if body is not None else b""
            content_type = accept = "application/json"
        # client tracing: the ROOT sampling decision is made here (head-
        # based); the traceparent header carries it server-side. Unsampled
        # spans cost two id generations and skip the ring entirely.
        with _tracing.TRACER.start_span(
                f"client.{method.lower()}", tid="client",
                attrs={"path": path}) as span:
            trace_header = (f"traceparent: "
                            f"{span.context.to_traceparent()}\r\n"
                            if span.sampled else "")
            with self._connect() as sock:
                sock.sendall(
                    f"{method} {path} HTTP/1.1\r\n"
                    f"Host: {self.host}\r\n"
                    f"{self._auth_header()}"
                    f"{trace_header}"
                    f"Content-Type: {content_type}\r\n"
                    f"Accept: {accept}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"Connection: close\r\n\r\n".encode() + payload)
                data = b""
                while True:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    data += chunk
        head, _, resp_body = data.partition(b"\r\n\r\n")
        try:
            status = int(head.split(None, 2)[1])
        except (IndexError, ValueError):
            # empty or non-HTTP reply (e.g. a plaintext request hitting a
            # TLS socket): a transport failure, not a protocol answer
            raise ConnectionError(
                "empty or non-HTTP reply from server") from None
        resp_headers: dict[str, str] = {}
        for line in head.split(b"\r\n")[1:]:
            hname, _, hval = line.decode("latin-1").partition(":")
            resp_headers[hname.strip().lower()] = hval.strip()
        if resp_body and wire.CONTENT_TYPE.encode() in head.lower():
            decoded = wire.decode_payload(resp_body)  # ValueError on corrupt
        else:
            decoded = json.loads(resp_body) if resp_body else {}
        return status, decoded, resp_headers

    @staticmethod
    def _raise_for_status(status: int, decoded: dict,
                          headers: dict[str, str] | None = None):
        if status == 404:
            raise NotFound(decoded.get("message", "not found"))
        if status in (401, 403):
            raise PermissionError(decoded.get("message", f"HTTP {status}"))
        if status == 422:
            raise ValidationError(decoded.get("message", "invalid object"))
        if status == 409:
            if decoded.get("reason") == "AlreadyExists":
                raise AlreadyExists(decoded.get("message", ""))
            if decoded.get("reason") == "Fenced":
                details = decoded.get("details") or {}
                raise FencedWrite(decoded.get("message", "write fenced"),
                                  epoch=int(details.get("epoch", 0) or 0),
                                  endpoint=details.get("endpoint", ""))
            raise Conflict(decoded.get("message", ""))
        if status == 410:
            raise Expired(decoded.get("message", ""))
        if status == 429:
            exc = TooManyRequests(decoded.get("message", ""))
            # machine-readable causes (Status.details.causes) ride the
            # exception so callers can distinguish a PDB denial from a
            # load shed without parsing prose
            exc.causes = tuple(
                c.get("reason", "") for c in
                (decoded.get("details") or {}).get("causes") or [])
            try:
                exc.retry_after = float(
                    (headers or {}).get("retry-after", 0))
            except ValueError:
                exc.retry_after = 0.0
            raise exc
        if status >= 400:
            raise ValueError(f"HTTP {status}: {decoded.get('message')}")
        return decoded

    @staticmethod
    def _path(kind: str, namespace: str | None = None,
              name: str | None = None) -> str:
        plural = PLURAL_OF[kind]
        path = "/api/v1"
        if namespace is not None:
            path += f"/namespaces/{namespace}"
        path += f"/{plural}"
        if name is not None:
            path += f"/{name}"
        return path

    # ---- ObjectStore surface ----

    @property
    def resource_version(self) -> int:
        decoded = self._request("GET", self._path("Pod"))
        return int(decoded["metadata"]["resourceVersion"])

    def list_with_version(self, kind: str) -> tuple[list[Any], int]:
        """One GET: the items and the list's own metadata.resourceVersion —
        the atomic snapshot Informer relists from."""
        decoded = self._request("GET", self._path(kind))
        items = [decode_object(kind, d) for d in decoded["items"]]
        return items, int(decoded["metadata"]["resourceVersion"])

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        return decode_object(kind, self._request(
            "GET", self._path(kind, namespace, name)))

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None, *,
             copy_objects: bool = True) -> list[Any]:
        decoded = self._request("GET", self._path(kind, namespace))
        out = [decode_object(kind, d) for d in decoded["items"]]
        if label_selector:
            out = [o for o in out
                   if all(o.metadata.labels.get(k) == v
                          for k, v in label_selector.items())]
        return out

    def create(self, obj: Any, *, copy: bool = True) -> Any:
        return decode_object(obj.kind, self._request(
            "POST", self._path(obj.kind, obj.metadata.namespace),
            encode_object(obj)))

    def update(self, obj: Any, *, check_version: bool = True) -> Any:
        body = encode_object(obj)
        if not check_version:
            body.setdefault("metadata", {}).pop("resourceVersion", None)
        return decode_object(obj.kind, self._request(
            "PUT", self._path(obj.kind, obj.metadata.namespace,
                              obj.metadata.name), body))

    def guaranteed_update(self, kind: str, name: str, namespace: str,
                          mutate, retries: int = 16) -> Any:
        for _ in range(retries):
            obj = self.get(kind, name, namespace)
            mutate(obj)
            try:
                return self.update(obj)
            except Conflict:
                continue
        raise Conflict(f"{kind} {namespace}/{name}: too many CAS retries")

    def patch(self, kind: str, name: str, namespace: str, patch,
              content_type: str) -> Any:
        """PATCH with one of the three patch content types
        (strategicpatch.STRATEGIC / MERGE / JSONPATCH)."""
        return decode_object(kind, self._request(
            "PATCH", self._path(kind, namespace, name), patch,
            content_type=content_type))

    def delete(self, kind: str, name: str, namespace: str = "default") -> Any:
        return decode_object(kind, self._request(
            "DELETE", self._path(kind, namespace, name)))

    def bind(self, binding: Binding) -> Any:
        return self._request(
            "POST",
            self._path("Pod", binding.namespace, binding.pod_name)
            + "/binding",
            {"target": {"kind": "Node", "name": binding.target_node},
             "metadata": {"name": binding.pod_name}})

    def raw(self, method: str, path: str) -> tuple[int, str]:
        """Non-JSON request (node-proxy surfaces: logs, exec). Returns
        (status, body-text) with chunked transfer decoding."""
        with self._connect() as sock:
            sock.sendall(
                f"{method} {path} HTTP/1.1\r\n"
                f"Host: {self.host}\r\n"
                f"{self._auth_header()}"
                f"Content-Length: 0\r\n"
                f"Connection: close\r\n\r\n".encode())
            data = b""
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                data += chunk
        head, _, body = data.partition(b"\r\n\r\n")
        try:
            status = parse_status_line(head)
        except ValueError:
            raise ConnectionError(
                "empty or non-HTTP reply from server") from None
        if b"transfer-encoding: chunked" in head.lower():
            out, rest = b"", body
            while rest:
                size_line, _, rest = rest.partition(b"\r\n")
                try:
                    size = int(size_line, 16)
                except ValueError:
                    break
                if size == 0:
                    break
                out += rest[:size]
                rest = rest[size + 2:]
            body = out
        return status, body.decode(errors="replace")

    def delete_collection(self, kind: str, namespace: str | None = None,
                          label_selector: dict[str, str] | None = None
                          ) -> int:
        from urllib.parse import quote

        path = self._path(kind, namespace)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            path += f"?labelSelector={quote(sel, safe='')}"
        decoded = self._request("DELETE", path)
        return int((decoded.get("details") or {}).get("deleted", 0))

    def evict(self, name: str, namespace: str = "default") -> bool:
        """pods/eviction subresource. False = the pod's disruption budget
        refused (HTTP 429 with a DisruptionBudget cause) — retry later,
        like kubectl drain. A load-shed 429 (max-in-flight, no such cause)
        re-raises: that is server pressure, not a PDB answer."""
        try:
            self._request(
                "POST", self._path("Pod", namespace, name) + "/eviction",
                {"apiVersion": "policy/v1beta1", "kind": "Eviction",
                 "metadata": {"name": name, "namespace": namespace}})
        except TooManyRequests as e:
            if "DisruptionBudget" in getattr(e, "causes", ()) \
                    or "disruption budget" in str(e):
                return False
            raise
        return True

    def watch(self, kind: str | None = None,
              since: int | None = None) -> RemoteWatchStream:
        """Open the chunked watch stream. Must run inside the event loop the
        stream will be consumed on; raises Expired on 410."""
        plural = PLURAL_OF[kind]
        query = "watch=1" + (f"&resourceVersion={since}"
                             if since is not None else "")
        loop = asyncio.get_running_loop()
        fut = loop.create_task(self._open_watch(plural, query))
        # Informer calls watch() synchronously from a coroutine: expose the
        # stream as a lazily-opened wrapper
        return _LazyWatch(fut)

    async def _open_watch(self, plural: str, query: str):
        if self.rate_limiter is not None:
            # async acquire: the sync accept() would park the event loop
            # this watch (and every other stream) runs on
            await self.rate_limiter.accept_async()
        n = len(self._endpoints)
        if n > 1:
            # spread watches round-robin across the replica set (each
            # replica's fan-out cache carries its share), walking the
            # whole set before giving up so one dead replica never fails
            # a watch open
            start = self._watch_seq % n
            self._watch_seq += 1
            order = [(start + i) % n for i in range(n)]
            lg = self._last_good
            if lg is not None and lg < n and lg != order[0]:
                # keep the round-robin start first (load spreading), but
                # probe the last-known-good endpoint right after it
                # instead of walking the set in rotation order
                order.remove(lg)
                order.insert(1, lg)
        else:
            order = [self._active]
        last_exc: Exception | None = None
        for idx in order:
            host, port = self._endpoints[idx]
            try:
                stream = await asyncio.wait_for(
                    self._open_watch_at(host, port, plural, query),
                    timeout=5.0 if n > 1 else None)
            except (Expired, ValueError):
                raise  # protocol answers: same on every replica
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                last_exc = e
                if n > 1:
                    self.failover_total += 1
                continue
            self._last_good = idx
            return stream
        raise ConnectionError(
            f"no replica would serve the watch "
            f"({len(order)} endpoint(s) tried)") from last_exc

    async def _open_watch_at(self, host: str, port: int,
                             plural: str, query: str):
        accept = (f"Accept: {wire.CONTENT_TYPE}, application/json\r\n"
                  if self._pb else "")
        reader, writer = await asyncio.open_connection(
            host, port, ssl=self._ssl,
            server_hostname=host if self._ssl is not None else None)
        try:
            writer.write(f"GET /api/v1/{plural}?{query} HTTP/1.1\r\n"
                         f"Host: {host}\r\n{self._auth_header()}{accept}"
                         f"Connection: keep-alive\r\n\r\n"
                         .encode())
            await writer.drain()
            status_line = await reader.readline()
            try:
                status = int(status_line.split(None, 2)[1])
            except (IndexError, ValueError):
                raise ConnectionError(
                    "empty or non-HTTP watch handshake") from None
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                headers[name.strip().lower()] = value.strip()
            if status == 410:
                length = int(headers.get("content-length", 0))
                body = await reader.readexactly(length) if length else b"{}"
                raise Expired(json.loads(body).get("message", "410 Gone"))
            if status == 503:
                # draining replica: transport-level answer, try the next
                raise ConnectionError("replica draining (503)")
            if status != 200:
                raise ValueError(f"watch failed: HTTP {status}")
        except BaseException:
            writer.close()
            raise
        binary = headers.get("content-type", "").startswith(
            wire.CONTENT_TYPE)
        return RemoteWatchStream(reader, writer, binary=binary)

    def watch_resilient(self, kind: str | None = None,
                        since: int | None = None) -> "FailoverWatch":
        """A watch that survives replica death: tracks the last delivered
        resourceVersion and transparently reopens on another endpoint with
        `since=last_rv` when the stream dies in transport or the replica
        drains — the consumer sees one gapless, duplicate-free event
        sequence. An honest 410 (resume point aged out of every replica's
        ring) still raises Expired: there is NO silent gap path."""
        return FailoverWatch(self, kind, since)


class _LazyWatch:
    """Defers the async watch handshake to the first next() call while
    keeping the Informer's synchronous `store.watch(...)` call shape. A 410
    at handshake time surfaces as ConnectionError->relist (equivalent
    recovery path to the in-process store's synchronous Expired)."""

    def __init__(self, open_task: asyncio.Task):
        self._open = open_task
        self._stream: RemoteWatchStream | None = None
        self._stopped = False

    async def _ensure(self) -> RemoteWatchStream:
        if self._stream is None:
            self._stream = await self._open
        return self._stream

    async def next(self, timeout: float | None = None) -> WatchEvent | None:
        stream = await self._ensure()
        if self._stopped:
            return None
        return await stream.next(timeout)

    def stop(self) -> None:
        self._stopped = True
        if self._stream is not None:
            self._stream.stop()
        elif self._open.done() and not self._open.cancelled() \
                and self._open.exception() is None:
            self._open.result().stop()
        else:
            self._open.cancel()

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev
